"""Per-request sampling contract for the serving decode loop (ISSUE 13).

The decode tier was greedy-only: every caller got ``argmax`` and the
oracle parity suite pinned it.  Real traffic wants temperature /
top-k / top-p sampling, stop sequences, logit bias, and a per-request
generation cap — each a distinct serving scenario (serve_bench
``--sampling``) — WITHOUT forking the step function per request.  So
the contract is:

- :class:`SamplingParams` is an immutable per-request value object
  carried on ``DecodeRequest.sampling`` (and threaded from
  ``Engine.submit(sampling=)`` in pass-through mode).  ``temperature
  == 0`` (the default) is EXACT greedy — bit-identical to the
  pre-ISSUE-13 loop and to ``full_decode``, which is also the
  token-identity condition the speculative verify walk preserves for
  greedy rows.
- :func:`sample_rows` is the ONE jitted sampling epilogue: the whole
  batch's next-token choice in a single fused call — per-row
  temperature scaling, top-k / top-p filtering, and a Gumbel-max draw
  keyed by (per-request seed, per-sequence token index) — the RNG
  stream never depends on batch composition, so an identical replay
  regenerates identical tokens (fp32 attention reduction order can
  still perturb a near-tied draw between DIFFERENT step shapes; the
  keys themselves cannot).  Greedy rows short-circuit host-side (the
  loop never pays a device round trip for pure-greedy batches,
  preserving the oracle's host-argmax arithmetic exactly).
- :func:`spec_sample_rows` extends the same contract to DRAFTED
  non-greedy rows (ISSUE 16): acceptance-rejection over the verify
  step's [B, Sq, V] logits — draft token d accepts with probability
  ``min(1, p_target(d) / p_draft(d))``, which for the prompt-lookup
  drafter's point-mass proposal is ``p_target(d)`` itself, and a
  rejection resamples the residual ``max(0, p_target - p_draft)``
  renormalized (p with d's mass zeroed).  Both arms marginalize to
  ``p_target`` token by token, so speculative sampled output is
  DISTRIBUTION-IDENTICAL to the plain epilogue (the tests hold a
  TV-distance bound over replayed draws), while per-row accepted
  counts come back from the one fused call — no per-sequence host
  sync.  The replay contract survives: the g-th generated token still
  owns ``fold_in(PRNGKey(seed), g)``; acceptance uniforms salt it
  with 1, residual Gumbels with 2, and bonus/no-draft rows use the
  UNSALTED Gumbel — byte-identical to ``sample_rows``'s draw, so a
  sequence that never drafts keeps its pre-speculation stream.
  Rolled-back rows never consume an index: g advances only with
  emitted tokens.
- Logit bias applies BEFORE everything (greedy included): a biased
  greedy request is still deterministic, so its argmax surface is just
  shifted — ``apply_bias`` is the shared host helper.
- Stop sequences are a host-side suffix check (:func:`stop_hit`)
  applied after EVERY emitted token — including tokens emitted from
  inside an accepted draft block, the same contract as EOS.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = ["SamplingParams", "sample_rows", "spec_sample_rows",
           "apply_bias", "stop_hit"]


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Immutable per-request sampling knobs.

    temperature: 0.0 (default) = EXACT greedy (argmax; deterministic —
        verified by the byte-identical longest-prefix walk); > 0
        samples from the scaled distribution (verified by the exact
        accept/resample epilogue — speculation stays ON either way).
    top_k: keep only the k highest-logit tokens before sampling
        (0 = off).  Ignored for greedy rows (argmax already is top-1).
    top_p: nucleus sampling — keep the smallest prefix of the
        probability-sorted vocab whose cumulative mass reaches p
        (1.0 = off; the top-1 token is always kept).
    stop: stop token sequences (any iterable of token iterables) — a
        sequence retires the moment its generated tokens END with one
        of them; the stop tokens stay in the output (the EOS
        convention).
    logit_bias: {token_id: additive bias} applied to every step's
        logits before argmax/sampling — greedy rows included.
    max_new: per-request generation cap; the effective cap is
        ``min(DecodeRequest.max_new_tokens, max_new)`` (None: the
        request's own cap stands).
    seed: per-request RNG stream for the Gumbel draw; the g-th
        generated token folds in g, so a retried request replays
        identically and batch composition cannot perturb it.
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop: Tuple[Tuple[int, ...], ...] = ()
    logit_bias: Optional[Tuple[Tuple[int, float], ...]] = None
    max_new: Optional[int] = None
    seed: int = 0

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p}")
        if self.max_new is not None and self.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {self.max_new}")
        if not 0 <= int(self.seed) < 2 ** 32:
            # the RNG key is a uint32: a negative seed would crash the
            # epilogue MID-BATCH (killing batch-mates) instead of
            # failing this one request's construction
            raise ValueError(
                f"seed must be a uint32 (0 <= seed < 2**32), got "
                f"{self.seed}")
        # normalize the container fields so the frozen instance is
        # hashable and order-stable (dicts/lists accepted at call sites)
        object.__setattr__(self, "stop", tuple(
            tuple(int(t) for t in s) for s in (self.stop or ())))
        if any(not s for s in self.stop):
            raise ValueError("stop sequences must be non-empty")
        bias = self.logit_bias
        if bias is not None:
            if isinstance(bias, dict):
                bias = bias.items()
            norm = tuple(sorted((int(t), float(b)) for t, b in bias))
            if norm and norm[0][0] < 0:
                raise ValueError(
                    f"logit_bias token ids must be >= 0, got "
                    f"{norm[0][0]}")
            object.__setattr__(self, "logit_bias", norm or None)

    def max_bias_token(self) -> int:
        """Largest biased token id (-1 when no bias) — the decode loop
        validates it against the model's vocab at admission, so an
        out-of-range id fails THAT request up front instead of
        crashing the shared batch mid-step."""
        return self.logit_bias[-1][0] if self.logit_bias else -1

    @property
    def greedy(self) -> bool:
        """True when this request's choice is deterministic argmax —
        verified by the longest-prefix walk; non-greedy rows verify
        through the exact accept/resample epilogue instead."""
        return self.temperature == 0.0


def apply_bias(row: np.ndarray,
               params: Optional[SamplingParams]) -> np.ndarray:
    """Host-side logit bias for one [V] row (a copy when bias applies;
    the input row otherwise) — shared by the greedy argmax path and the
    draft-acceptance walk so both see the same decision surface."""
    if params is None or not params.logit_bias:
        return row
    out = np.asarray(row, np.float32).copy()
    for tok, b in params.logit_bias:
        out[tok] += b
    return out


def stop_hit(tokens: Sequence[int],
             params: Optional[SamplingParams]) -> bool:
    """True when `tokens` (the generated tokens so far) ends with one of
    the request's stop sequences."""
    if params is None or not params.stop:
        return False
    for s in params.stop:
        n = len(s)
        if n <= len(tokens) and tuple(tokens[-n:]) == s:
            return True
    return False


def _filter_scaled(logits, temps, top_ks, top_ps, vocab: int):
    """The shared filter pipeline (traced under jit): per-row
    temperature scaling, top-k, top-p over [R, V] rows -> filtered
    logits with excluded tokens at -inf.  Both the plain epilogue
    (``_sample_jit``) and the speculative accept/resample epilogue
    (``_spec_jit``) trace THIS function, so the two samplers share one
    decision surface by construction — the distributional-parity tests
    lean on that."""
    import jax
    import jax.numpy as jnp

    x = logits / jnp.maximum(temps, 1e-6)[:, None]
    # top-k: mask everything below the k-th largest logit (k=0/V
    # disables); ties at the threshold stay in, which only widens
    # the kept set — standard top-k semantics
    sorted_desc = jnp.sort(x, axis=-1)[:, ::-1]
    k = jnp.clip(jnp.where(top_ks > 0, top_ks, vocab), 1, vocab)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None],
                              axis=-1)  # [R, 1]
    x = jnp.where(x >= kth, x, -jnp.inf)
    # top-p over the filtered distribution: keep every token whose
    # PRECEDING cumulative mass is < p (the smallest prefix
    # reaching p; the top-1 always stays because its preceding
    # mass is 0).  Comparing the preceding mass — not the
    # inclusive cumsum — keeps top_p=1.0 a true no-op even when
    # the fp32 cumsum tops out at 0.9999999 and never reaches 1
    probs = jax.nn.softmax(x, axis=-1)
    p_desc = jnp.sort(probs, axis=-1)[:, ::-1]
    preceding = jnp.cumsum(p_desc, axis=-1) - p_desc
    kept = preceding < top_ps[:, None]
    p_min = jnp.min(jnp.where(kept, p_desc, jnp.inf), axis=-1,
                    keepdims=True)
    return jnp.where(probs >= p_min, x, -jnp.inf)


@functools.lru_cache(maxsize=32)
def _sample_jit(vocab: int):
    """The jitted epilogue body, one compile per vocab width: [B, V]
    biased logits + per-row (temperature, top_k, top_p, key-fold data)
    -> [B] sampled token ids.  All three filters fuse into one call."""
    import jax
    import jax.numpy as jnp

    def body(logits, temps, top_ks, top_ps, seeds, steps):
        x = _filter_scaled(logits, temps, top_ks, top_ps, vocab)
        # Gumbel-max draw keyed (request seed, per-sequence token
        # index): batch composition cannot perturb a request's stream
        keys = jax.vmap(lambda s, g: jax.random.fold_in(
            jax.random.PRNGKey(s), g))(seeds, steps)
        gumbel = jax.vmap(
            lambda kk: jax.random.gumbel(kk, (vocab,)))(keys)
        return jnp.argmax(x + gumbel, axis=-1).astype(jnp.int32)

    return jax.jit(body)


@functools.lru_cache(maxsize=32)
def _spec_jit(vocab: int, sq: int):
    """The jitted speculative accept/resample epilogue, one compile per
    (vocab, padded block width): [B, Sq, V] biased verify logits + the
    per-row sampling knobs + the draft block -> (accepted counts [B],
    chosen tokens [B, Sq]) in ONE fused call — the per-row accepted
    count is computed device-side (sum of the accept cumprod), never by
    a per-sequence host walk.

    Exactness (acceptance-rejection under a DETERMINISTIC proposal):
    the drafter proposes a point mass at d, so ``min(1, p(d)/q(d))``
    collapses to accepting d with probability p(d) — the
    filtered/temperature target probability itself — and the residual
    ``max(0, p - q)`` renormalized is exactly p with d's mass zeroed,
    drawn here as Gumbel-argmax over the filtered logits with d masked
    to -inf.  Both arms marginalize to p:
    ``P(emit s) = p(d)·[s=d] + (1-p(d)) · p(s)·[s≠d]/(1-p(d)) = p(s)``.

    RNG replay schedule: the g-th generated token owns
    ``key_g = fold_in(PRNGKey(seed), g)`` — the plain epilogue's key.
    Accept uniforms draw from ``fold_in(key_g, 1)``, residual Gumbels
    from ``fold_in(key_g, 2)``, and the bonus row (every draft landed)
    uses key_g's unsalted Gumbel — byte-identical to ``sample_rows``.
    Row t's token owns index ``steps + t``; rejected rows never consume
    an index (the loop advances g only with emitted tokens)."""
    import jax
    import jax.numpy as jnp

    def body(logits, temps, top_ks, top_ps, seeds, steps, drafts,
             q_lens):
        B = logits.shape[0]
        rep = lambda a: jnp.repeat(a, sq)
        x = _filter_scaled(
            logits.reshape(B * sq, vocab), rep(temps), rep(top_ks),
            rep(top_ps), vocab).reshape(B, sq, vocab)
        probs = jax.nn.softmax(x, axis=-1)
        # per-(row, position) key: the g-th generated token's key_g
        g = steps[:, None] + jnp.arange(sq, dtype=jnp.uint32)[None, :]
        key_g = jax.vmap(jax.vmap(
            lambda s, gg: jax.random.fold_in(jax.random.PRNGKey(s),
                                             gg)))(
            jnp.broadcast_to(seeds[:, None], g.shape), g)
        u = jax.vmap(jax.vmap(lambda kk: jax.random.uniform(
            jax.random.fold_in(kk, 1), ())))(key_g)           # [B, Sq]
        g_resid = jax.vmap(jax.vmap(lambda kk: jax.random.gumbel(
            jax.random.fold_in(kk, 2), (vocab,))))(key_g)     # [B,Sq,V]
        g_plain = jax.vmap(jax.vmap(lambda kk: jax.random.gumbel(
            kk, (vocab,))))(key_g)                            # [B,Sq,V]
        # accept draft d_t iff u_t < p(d_t); rows past the draft depth
        # can never accept, and the cumprod keeps acceptance prefix-
        # contiguous (the first rejection ends the row's walk)
        p_draft = jnp.take_along_axis(
            probs, drafts[..., None], axis=-1)[..., 0]        # [B, Sq]
        t_iota = jnp.arange(sq)[None, :]
        has_draft = t_iota < (q_lens[:, None] - 1)
        accept = (u < p_draft) & has_draft
        acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                      axis=1)                                 # [B]
        # residual: the draft's mass zeroed, renormalized (= masked
        # Gumbel-argmax); bonus (last fed row): the plain draw
        v_iota = jnp.arange(vocab)[None, None, :]
        x_masked = jnp.where(v_iota == drafts[..., None], -jnp.inf, x)
        resid_tok = jnp.argmax(x_masked + g_resid, axis=-1)
        plain_tok = jnp.argmax(x + g_plain, axis=-1)
        is_bonus = t_iota == (q_lens[:, None] - 1)
        tokens = jnp.where(
            t_iota < acc[:, None], drafts,
            jnp.where(is_bonus, plain_tok, resid_tok))
        return acc.astype(jnp.int32), tokens.astype(jnp.int32)

    return jax.jit(body)


def sample_rows(logits: np.ndarray, params: Sequence[SamplingParams],
                steps: Sequence[int]) -> np.ndarray:
    """The ONE jitted sampling epilogue: sample a next token for every
    row of `logits` [B, V] under its request's (non-greedy)
    SamplingParams; ``steps[i]`` is row i's per-sequence generated-token
    index (the RNG fold key).  Logit bias must already be applied
    (``apply_bias`` — the loop biases rows before both the greedy and
    sampled arms).  Greedy rows do NOT belong here — the loop resolves
    them host-side so the oracle argmax arithmetic is untouched."""
    logits = np.ascontiguousarray(np.asarray(logits, np.float32))
    if logits.ndim != 2:
        raise ValueError(f"sample_rows wants [B, V] rows, got "
                         f"{logits.shape}")
    B, V = logits.shape
    if len(params) != B or len(steps) != B:
        raise ValueError("params/steps must align with the logit rows")
    temps = np.asarray([p.temperature for p in params], np.float32)
    if (temps <= 0).any():
        raise ValueError(
            "greedy rows (temperature 0) must take the host argmax "
            "path, not the sampling epilogue")
    top_ks = np.asarray([p.top_k for p in params], np.int32)
    top_ps = np.asarray([p.top_p for p in params], np.float32)
    seeds = np.asarray([p.seed for p in params], np.uint32)
    steps = np.asarray(steps, np.uint32)
    return np.asarray(_sample_jit(V)(
        logits, temps, top_ks, top_ps, seeds, steps))


def spec_sample_rows(
        logits: np.ndarray, params: Sequence[SamplingParams],
        steps: Sequence[int], drafts: Sequence[Sequence[int]],
) -> Tuple[np.ndarray, np.ndarray]:
    """The speculative counterpart of :func:`sample_rows`: decide every
    drafted non-greedy row's accept/resample outcome in ONE jitted
    call.  ``logits`` is the verify step's [B, Sq, V] (bias already
    applied per [Sq, V] slice); ``drafts[i]`` holds row i's proposed
    tokens (its block minus the committed head — may be empty, in
    which case row i reduces exactly to ``sample_rows`` at row 0);
    ``steps[i]`` is the generated-token index of row i's FIRST emitted
    token.  Returns ``(accepted [B] int32, tokens [B, Sq] int32)``:
    position t of row i holds the accepted draft for ``t <
    accepted[i]``, the residual resample at ``t == accepted[i]`` (or
    the bonus draw when every draft landed) — entries past each row's
    walk are garbage the caller must ignore."""
    logits = np.ascontiguousarray(np.asarray(logits, np.float32))
    if logits.ndim != 3:
        raise ValueError(f"spec_sample_rows wants [B, Sq, V] verify "
                         f"logits, got {logits.shape}")
    B, Sq, V = logits.shape
    if len(params) != B or len(steps) != B or len(drafts) != B:
        raise ValueError(
            "params/steps/drafts must align with the logit rows")
    temps = np.asarray([p.temperature for p in params], np.float32)
    if (temps <= 0).any():
        raise ValueError(
            "greedy rows (temperature 0) must take the host "
            "longest-prefix walk, not the accept/resample epilogue")
    draft_arr = np.zeros((B, Sq), np.int32)
    q_lens = np.empty(B, np.int32)
    for i, d in enumerate(drafts):
        d = [int(t) for t in d]
        if len(d) >= Sq:
            raise ValueError(
                f"row {i} proposes {len(d)} drafts but the verify "
                f"width holds at most {Sq - 1} (1 committed + drafts)")
        draft_arr[i, :len(d)] = d
        q_lens[i] = len(d) + 1
    top_ks = np.asarray([p.top_k for p in params], np.int32)
    top_ps = np.asarray([p.top_p for p in params], np.float32)
    seeds = np.asarray([p.seed for p in params], np.uint32)
    steps = np.asarray(steps, np.uint32)
    acc, toks = _spec_jit(V, Sq)(
        logits, temps, top_ks, top_ps, seeds, steps, draft_arr, q_lens)
    return np.asarray(acc), np.asarray(toks)
