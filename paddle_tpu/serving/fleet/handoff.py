"""The prefill→decode handoff contract.

A :class:`Handoff` is everything a decode replica needs to continue a
sequence another replica prefilled: the original request, the first
generated token (chosen by the prefill side against its own logits —
greedy argmax, biased argmax, or the seeded sampling epilogue, so the
choice is exactly what a monolithic loop would have made), the logits
row behind it, and the sequence's KV pages staged to host buffers
(:class:`~paddle_tpu.serving.kvcache.SeqExport` — numpy, so the same
payload crosses a process boundary unchanged).

Prefix-cache composition: before exporting, the handoff broker asks
the DESTINATION replica to reserve the longest prefix of the prompt
its own cache already holds (:class:`PrefixReservation` — the matched
FULL pages, refcount-pinned so eviction cannot race the transfer), and
the export then ships only the unshared tail.  At admission the
destination re-attaches the reserved pages read-only and imports the
tail in one atomic claim — the imported footprint is charged exactly
like a locally-prefilled sequence's.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional

import numpy as np

from ..adapters import AdapterMismatchError
from ..generate import DecodeRequest
from ..kvcache import SeqExport

__all__ = ["Handoff", "HandoffDropError", "PrefixReservation",
           "RidReservation"]


class HandoffDropError(RuntimeError):
    """The handoff payload was lost in transit (chaos:
    FAULT_SERVE_HANDOFF_DROP) — the fleet requeues the request for a
    fresh prefill instead of losing it."""


@dataclasses.dataclass
class PrefixReservation:
    """Matched FULL prefix pages on the DESTINATION pool, refcount-
    pinned for the duration of the transfer so LRU eviction cannot
    invalidate them between the reserve and the import.  Registered as
    an external owner on the destination pool (DecodeReplica keeps the
    registry), so a mid-transfer ``check_invariants`` audit counts the
    holds as legitimate."""

    keys: List[str]
    pages: List[int]
    tokens: int                 # page-aligned prompt tokens covered
    released: bool = False
    # id(self) -> self in the owning DecodeReplica's registry (a dict,
    # not a set: dataclass equality must not conflate two reservations
    # over the same pages)
    _registry: Optional[dict] = dataclasses.field(
        default=None, repr=False, compare=False)

    def release(self, pool) -> int:
        """Drop the reservation holds (idempotent).  Called either by
        :meth:`Handoff.admit` once the pages joined the sequence's
        table, or by the failover path when the transfer died."""
        if self.released:
            return 0
        self.released = True
        if self._registry is not None:
            self._registry.pop(id(self), None)
            self._registry = None
        return pool.release_pages(self.pages)


class RidReservation:
    """Picklable stand-in for a `PrefixReservation` pinned in another
    PROCESS (the process fleet, serving/fleet/proc.py): carries only
    the owner-side registry id and the token count the export was
    planned against, so ``res.tokens`` drives ``skip_tokens`` on the
    prefill side without the pages ever leaving the owner.  `release`
    here is a local no-op — the real pages are unwound by the
    ``release_prefix`` verb against the owner or consumed when the
    handoff lands there and the owner's service swaps the real
    reservation back in.  Lives HERE (not in proc.py) because the
    replica entrypoint runs proc.py as ``__main__``: a stub minted
    there would pickle as ``__main__.RidReservation`` and fail to
    resolve on the broker."""

    def __init__(self, rid: str, tokens: int):
        self.rid = rid
        self.tokens = int(tokens)
        self.released = False

    def release(self, pool) -> None:  # noqa: ARG002 — seam parity
        self.released = True


class Handoff:
    """One prefilled sequence in flight between replicas."""

    def __init__(self, request: DecodeRequest, first_token: int,
                 first_logits: np.ndarray, payload: SeqExport,
                 reservation: Optional[PrefixReservation] = None,
                 src: Optional[str] = None, dest: Optional[str] = None):
        self.request = request
        self.first_token = int(first_token)
        self.first_logits = first_logits
        self.payload = payload
        self.reservation = reservation
        self.src = src
        self.dest = dest
        self.first_token_at = time.perf_counter()
        self.admitted = False

    @property
    def matched_tokens(self) -> int:
        """Prefix tokens the destination re-attaches from its own
        cache (== payload.skip_tokens) — the decode loop's admission
        reads this for its prefix-aware footprint charge."""
        res = self.reservation
        return res.tokens if res is not None else 0

    def nbytes(self) -> int:
        return self.payload.nbytes()

    def reroutable(self) -> bool:
        """A payload that skipped nothing can go to ANY decode replica;
        one exported against a reservation is missing its prefix
        content and only fits the replica that reserved it — failover
        must re-prefill instead."""
        return self.payload.skip_tokens == 0

    def admit(self, pool, prefix_cache, seq_id: int) -> None:
        """Materialize the sequence on the destination: re-attach the
        reserved prefix read-only (through the cache, so quarantine
        invalidation knows the chain), import the shipped tail in one
        atomic claim, then drop the reservation's transfer holds.

        The payload's ``adapter_id`` stamp must match the request's
        (ISSUE 19) — a mixed-up broker or a stale requeue must never
        decode one tenant's K/V under another tenant's weights; the
        typed reject sends the request back for a fresh prefill."""
        payload_aid = getattr(self.payload, "adapter_id", None)
        request_aid = getattr(self.request, "adapter_id", None)
        if payload_aid != request_aid:
            raise AdapterMismatchError(
                f"handoff payload for seq {self.payload.seq_id} was "
                f"prefilled under adapter {payload_aid!r} but the "
                f"request wants {request_aid!r} — refusing to admit")
        res = self.reservation
        if res is not None and res.tokens:
            if prefix_cache is None:
                raise RuntimeError(
                    "handoff carries a prefix reservation but the "
                    "destination loop has no prefix cache")
            from ..prefixcache import PrefixMatch

            prefix_cache.attach(seq_id, PrefixMatch(
                keys=list(res.keys), pages=list(res.pages),
                tokens=res.tokens))
        pool.import_seq(self.payload, seq_id)
        if res is not None:
            res.release(pool)
        self.admitted = True

    def release(self, pool) -> None:
        """Failover cleanup: drop the reservation holds of a handoff
        that will never be admitted on this pool."""
        if self.reservation is not None and not self.admitted:
            self.reservation.release(pool)
