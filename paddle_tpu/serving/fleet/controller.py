"""Elastic fleet control: autoscaling on heartbeat signals + rolling
weight upgrades over the zero-loss drain handoff.

The controller is deliberately dumb-and-deterministic: each ``step()``
reads one signal snapshot per replica class — queue depth, shed count,
health state, lease liveness — and applies a threshold policy with
sustain counters and a cooldown.  Signals come from the heartbeat
PAYLOADS the replicas already publish on the elastic master's liveness
plane (``ReplicaDirectory.status()`` — works identically over
``RemoteMaster``, so the control plane is cross-process even while the
data plane stays in-process threads), falling back to direct replica
reads when no directory is wired.

Decisions:

- **scale_up** — sustained queue growth (mean queued items per live
  replica >= ``queue_high`` for ``sustain`` consecutive steps) or any
  shedding since the last step, while below ``max_replicas``.
- **scale_down** — sustained idleness (zero queued work for
  ``idle_sustain`` steps) while above ``min_replicas``; the victim is
  drained through the zero-loss handoff (queued + in-flight work
  completes there) before removal.
- **replica_dead** — a lease-expired or dead replica is quarantined
  (routing stops, lease deregistered — no ghost leases) and replaced
  when the class would drop below ``min_replicas``.

``rolling_upgrade(new_params)`` walks every replica: drain (zero lost
or duplicated requests — traffic keeps flowing to the others), swap
weights (prefix caches invalidated, pool asserted empty), rejoin.
Every decision lands in the flight recorder
(scale_up/scale_down/upgrade/replica_dead events) and on the
``paddle_tpu_serving_fleet_events`` counter.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Sequence

from ... import flags as _flags
from ...observability import flight as _flight
from .. import metrics as _smetrics
from .fleet import Fleet

_log = logging.getLogger("paddle_tpu.serving.fleet")

__all__ = ["AutoscalePolicy", "FleetController"]

_ROLES = ("prefill", "decode")


@dataclasses.dataclass
class AutoscalePolicy:
    """Threshold policy — all counts are per controller ``step()``."""

    queue_high: int = 4      # mean queued items/replica that = pressure
    sustain: int = 2         # pressured steps before scale-up
    idle_sustain: int = 3    # idle steps before scale-down
    cooldown: int = 1        # steps to hold off after any scale action


class FleetController:
    """Scales a :class:`Fleet`'s replica classes on heartbeat signals."""

    def __init__(self, fleet: Fleet,
                 policy: Optional[AutoscalePolicy] = None,
                 min_replicas: Optional[Dict[str, int]] = None,
                 max_replicas: Optional[Dict[str, int]] = None):
        self.fleet = fleet
        self.policy = policy or AutoscalePolicy()
        self.min_replicas = {r: 1 for r in _ROLES}
        self.min_replicas.update(min_replicas or {})
        self.max_replicas = {r: 4 for r in _ROLES}
        self.max_replicas.update(max_replicas or {})
        self._pressure = {r: 0 for r in _ROLES}
        self._idle = {r: 0 for r in _ROLES}
        self._cooldown = {r: 0 for r in _ROLES}
        self._last_shed = {r: 0 for r in _ROLES}
        self.steps = 0
        self.decisions: List[Dict] = []

    # -- signals --------------------------------------------------------

    def signals(self) -> Dict[str, Dict]:
        """One snapshot per replica class: live replica count, total
        queue depth, total shed count, and dead replica names.  Read
        from the heartbeat-payload plane when the fleet has a
        directory (the cross-process path), from the replicas
        directly otherwise."""
        directory = self.fleet.directory
        status = directory.status() if directory is not None else {}
        expired = set(directory.expired()) if directory is not None \
            else set()
        out = {r: {"replicas": 0, "queue_depth": 0, "shed": 0,
                   "dead": []} for r in _ROLES}
        for name, rep in self.fleet.replicas().items():
            if not rep.routing and not rep.alive:
                continue  # already-quarantined corpse
            sig = out.get(rep.role)
            if sig is None:
                continue
            st = status.get(name)
            payload = (st or {}).get("payload") or {}
            dead = not rep.alive or name in expired
            if dead:
                sig["dead"].append(name)
                continue
            sig["replicas"] += 1
            # the heartbeat payload is the truth when present (it is
            # what a cross-process controller would see); direct reads
            # back-fill for directory-less fleets
            if payload:
                sig["queue_depth"] += int(payload.get("queue_depth", 0))
                sig["shed"] += int(payload.get("shed", 0))
            else:
                sig["queue_depth"] += rep.queue_depth()
                sig["shed"] += rep._shed
        return out

    # -- the control loop -----------------------------------------------

    def _note(self, action: str, role: str, **detail) -> None:
        d = dict(action=action, role=role, step=self.steps, **detail)
        self.decisions.append(d)
        _log.info("fleet controller: %s %s (%s)", action, role, detail)
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_fleet_event(action, role=role)
            _flight.default_flight().record(
                action, fleet=self.fleet.name, role=role, **detail)

    def _decide(self, role: str, sig: Dict) -> Optional[str]:
        """Pure policy: fold one signal snapshot into the streak
        counters and return 'scale_up' / 'scale_down' / None.  Split
        out so the thresholds are unit-testable without a fleet."""
        p = self.policy
        live = max(sig["replicas"], 1)
        shed_delta = sig["shed"] - self._last_shed[role]
        self._last_shed[role] = sig["shed"]
        pressured = (sig["queue_depth"] >= p.queue_high * live
                     or shed_delta > 0)
        idle = sig["queue_depth"] == 0 and shed_delta == 0
        self._pressure[role] = self._pressure[role] + 1 if pressured \
            else 0
        self._idle[role] = self._idle[role] + 1 if idle else 0
        if self._cooldown[role] > 0:
            self._cooldown[role] -= 1
            return None
        if self._pressure[role] >= p.sustain \
                and sig["replicas"] < self.max_replicas[role]:
            self._pressure[role] = 0
            self._cooldown[role] = p.cooldown
            return "scale_up"
        if self._idle[role] >= p.idle_sustain \
                and sig["replicas"] > self.min_replicas[role]:
            self._idle[role] = 0
            self._cooldown[role] = p.cooldown
            return "scale_down"
        return None

    def step(self) -> List[Dict]:
        """One control iteration; returns the decisions it acted on."""
        self.steps += 1
        acted: List[Dict] = []
        sigs = self.signals()
        for role in _ROLES:
            sig = sigs[role]
            for name in sig["dead"]:
                self.fleet.quarantine_replica(name)
                self._note("replica_dead", role, replica=name)
                acted.append(self.decisions[-1])
            # replace casualties that dropped the class below min
            while sig["replicas"] < self.min_replicas[role]:
                name = getattr(self.fleet, f"add_{role}")()
                sig["replicas"] += 1
                self.fleet._count("scale_ups")
                self.fleet._count("respawns")
                self._note("scale_up", role, replica=name,
                           reason="below_min")
                acted.append(self.decisions[-1])
            verdict = self._decide(role, sig)
            if verdict == "scale_up":
                name = getattr(self.fleet, f"add_{role}")()
                self.fleet._count("scale_ups")
                self._note("scale_up", role, replica=name,
                           queue_depth=sig["queue_depth"])
                acted.append(self.decisions[-1])
            elif verdict == "scale_down":
                victim = self._pick_victim(role)
                if victim is not None:
                    drained = self.fleet.drain_replica(victim,
                                                       timeout=30.0)
                    self.fleet.remove_replica(victim)
                    self.fleet._count("scale_downs")
                    self._note("scale_down", role, replica=victim,
                               drained=bool(drained))
                    acted.append(self.decisions[-1])
        return acted

    def _pick_victim(self, role: str) -> Optional[str]:
        """Scale-down victim: the live replica with the shallowest
        queue (least work to drain; name tiebreak)."""
        reps = self.fleet.replicas(role)
        live = sorted((rep.queue_depth(), name)
                      for name, rep in reps.items()
                      if rep.alive and rep.routing)
        if len(live) <= self.min_replicas[role]:
            return None
        return live[0][1]

    # -- rolling upgrade -------------------------------------------------

    def rolling_upgrade(self, new_params: Dict,
                        timeout: float = 30.0) -> List[str]:
        """Swap every replica's weights under live traffic: drain one
        replica (its queued + in-flight work completes; new traffic
        routes to the others), swap params (prefix caches cleared,
        pool asserted empty), rejoin, repeat.  Zero requests lost or
        duplicated — the drain handoff guarantees it.  Returns the
        upgraded replica names in order."""
        upgraded: List[str] = []
        for role in _ROLES:
            for name in sorted(self.fleet.replicas(role)):
                rep = self.fleet.replicas(role).get(name)
                if rep is None or not rep.alive:
                    continue
                if not self.fleet.drain_replica(name, timeout=timeout):
                    raise RuntimeError(
                        f"replica {name} did not drain within "
                        f"{timeout}s — aborting the rolling upgrade")
                rep.swap_params(new_params, timeout=timeout)
                self.fleet.resume_replica(name)
                self.fleet._count("upgrades")
                self._note("upgrade", role, replica=name)
                upgraded.append(name)
        return upgraded

    def rolling_adapter_update(self, publish: Optional[Dict] = None,
                               retire: Sequence[str] = (),
                               timeout: float = 30.0) -> List[str]:
        """Hot adapter publish/retire under live traffic (ISSUE 19) —
        the ``rolling_upgrade`` cycle scoped to LoRA variants: drain
        one replica, publish each ``{adapter_id: weights}`` entry
        (register-or-replace) and retire the named ids on its adapter
        pool, rejoin, repeat.  Replicas without an adapter pool are
        skipped — a mixed fleet upgrades the tenanted members only.
        Returns the updated replica names in order."""
        publish = publish or {}
        updated: List[str] = []
        for role in _ROLES:
            for name in sorted(self.fleet.replicas(role)):
                rep = self.fleet.replicas(role).get(name)
                if rep is None or not rep.alive:
                    continue
                if getattr(rep, "adapter_pool", None) is None:
                    continue
                if not self.fleet.drain_replica(name, timeout=timeout):
                    raise RuntimeError(
                        f"replica {name} did not drain within "
                        f"{timeout}s — aborting the adapter update")
                for aid, weights in publish.items():
                    rep.publish_adapter(aid, weights)
                for aid in retire:
                    rep.retire_adapter(aid)
                self.fleet.resume_replica(name)
                self.fleet._count("upgrades")
                self._note("adapter_update", role, replica=name,
                           published=sorted(publish),
                           retired=sorted(retire))
                updated.append(name)
        return updated


def run_controller(controller: FleetController, every_s: float = 0.1,
                   stop=None) -> None:  # pragma: no cover — helper
    """Drive step() on an interval until `stop` (a threading.Event) is
    set — the long-running deployment shape; tests call step()
    directly for determinism."""
    while stop is None or not stop.is_set():
        controller.step()
        time.sleep(every_s)
