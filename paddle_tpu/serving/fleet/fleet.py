"""The disaggregated-serving frontend: one submit() over two replica
classes, with the KV handoff brokered in between.

Request life: ``submit(DecodeRequest)`` routes to the least-loaded
live :class:`PrefillReplica`; when its prefill completes, the broker
(running on the prefill worker via ``plan_handoff`` + a future
callback) has already reserved the destination
:class:`DecodeReplica`'s cached prefix and shipped only the unshared
tail; the decode replica imports the pages and decodes to completion;
the fleet future resolves with the finished ``GeneratedSequence``.

Failure is fail-over, never loss: a killed replica's queued work
returns typed (:class:`ReplicaKilledError`) and is resubmitted to
survivors; a dropped handoff payload (chaos
``FAULT_SERVE_HANDOFF_DROP``) requeues the request for a fresh prefill
(a payload exported against a destination reservation cannot be
rerouted — its prefix content never shipped); a poisoned prefill
quarantines one request (its result carries the
``NonFiniteSequenceError``, matching the monolithic loop's contract).
Every submit's future resolves exactly once — ``lost_requests == 0``
is the bankable invariant.

Scaling: ``add_prefill``/``add_decode`` (the autoscaler's actuators)
spawn replicas through caller-supplied factories;
``drain_replica``/``resume_replica``/``remove_replica`` implement
zero-loss scale-down and the rolling-upgrade drain→swap→rejoin cycle
(:meth:`FleetController.rolling_upgrade` drives it).  With a
``ReplicaDirectory`` the replicas heartbeat the elastic master with
status payloads and the controller reads its signals over the same
plane — in-process or through ``RemoteMaster``.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Tuple

from ... import flags as _flags
from ...resilience import faultinject as _finject
from ...observability import flight as _flight
from .. import metrics as _smetrics
from ..adapters import AdapterError
from ..generate import (
    DecodeRequest,
    GeneratedSequence,
    NonFiniteSequenceError,
)
from .handoff import Handoff, HandoffDropError
from .replica import (
    DecodeReplica,
    FleetQueueFullError,
    FleetReplica,
    PrefillReplica,
    ReplicaDrainingError,
    ReplicaKilledError,
)

_log = logging.getLogger("paddle_tpu.serving.fleet")

__all__ = ["Fleet", "NoReplicaAvailableError"]


class NoReplicaAvailableError(RuntimeError):
    """No live replica of the needed class could admit the request
    (after the retry budget) — the fleet-level fast failure."""


class Fleet:
    """Prefill and decode replica classes behind one submit()."""

    def __init__(self,
                 spawn_prefill: Callable[[str], PrefillReplica],
                 spawn_decode: Callable[[str], DecodeReplica],
                 n_prefill: int = 1, n_decode: int = 1,
                 directory=None, max_retries: int = 3,
                 place_timeout_s: float = 10.0, name: str = "fleet"):
        self.name = name
        self.directory = directory
        self.max_retries = int(max_retries)
        # how long a request may WAIT for a placeable replica (drain
        # windows during rolling upgrades, queue-full backpressure,
        # the gap while the autoscaler replaces a casualty) before the
        # fleet fails it typed — waiting is not a failover
        self.place_timeout_s = float(place_timeout_s)
        self._spawn = {"prefill": spawn_prefill, "decode": spawn_decode}
        self._lock = threading.Lock()
        self._prefill: Dict[str, PrefillReplica] = {}
        self._decode: Dict[str, DecodeReplica] = {}
        self._next_id = {"prefill": 0, "decode": 0}
        self._stats = {
            "submitted": 0, "completed": 0, "failed": 0,
            "handoffs": 0, "handoff_bytes": 0, "skipped_tokens": 0,
            "handoff_drops": 0, "handoff_drops_recovered": 0,
            "failovers": 0, "re_prefills": 0,
            "replica_deaths": 0, "scale_ups": 0, "scale_downs": 0,
            "upgrades": 0, "respawns": 0,
        }
        self.ttfts: List[float] = []   # fleet-level submit→first-token
        # first-failover → final-resolution, per disturbed request:
        # the price of a casualty as the CALLER experiences it
        self.failover_latencies: List[float] = []
        for _ in range(int(n_prefill)):
            self.add_prefill()
        for _ in range(int(n_decode)):
            self.add_decode()

    # -- membership / scaling -------------------------------------------

    def _add(self, role: str) -> str:
        with self._lock:
            name = f"{role}{self._next_id[role]}"
            self._next_id[role] += 1
        rep = self._spawn[role](name)
        if rep.role != role:
            raise ValueError(
                f"spawn_{role} returned a {rep.role!r} replica")
        if role == "prefill":
            rep.plan_handoff = self._plan_handoff
        if self.directory is not None:
            rep.join_directory(self.directory)
        with self._lock:
            getattr(self, f"_{role}")[name] = rep
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_fleet_replicas(role, len(self.replicas(role)))
        return name

    def add_prefill(self) -> str:
        """Scale up the prefill class by one replica; returns its name."""
        return self._add("prefill")

    def add_decode(self) -> str:
        """Scale up the decode class by one replica; returns its name."""
        return self._add("decode")

    def replicas(self, role: Optional[str] = None) -> Dict[str, FleetReplica]:
        with self._lock:
            if role == "prefill":
                return dict(self._prefill)
            if role == "decode":
                return dict(self._decode)
            out: Dict[str, FleetReplica] = dict(self._prefill)
            out.update(self._decode)
            return out

    def _find(self, name: str) -> FleetReplica:
        with self._lock:
            rep = self._prefill.get(name) or self._decode.get(name)
        if rep is None:
            raise KeyError(f"no replica {name!r}")
        return rep

    def drain_replica(self, name: str,
                      timeout: Optional[float] = None) -> bool:
        """Zero-loss drain: stop routing to the replica, then wait for
        its queued + in-flight work to finish there."""
        rep = self._find(name)
        rep.routing = False
        return rep.drain(timeout)

    def resume_replica(self, name: str) -> None:
        rep = self._find(name)
        rep.resume()
        rep.routing = True

    def remove_replica(self, name: str) -> FleetReplica:
        """Decommission a (drained) replica: stop its worker, then
        deregister its lease — closing FIRST, so a beat in flight
        cannot re-register the ghost the deregistration just
        removed."""
        with self._lock:
            rep = self._prefill.pop(name, None) \
                or self._decode.pop(name, None)
        if rep is None:
            raise KeyError(f"no replica {name!r}")
        rep.routing = False
        rep.close(timeout=10.0)
        if self.directory is not None:
            self.directory.deregister(name)
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_fleet_replicas(
                rep.role, len(self.replicas(rep.role)))
        return rep

    def quarantine_replica(self, name: str) -> None:
        """A dead/silent replica: silence it for good (routing stops,
        heartbeats stop, queued work fails over typed — an
        alive-but-flapping replica must not beat its ghost lease back
        to life), then deregister the lease.  The object stays visible
        for post-mortems."""
        rep = self._find(name)
        rep.quarantine()
        with self._lock:
            self._stats["replica_deaths"] += 1
        if self.directory is not None:
            self.directory.deregister(name)
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_fleet_event("replica_dead", role=rep.role)
            _flight.default_flight().record(
                "replica_dead", fleet=self.name, replica=name,
                role=rep.role)

    # -- routing --------------------------------------------------------

    def _pick(self, reps: Dict[str, FleetReplica]) -> Optional[FleetReplica]:
        """Least-queue-depth live routable replica (name tiebreak)."""
        best = None
        best_key = None
        for name in sorted(reps):
            rep = reps[name]
            if not (rep.alive and rep.routing and not rep.draining):
                continue
            key = (rep.queue_depth(), name)
            if best_key is None or key < best_key:
                best, best_key = rep, key
        return best

    def _plan_handoff(self, req: DecodeRequest):
        """Called by the prefill worker right before export: pick the
        destination decode replica and reserve its cached prefix (the
        payload then ships only the unshared tail)."""
        with self._lock:
            reps = dict(self._decode)
        rep = self._pick(reps)
        if rep is None:
            return None
        return rep.name, rep.reserve_prefix(
            req.prompt, adapter_id=getattr(req, "adapter_id", None))

    # -- the request path -----------------------------------------------

    def submit(self, req: DecodeRequest) -> Future:
        """One request through prefill → handoff → decode; the returned
        Future resolves to the finished GeneratedSequence (with
        ``.error`` set for a quarantined sequence, the monolithic
        loop's contract) or raises typed when the fleet could not place
        it within the retry budget."""
        fut: Future = Future()
        with self._lock:
            self._stats["submitted"] += 1
        fut.add_done_callback(self._bank_outcome)
        self._dispatch_prefill(req, fut, retries=0,
                               t_submit=time.perf_counter())
        return fut

    def _bank_outcome(self, fut: Future) -> None:
        """Per-request post-resolution accounting: the caller-visible
        first-failover→resolution latency, and whether a dropped
        handoff's request was recovered (resolved clean) rather than
        failed."""
        t0 = getattr(fut, "_failover_t0", None)
        if t0 is not None:
            with self._lock:
                self.failover_latencies.append(time.perf_counter() - t0)
        if getattr(fut, "_dropped", False):
            try:
                recovered = fut.exception() is None
            except Exception:  # noqa: BLE001 — cancelled counts as lost
                recovered = False
            if recovered:
                self._count("handoff_drops_recovered")

    def _mark_failover(self, fut: Future) -> None:
        # first disturbance only: the latency is failover→resolution as
        # the caller experiences it, not per-hop
        if not hasattr(fut, "_failover_t0"):
            fut._failover_t0 = time.perf_counter()

    def infer(self, req: DecodeRequest,
              timeout: Optional[float] = None) -> GeneratedSequence:
        return self.submit(req).result(timeout)

    def _resolve(self, fut: Future, result=None, error=None) -> None:
        with self._lock:
            self._stats["completed" if error is None else "failed"] += 1
        if not fut.set_running_or_notify_cancel():
            return
        if error is not None:
            fut.set_exception(error)
        else:
            fut.set_result(result)

    def _dispatch_prefill(self, req: DecodeRequest, fut: Future,
                          retries: int, t_submit: float) -> None:
        with self._lock:
            reps = dict(self._prefill)
        rep = self._pick(reps)
        if rep is not None:
            try:
                pfut = rep.submit(req)
            except (ValueError, AdapterError) as e:
                # request-shape / unknown-adapter validation: retrying
                # cannot fix it
                self._resolve(fut, error=e)
                return
            except (ReplicaKilledError, ReplicaDrainingError,
                    FleetQueueFullError):
                rep = None  # raced a kill/drain/full — wait and re-place
        if rep is not None:
            pfut.add_done_callback(
                lambda f: self._on_prefilled(f, req, fut, retries,
                                             t_submit))
            return
        # nothing placeable RIGHT NOW (a rolling upgrade draining the
        # only replica, queue-full backpressure, a casualty awaiting
        # its replacement): wait within the placement budget instead
        # of failing the request — waiting is not a failover
        if time.perf_counter() - t_submit < self.place_timeout_s:
            t = threading.Timer(
                0.05, self._dispatch_prefill,
                args=(req, fut, retries, t_submit))
            t.daemon = True
            t.start()
        else:
            self._resolve(fut, error=NoReplicaAvailableError(
                f"no prefill replica admitted the request within "
                f"{self.place_timeout_s}s"))

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._stats[key] += n

    def _on_prefilled(self, pfut: Future, req: DecodeRequest,
                      fut: Future, retries: int,
                      t_submit: float) -> None:
        exc = pfut.exception()
        if exc is not None:
            if isinstance(exc, NonFiniteSequenceError):
                # quarantine-not-crash: the request's result carries
                # the error, exactly as the monolithic loop reports it
                self._resolve(fut, result=GeneratedSequence(
                    seq_id=getattr(exc, "seq_id", -1),
                    prompt=[int(t) for t in req.prompt], error=exc))
            elif isinstance(exc, ReplicaKilledError) \
                    and retries < self.max_retries:
                self._count("failovers")
                self._mark_failover(fut)
                self._dispatch_prefill(req, fut, retries + 1, t_submit)
            else:
                self._resolve(fut, error=exc)
            return
        hd: Handoff = pfut.result()
        if _finject.serve_handoff_drop():
            # chaos: the payload is lost in transit — release the
            # destination's reservation and requeue for a fresh prefill
            self._count("handoff_drops")
            fut._dropped = True
            self._release_on_dest(hd)
            if _flags._VALUES["FLAGS_observability"]:
                _smetrics.record_fleet_event("handoff_drop")
                _flight.default_flight().record(
                    "handoff_drop", fleet=self.name, src=hd.src,
                    dest=hd.dest, trace_id=req.trace_id)
            if retries < self.max_retries:
                self._count("re_prefills")
                self._dispatch_prefill(req, fut, retries + 1, t_submit)
            else:
                self._resolve(fut, error=HandoffDropError(
                    "handoff dropped and retry budget exhausted"))
            return
        self._dispatch_decode(hd, req, fut, retries, t_submit)

    def _release_on_dest(self, hd: Handoff) -> None:
        if hd.dest is None:
            return
        with self._lock:
            dest = self._decode.get(hd.dest)
        if dest is not None:
            hd.release(dest.pool)

    def _dispatch_decode(self, hd: Handoff, req: DecodeRequest,
                         fut: Future, retries: int,
                         t_submit: float) -> None:
        if hd.dest is None and hd.reroutable():
            # an UNPLANNED handoff: no decode replica was up at export
            # time (the payload ships whole, skip_tokens == 0) — route
            # it now.  This is placement, not a failover
            with self._lock:
                reps = dict(self._decode)
            rep = self._pick(reps)
            if rep is not None:
                hd.dest = rep.name
        with self._lock:
            dest = self._decode.get(hd.dest) if hd.dest else None
        if dest is None or not (dest.alive and dest.routing
                                and not dest.draining):
            self._release_on_dest(hd)
            self._failover_handoff(hd, req, fut, retries, t_submit,
                                   why="destination unavailable")
            return
        try:
            dfut = dest.submit(hd)
        except (ReplicaKilledError, ReplicaDrainingError,
                FleetQueueFullError, HandoffDropError, ValueError,
                AdapterError) as e:
            self._release_on_dest(hd)
            if isinstance(e, (ValueError, AdapterError)) \
                    or retries >= self.max_retries:
                self._resolve(fut, error=e)
            else:
                self._failover_handoff(hd, req, fut, retries, t_submit,
                                       why=type(e).__name__)
            return
        # ONE TTFT sample per request, and only for a first token whose
        # payload actually reached a decode replica — a dropped handoff
        # re-prefills, and counting its never-delivered first token
        # would skew the banked percentiles low
        if not getattr(fut, "_ttft_banked", False):
            fut._ttft_banked = True
            self.ttfts.append(hd.first_token_at - t_submit)
        self._count("handoffs")
        self._count("handoff_bytes", hd.nbytes())
        self._count("skipped_tokens", hd.payload.skip_tokens)
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_fleet_event("handoff")
            _smetrics.record_handoff_bytes(hd.nbytes())
            _flight.default_flight().record(
                "handoff", fleet=self.name, src=hd.src, dest=hd.dest,
                bytes=hd.nbytes(),
                skipped_tokens=hd.payload.skip_tokens,
                trace_id=req.trace_id)
        dfut.add_done_callback(
            lambda f: self._on_decoded(f, hd, req, fut, retries,
                                       t_submit))

    def _failover_handoff(self, hd: Handoff, req: DecodeRequest,
                          fut: Future, retries: int, t_submit: float,
                          why: str, count: bool = True) -> None:
        """The planned destination cannot take the handoff.  A payload
        that shipped everything reroutes to any other decode replica
        (waiting out a drain window if none is up right now); one
        exported against a prefix reservation is missing content and
        must re-prefill."""
        if count:
            self._count("failovers")
            self._mark_failover(fut)
            if _flags._VALUES["FLAGS_observability"]:
                _smetrics.record_fleet_event("failover", role="decode")
        if hd.reroutable():
            with self._lock:
                reps = dict(self._decode)
            rep = self._pick(reps)
            if rep is not None:
                hd.dest = rep.name
                self._dispatch_decode(hd, req, fut, retries + 1,
                                      t_submit)
                return
            if time.perf_counter() - t_submit < self.place_timeout_s:
                # every decode replica is draining/replacing right now
                # — the payload is host-resident, waiting costs nothing
                t = threading.Timer(
                    0.05, self._failover_handoff,
                    args=(hd, req, fut, retries, t_submit, why, False))
                t.daemon = True
                t.start()
                return
        if retries < self.max_retries:
            self._count("re_prefills")
            self._dispatch_prefill(req, fut, retries + 1, t_submit)
        else:
            self._resolve(fut, error=NoReplicaAvailableError(
                f"no decode replica could take the handoff ({why})"))

    def _on_decoded(self, dfut: Future, hd: Handoff,
                    req: DecodeRequest, fut: Future, retries: int,
                    t_submit: float) -> None:
        exc = dfut.exception()
        if exc is None:
            self._resolve(fut, result=dfut.result())
            return
        if isinstance(exc, ReplicaKilledError) \
                and retries < self.max_retries:
            self._release_on_dest(hd)
            self._failover_handoff(hd, req, fut, retries, t_submit,
                                   why="replica killed")
            return
        self._resolve(fut, error=exc)

    # -- introspection / lifecycle --------------------------------------

    def stats(self) -> Dict:
        with self._lock:
            st = dict(self._stats)
            st["prefill_replicas"] = len(self._prefill)
            st["decode_replicas"] = len(self._decode)
            st["lost_requests"] = (st["submitted"] - st["completed"]
                                   - st["failed"])
        return st

    def health(self) -> Dict:
        return {name: rep.health()
                for name, rep in sorted(self.replicas().items())}

    def audit(self) -> Dict:
        """Leak/integrity epilogue over every replica pool: clear the
        prefix caches (pinned cache pages are a feature; pages nobody
        owns are a leak), then audit.  Returns aggregate
        ``pages_leaked`` and ``invariants_ok``."""
        leaked = 0
        ok = True
        for rep in self.replicas().values():
            if not rep.alive:
                continue  # a chaos-killed replica's pool died with it
            if rep.cache is not None:
                rep.cache.clear()
            leaked += rep.pool.used_pages
            ok = ok and rep.pool.check_invariants()["ok"]
        return {"pages_leaked": leaked, "invariants_ok": int(ok)}

    def close(self, timeout: Optional[float] = None) -> None:
        for rep in self.replicas().values():
            rep.routing = False
        for rep in self.replicas().values():
            rep.close(timeout)

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
