"""Replicas as real OS processes behind the same `FleetReplica` seam.

PR 15 split serving into prefill/decode replica classes but every
replica stayed an in-process thread, so the banked ``lost_requests=0``
invariant had only ever been proven against cooperative thread death.
This module closes the gap:

- **Entrypoint** — ``python -m paddle_tpu.serving.fleet.proc --role
  {prefill,decode} --name N --artifact F [--master host:port]`` loads a
  pickled artifact (params + DecodeConfig + per-role kwargs), builds
  the real thread replica inside the child, joins the
  ``ReplicaDirectory`` over ``RemoteMaster`` (heartbeats die WITH the
  process — lease expiry is the second death detector), serves the
  frame protocol, and prints ``SERVING <endpoint> <pid>``.
- **Data plane** — every fleet verb (submit/collect, drain/resume,
  swap_params, audit, shutdown) crosses the length-prefixed frame
  sub-protocol (`elastic.rpc.FrameClient`/`FrameServer`): pickle
  frames carry numpy, so a `Handoff`'s `SeqExport` payload and a
  `GeneratedSequence`'s logits cross sockets byte-identical.  Replica-
  side typed errors re-raise by NAME on the broker via the frame
  plane's error registry.  ``submit`` is idempotent (client-minted
  request id, server-side dedup) and ``collect`` is ack-based, so the
  client's bounded-backoff retry can re-send either after a torn
  response without duplicating or dropping work.
- **`ProcReplica`** — the broker-side proxy implementing the
  `FleetReplica` surface (`submit`→local Future, queue_depth, drain /
  resume / quarantine / close / swap_params, health, a pool facade
  backed by the ``audit`` verb), so `Fleet`/`FleetController`/
  serve_bench run UNCHANGED over processes.  One collector thread per
  replica drains finished futures; ANY transport failure marks the
  replica dead and fails every in-flight future with
  `ReplicaKilledError` — socket peers degrade typed, never hang.

Chaos is now SIGKILL-grade: ``FAULT_SERVE_PROC_KILL=<name>`` makes the
named child SIGKILL itself at its next batch start (no cleanup, no
atexit — a vanished PID), and `ProcReplica.quarantine` SIGKILLs a live
pid outright.

**Prefix reservations cross processes** (ISSUE 18 bugfix — PR 17
shipped the full payload on every cross-process handoff): the broker's
``reserve_prefix`` is now a real ``reserve_prefix`` verb against the
destination decode process (the real `PrefixReservation` stays pinned
in the CHILD's registry; a picklable `RidReservation` stub carries
only its rid+tokens over the wire), PLANNED handoffs ship
``skip_tokens > 0`` again (the broker attaches the plan to the request
before submit; the child's prefill exports only the unshared tail and
returns the stub on the Handoff, which the broker swaps back for the
original reservation handle), and ``release_prefix`` unwinds a
reservation whose payload was dropped or failed over.  UNPLANNED
failover is unchanged: a payload exported against a reservation is
missing content, so it re-prefills, while full payloads stay
reroutable to any surviving decode replica.
"""

from __future__ import annotations

import argparse
import logging
import os
import pickle
import signal
import subprocess
import sys
import tempfile
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

from ... import flags as _flags
from ...elastic.rpc import FrameClient, FrameError, register_error, serve_frames
from ...observability import flight as _flight
from ...resilience import faultinject as _finject
from .. import metrics as _smetrics
from ..adapters import (
    AdapterCorruptError,
    AdapterError,
    AdapterGeometryError,
    AdapterHostFullError,
    AdapterInUseError,
    AdapterMismatchError,
    AdapterNotRegisteredError,
    AdapterPoolFullError,
)
from .handoff import Handoff, HandoffDropError, RidReservation
from .replica import (
    FleetQueueFullError,
    ReplicaDrainingError,
    ReplicaKilledError,
)

_log = logging.getLogger("paddle_tpu.serving.fleet")

__all__ = ["ProcReplica", "ProcSpawner", "RemotePrefixReservation",
           "main"]

# fleet-typed errors cross the frame plane by name (the registry lives
# in elastic.rpc; registering here avoids an elastic→serving layering
# inversion)
for _cls in (ReplicaKilledError, ReplicaDrainingError,
             FleetQueueFullError, HandoffDropError,
             AdapterError, AdapterNotRegisteredError,
             AdapterGeometryError, AdapterInUseError,
             AdapterPoolFullError, AdapterHostFullError,
             AdapterCorruptError, AdapterMismatchError):
    register_error(_cls)

_TRANSPORT_ERRORS = (ConnectionError, TimeoutError, OSError)


# -- cross-process prefix reservations (ISSUE 18) ---------------------------
# The picklable wire stub (RidReservation) lives in handoff.py: this
# module runs as __main__ inside replica children, which would break
# its pickle identity.

class RemotePrefixReservation:
    """Broker-side handle for a prefix reservation pinned inside a
    decode PROCESS.  Mirrors the release seam the fleet exercises on
    failure paths (`Handoff.release(pool)` — the pool argument is
    ignored; the real pool lives with the owner): releasing sends the
    ``release_prefix`` verb to the owning replica, best-effort and
    idempotent, because a dead owner's pin died with its pool and a
    handoff can be released once by chaos and again by failover."""

    def __init__(self, owner: "ProcReplica", rid: str, tokens: int):
        self.owner = owner
        self.rid = rid
        self.tokens = int(tokens)
        self.released = False

    def release(self, pool=None) -> None:  # noqa: ARG002 — remote pool
        if self.released:
            return
        self.released = True
        self.owner._release_prefix(self.rid)


def _release_reservation(res) -> None:
    """Release a reservation handle without knowing the owning pool:
    thread `PrefixReservation`s learn their pool at creation time
    (``_owner_pool``), `RemotePrefixReservation`s ignore the argument
    and cross the frame plane instead."""
    try:
        res.release(getattr(res, "_owner_pool", None))
    except Exception:  # noqa: BLE001 — unwind is best-effort
        _log.warning("failed to release a planned prefix reservation",
                     exc_info=True)


def _plan_from_req(req):
    """Child-side handoff planner for a prefill process: the BROKER
    already planned against the destination's prefix trie (it owns the
    ``reserve_prefix`` verbs) and attached the result to the request
    before submit; re-hydrate it so `_prefill_jobs` exports with
    ``skip_tokens == res.tokens`` and stamps the stub on the Handoff."""
    plan = getattr(req, "_proc_plan", None)
    if not plan:
        return None
    res = None
    if plan.get("prid") is not None:
        res = RidReservation(plan["prid"], plan.get("tokens", 0))
    return plan["dest"], res


# -- child side: the verb service -------------------------------------------

class _ReplicaService:
    """Frame-verb dispatcher wrapped around a real (thread) replica,
    running INSIDE the replica process."""

    def __init__(self, rep):
        self.rep = rep
        self._lock = threading.Lock()
        self._pending: Dict[str, Future] = {}
        # rid -> ("ok", result) | ("err", exception): held until the
        # broker ACKs, so a collect response lost mid-write re-delivers
        self._done: Dict[str, Tuple] = {}
        # rid -> real PrefixReservation pinned by `reserve_prefix`;
        # consumed when the planned handoff's submit swaps it back onto
        # the Handoff, or unwound by the `release_prefix` verb
        self._reservations: Dict[str, object] = {}
        self._next_res = 0

    def dispatch(self, verb: str, **kwargs):
        fn = getattr(self, f"v_{verb}", None)
        if fn is None:
            raise ValueError(f"unknown verb {verb!r}")
        return fn(**kwargs)

    def v_ping(self) -> Dict:
        return {"pid": os.getpid(), "name": self.rep.name,
                "role": self.rep.role}

    def v_health(self) -> Dict:
        h = dict(self.rep.health())
        h["pid"] = os.getpid()
        return h

    def v_submit(self, rid: str, item) -> Dict:
        with self._lock:
            if rid in self._pending or rid in self._done:
                return {"dup": True}  # idempotent retry after torn resp
        stub = getattr(item, "reservation", None)
        real = None
        if isinstance(stub, RidReservation):
            # a planned handoff landing on its reserving replica: swap
            # the wire stub for the real pinned reservation so admit
            # re-attaches the reserved prefix pages
            with self._lock:
                real = self._reservations.pop(stub.rid, None)
            if real is None:
                raise HandoffDropError(
                    f"prefix reservation {stub.rid} is gone on "
                    f"{self.rep.name}; the planned payload is missing "
                    f"its reserved prefix")
            item.reservation = real
        try:
            fut = self.rep.submit(item)  # typed errors re-raise by name
        except BaseException:
            if real is not None:  # the pin survives a typed rejection
                with self._lock:
                    self._reservations[stub.rid] = real
                item.reservation = stub
            raise
        with self._lock:
            self._pending[rid] = fut
        fut.add_done_callback(lambda f, rid=rid: self._finish(rid, f))
        return {"queued": True}

    def _finish(self, rid: str, fut: Future) -> None:
        exc = fut.exception()
        if exc is None:
            entry = ("ok", fut.result())
        else:
            try:  # probe: an unpicklable exception must not tear collect
                pickle.dumps(exc)
            except Exception:  # noqa: BLE001 — degrade to name+message
                exc = RuntimeError(f"{type(exc).__name__}: {exc}")
            entry = ("err", exc)
        with self._lock:
            self._pending.pop(rid, None)
            self._done[rid] = entry

    def v_collect(self, ack=(), wait_s: float = 0.0) -> Dict:
        """Ack-then-poll: drop the rids the broker safely resolved,
        then return every finished-unacked entry (briefly blocking up
        to `wait_s` when none are ready).  Piggybacks the health
        snapshot so the broker's cached queue_depth/shed stay fresh
        without extra round-trips."""
        with self._lock:
            for rid in ack:
                self._done.pop(rid, None)
        deadline = time.perf_counter() + max(0.0, float(wait_s))
        while True:
            with self._lock:
                done = dict(self._done)
            if done or time.perf_counter() >= deadline:
                break
            time.sleep(0.005)
        return {"done": done, "health": self.rep.health()}

    def v_begin_drain(self) -> Dict:
        self.rep.begin_drain()
        return {}

    def v_drain(self, timeout_s: Optional[float] = None) -> Dict:
        return {"drained": bool(self.rep.drain(timeout_s))}

    def v_resume(self) -> Dict:
        self.rep.resume()
        return {}

    def v_swap_params(self, params, timeout_s: float = 5.0) -> Dict:
        self.rep.swap_params(params, timeout=timeout_s)
        return {}

    def v_audit(self) -> Dict:
        """The fleet audit, server-side: clear the prefix cache (pinned
        cache pages are a feature; pages nobody owns are a leak), then
        report pool residency + invariants."""
        rep = self.rep
        if rep.cache is not None:
            rep.cache.clear()
        inv = rep.pool.check_invariants()
        return {"used_pages": int(rep.pool.used_pages),
                "ok": bool(inv["ok"])}

    def v_reserve_prefix(self, prompt, adapter_id=None) -> Dict:
        """Pin the longest cached full-page prefix in THIS process and
        keep the real reservation here; only its rid + token count
        cross the wire.  The pin is consumed by the planned handoff's
        `v_submit` or unwound by `v_release_prefix`.  The match runs
        in `adapter_id`'s cache namespace (ISSUE 19)."""
        fn = getattr(self.rep, "reserve_prefix", None)
        res = fn(list(prompt), adapter_id=adapter_id) \
            if fn is not None else None
        if res is None:
            return {"rid": None, "tokens": 0}
        with self._lock:
            rid = f"res-{self._next_res}"
            self._next_res += 1
            self._reservations[rid] = res
        return {"rid": rid, "tokens": int(res.tokens)}

    def v_release_prefix(self, rid: str) -> Dict:
        with self._lock:
            res = self._reservations.pop(rid, None)
        if res is None:
            return {"released": False}  # consumed or already unwound
        res.release(self.rep.pool)
        return {"released": True}

    def v_shutdown(self, timeout_s: float = 10.0) -> Dict:
        def _exit():
            try:
                self.rep.close(timeout_s)
            finally:
                os._exit(0)

        threading.Thread(target=_exit, daemon=True).start()
        return {"__close__": True}


def _arm_proc_kill(rep) -> None:
    """FAULT_SERVE_PROC_KILL: SIGKILL ourselves at the next batch start
    — mid-prefill/mid-decode from the broker's perspective, since the
    submits that built this batch already ACKed."""
    if not os.environ.get("FAULT_SERVE_PROC_KILL"):
        return
    orig = rep._process

    def chaos_process(batch):
        if _finject.serve_proc_kill(rep.name):
            _log.warning("replica %s: chaos SIGKILL (pid %d)",
                         rep.name, os.getpid())
            # let the submit responses that built this batch finish
            # writing first: the kill must land mid-WORK (queued items
            # ACKed, results never coming), not mid-handshake
            time.sleep(0.05)
            os.kill(os.getpid(), signal.SIGKILL)
        orig(batch)

    rep._process = chaos_process


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.fleet.proc",
        description="one fleet replica as an OS process")
    ap.add_argument("--role", required=True,
                    choices=("prefill", "decode"))
    ap.add_argument("--name", required=True)
    ap.add_argument("--artifact", required=True,
                    help="pickle: {params, cfg, prefill: kwargs, "
                         "decode: kwargs}")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--master", default=None,
                    help="elastic master endpoint (host:port) to "
                         "heartbeat through; omit for directory-less "
                         "fleets")
    ap.add_argument("--max-silence", type=float, default=2.0)
    args = ap.parse_args(argv)

    with open(args.artifact, "rb") as f:
        art = pickle.load(f)
    from .replica import DecodeReplica, PrefillReplica

    cls = PrefillReplica if args.role == "prefill" else DecodeReplica
    rep = cls(args.name, art["params"], art["cfg"],
              **art.get(args.role, {}))
    if args.role == "prefill":
        # the broker plans the handoff (it can reach every decode
        # replica's trie) and ships the plan on the request
        rep.plan_handoff = _plan_from_req
    _arm_proc_kill(rep)
    service = _ReplicaService(rep)
    srv = serve_frames(service.dispatch, host=args.host, port=args.port)
    if args.master:
        from ...elastic.rpc import RemoteMaster
        from ..distributed import ReplicaDirectory

        rep.join_directory(ReplicaDirectory(
            RemoteMaster(args.master), max_silence_s=args.max_silence))
    # the handshake line the spawner waits for — everything above
    # (imports, pool allocation, directory join) already succeeded
    print(f"SERVING {srv.endpoint} {os.getpid()}", flush=True)
    try:
        while rep.alive:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    return 0


# -- broker side: spawner + proxy -------------------------------------------

class _RemotePoolView:
    """The `rep.pool` facade the fleet audit reads (`used_pages`,
    `check_invariants`), backed by one `audit` verb per snapshot (the
    cache-clear happens server-side).  A dead process's pool died with
    it: the view reports empty/ok, matching the audit's thread-fleet
    convention of skipping corpses."""

    def __init__(self, rep: "ProcReplica"):
        self._rep = rep

    @property
    def used_pages(self) -> int:
        return self._rep._audit()["used_pages"]

    def check_invariants(self) -> Dict:
        return {"ok": self._rep._audit()["ok"]}


class ProcReplica:
    """Broker-side proxy for one replica process — the `FleetReplica`
    seam over the frame plane.  `submit` mints a request id, registers
    a local Future, and sends the item; ONE collector thread per
    replica drains finished results back into those futures.  Any
    transport-level failure (refused connect after retries, reset,
    torn frame, timeout) marks the replica dead and fails every
    pending future with `ReplicaKilledError` — the exact degradation
    contract the thread fleet's chaos kill established, now proven
    against a vanished PID."""

    def __init__(self, name: str, role: str, proc: subprocess.Popen,
                 endpoint: str, pid: int, spawner=None,
                 call_timeout_s: float = 30.0,
                 max_retries: int = 3):
        self.name = name
        self.role = role
        self.proc = proc
        self.endpoint = endpoint
        self.pid = int(pid)
        self.routing = True
        self.directory = None
        self.plan_handoff = None   # set by Fleet on prefill replicas;
        # the broker runs it at submit time (it owns the dest-side
        # reserve_prefix verbs) and ships the plan on the request, so
        # the child's export skips the reserved prefix (ISSUE 18)
        self.cache = None          # audit clears the cache server-side
        self.pool = _RemotePoolView(self)
        self._spawner = spawner
        self._lock = threading.Lock()
        self._pending: Dict[str, Future] = {}
        self._acks: List[str] = []
        self._next_rid = 0
        # submit rid -> real dest reservation handle for a PLANNED
        # prefill in flight through the child; swapped back onto the
        # returned Handoff at collect, released if the prefill errors
        # or the process dies first
        self._planned: Dict[str, object] = {}
        self._alive = True
        self._closed = False
        self._draining = False
        self._shed = 0
        self._processed = 0
        self._qdepth_remote = 0
        self._audit_cache: Optional[Tuple[float, Dict]] = None
        # separate connections: collect long-polls server-side, and a
        # submit must never queue behind that wait
        self._ctl = FrameClient(endpoint, timeout=call_timeout_s,
                                max_retries=max_retries)
        self._col = FrameClient(endpoint, timeout=call_timeout_s,
                                max_retries=max_retries)
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True,
            name=f"procfleet-{name}-collect")
        self._collector.start()

    # -- liveness surface ----------------------------------------------

    @property
    def alive(self) -> bool:
        return self._alive

    @property
    def draining(self) -> bool:
        return self._draining

    def queue_depth(self) -> int:
        # outstanding = submitted-not-collected on that process; the
        # local view IS the broker's routing signal, no RPC needed
        with self._lock:
            return len(self._pending)

    def health(self) -> Dict:
        if not self._alive:
            return {"state": "BROKEN", "role": self.role,
                    "queue_depth": 0, "alive": False,
                    "shed": self._shed, "processed": self._processed,
                    "errors": 0, "pid": self.pid}
        try:
            return self._ctl.call("health", timeout=5.0)
        except _TRANSPORT_ERRORS as e:
            self._mark_dead(f"health probe failed: {e}")
            return self.health()

    def join_directory(self, directory) -> None:
        # the process registered ITSELF at startup (--master): its
        # heartbeats must die with the pid, not with the broker.  Keep
        # the handle so fleet-side deregistration works
        self.directory = directory

    # -- request path ---------------------------------------------------

    def submit(self, item) -> Future:
        with self._lock:
            if not self._alive:
                raise ReplicaKilledError(
                    f"replica {self.name} (pid {self.pid}) is dead")
            if self._draining or self._closed or not self.routing:
                raise ReplicaDrainingError(
                    f"replica {self.name} is draining")
            rid = f"{self.name}-{self._next_rid}"
            self._next_rid += 1
            fut: Future = Future()
            self._pending[rid] = fut
        orig_res = None
        try:
            orig_res = self._plan_for(rid, item)
            self._ctl.call("submit", rid=rid, item=item)
        except _TRANSPORT_ERRORS as e:
            with self._lock:
                self._pending.pop(rid, None)
            self._unplan(rid)
            self._mark_dead(f"submit transport failure: {e}")
            raise ReplicaKilledError(
                f"replica {self.name} (pid {self.pid}) died during "
                f"submit: {e}") from e
        except Exception as e:
            # replica-side typed rejection (draining/full/ValueError),
            # re-raised by name: the item never queued there
            with self._lock:
                self._pending.pop(rid, None)
                if isinstance(e, FleetQueueFullError):
                    self._shed += 1
            self._unplan(rid)
            raise
        finally:
            if orig_res is not None:
                # broker-side handoff keeps the REAL handle: the fleet's
                # failure paths release through it, and the stub only
                # ever existed for the wire
                item.reservation = orig_res
        return fut

    def _plan_for(self, rid: str, item):
        """Role-dependent reservation plumbing around one submit.

        Prefill: run the fleet's handoff planner HERE (the destination
        tries are reachable broker-side through `reserve_prefix`) and
        attach the plan to the request; the child reads it back through
        its own ``plan_handoff`` and exports with ``skip_tokens``.  The
        real dest reservation parks in ``_planned[rid]`` until the
        Handoff comes back (or the attempt dies).

        Decode: a planned `Handoff` arrives carrying the broker's
        `RemotePrefixReservation` handle; swap in the picklable rid
        stub for the wire (the real reservation is already pinned in
        the child) and return the original for the caller to restore."""
        if self.role == "prefill" and self.plan_handoff is not None \
                and hasattr(item, "prompt"):
            item._proc_plan = None  # never reuse a stale retry plan
            try:
                plan = self.plan_handoff(item)
            except Exception:  # noqa: BLE001 — planning is best-effort
                plan = None
            if plan is not None:
                dest, res = plan
                prid = None
                if res is not None:
                    prid = rid
                    with self._lock:
                        self._planned[rid] = res
                item._proc_plan = {
                    "dest": dest, "prid": prid,
                    "tokens": int(res.tokens) if res is not None else 0}
            return None
        res = getattr(item, "reservation", None)
        if isinstance(res, RemotePrefixReservation):
            if res.owner is not self:
                # a reservation only fits the replica that pinned it;
                # the fleet's failover turns this into a re-prefill
                raise HandoffDropError(
                    f"handoff reservation is pinned on "
                    f"{res.owner.name}, not {self.name}")
            item.reservation = RidReservation(res.rid, res.tokens)
            return res
        return None

    def _unplan(self, rid: str) -> None:
        with self._lock:
            res = self._planned.pop(rid, None)
        if res is not None:
            _release_reservation(res)

    def _collect_loop(self) -> None:
        while True:
            with self._lock:
                if self._closed or not self._alive:
                    return
                ack, self._acks = self._acks, []
            try:
                resp = self._col.call("collect", ack=ack, wait_s=0.2,
                                      timeout=15.0)
            except _TRANSPORT_ERRORS as e:
                self._mark_dead(f"collect transport failure: {e}")
                return
            except Exception as e:  # noqa: BLE001 — a verb-level error
                # here means a protocol bug, not a death; log and retry
                _log.warning("replica %s collect error: %s",
                             self.name, e)
                time.sleep(0.05)
                continue
            h = resp.get("health") or {}
            with self._lock:
                self._shed = int(h.get("shed", self._shed))
                self._processed = int(h.get("processed",
                                            self._processed))
                self._qdepth_remote = int(h.get("queue_depth", 0))
            for rid, entry in (resp.get("done") or {}).items():
                with self._lock:
                    fut = self._pending.pop(rid, None)
                    self._acks.append(rid)
                if fut is None:
                    continue
                with self._lock:
                    planned = self._planned.pop(rid, None)
                if fut.set_running_or_notify_cancel():
                    if entry[0] == "ok":
                        if planned is not None:
                            self._attach_planned(entry[1], planned)
                        fut.set_result(entry[1])
                    else:
                        if planned is not None:
                            # the prefill died before forming the
                            # handoff; unwind the dest's pin
                            _release_reservation(planned)
                        fut.set_exception(entry[1])

    @staticmethod
    def _attach_planned(result, res) -> None:
        """Swap the returned Handoff's wire stub back for the REAL
        dest reservation handle the broker parked at submit time, so
        downstream dispatch/admit/release see the same object the plan
        minted — uniform across thread and process destinations."""
        stub = getattr(result, "reservation", None)
        if isinstance(stub, RidReservation):
            result.reservation = res
        else:
            # the child prefilled without consuming the plan (stale
            # request state); the dest pin would otherwise leak
            _release_reservation(res)

    def _mark_dead(self, reason: str) -> None:
        with self._lock:
            if not self._alive:
                return
            self._alive = False
            leftovers, self._pending = self._pending, {}
            planned, self._planned = self._planned, {}
        for res in planned.values():
            # planned handoffs died with the prefill process, but their
            # reservations pin pages on (likely alive) DEST replicas
            _release_reservation(res)
        # routing stays ON, matching the thread replica's _die: the
        # controller reads alive=False + routing=True as a fresh corpse
        # and quarantines it (which is what turns routing off).  The
        # dispatch path never places on a dead replica regardless.
        level = logging.INFO if reason == "closed" and not leftovers \
            else logging.WARNING
        _log.log(
            level,
            "replica %s (pid %d) dead: %s; failing %d in-flight items "
            "over", self.name, self.pid, reason, len(leftovers))
        err = ReplicaKilledError(
            f"replica {self.name} (pid {self.pid}) died: {reason}")
        for fut in leftovers.values():
            if fut.set_running_or_notify_cancel():
                fut.set_exception(err)
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_fleet_event("proc_exit", role=self.role,
                                         pid=self.pid)
            _flight.default_flight().record(
                "proc_exit", replica=self.name, role=self.role,
                pid=self.pid, reason=reason)

    # -- drain / upgrade / stop ----------------------------------------

    def begin_drain(self) -> None:
        self._draining = True
        try:
            self._ctl.call("begin_drain")
        except _TRANSPORT_ERRORS as e:
            self._mark_dead(f"begin_drain transport failure: {e}")

    def drain(self, timeout: Optional[float] = None) -> bool:
        self.begin_drain()
        if not self._alive:
            return True  # nothing queued survives a dead process
        t = 30.0 if timeout is None else float(timeout)
        try:
            resp = self._ctl.call("drain", timeout=t + 10.0,
                                  timeout_s=t)
            drained = bool(resp["drained"])
        except _TRANSPORT_ERRORS as e:
            self._mark_dead(f"drain transport failure: {e}")
            return True
        if not drained:
            return False
        # drained server-side; wait for the collector to deliver the
        # last results so the caller sees resolved futures
        deadline = time.perf_counter() + t
        while time.perf_counter() < deadline:
            with self._lock:
                if not self._pending or not self._alive:
                    return True
            time.sleep(0.01)
        return not self._pending

    def resume(self) -> None:
        try:
            self._ctl.call("resume")
        except _TRANSPORT_ERRORS as e:
            self._mark_dead(f"resume transport failure: {e}")
            return
        self._draining = False

    def swap_params(self, new_params, timeout: float = 5.0) -> None:
        self._ctl.call("swap_params", params=new_params,
                       timeout=float(timeout) + 30.0,
                       timeout_s=timeout)

    def _audit(self) -> Dict:
        with self._lock:
            cached = self._audit_cache
            if cached is not None \
                    and time.perf_counter() - cached[0] < 0.2:
                return cached[1]
        if not self._alive:
            return {"used_pages": 0, "ok": True}
        try:
            out = self._ctl.call("audit", timeout=10.0)
        except _TRANSPORT_ERRORS as e:
            self._mark_dead(f"audit transport failure: {e}")
            return {"used_pages": 0, "ok": True}
        with self._lock:
            self._audit_cache = (time.perf_counter(), out)
        return out

    def reserve_prefix(self, prompt, adapter_id=None):
        """Pin the longest cached full-page prefix in the remote decode
        process (ISSUE 18): the real reservation stays in the child's
        registry, the broker holds a `RemotePrefixReservation` handle
        whose release crosses back as a verb, and the planned handoff
        ships only the unshared tail (``skip_tokens = res.tokens``).
        The match runs in `adapter_id`'s cache namespace (ISSUE 19)."""
        if not self._alive or self._draining or not self.routing:
            return None
        try:
            resp = self._ctl.call("reserve_prefix",
                                  prompt=[int(t) for t in prompt],
                                  adapter_id=adapter_id,
                                  timeout=10.0)
        except _TRANSPORT_ERRORS as e:
            self._mark_dead(f"reserve_prefix transport failure: {e}")
            return None
        except Exception:  # noqa: BLE001 — planning is best-effort;
            return None    # an unplanned handoff ships whole
        rid = resp.get("rid")
        if rid is None:
            return None
        return RemotePrefixReservation(self, rid,
                                       int(resp.get("tokens", 0)))

    def _release_prefix(self, rid: str) -> None:
        if not self._alive:
            return  # the pin died with the process's pool
        try:
            self._ctl.call("release_prefix", rid=rid, timeout=10.0)
        except _TRANSPORT_ERRORS as e:
            self._mark_dead(f"release_prefix transport failure: {e}")
        except Exception:  # noqa: BLE001 — already consumed is fine
            pass

    def quarantine(self) -> None:
        """SIGKILL-grade quarantine: fail in-flight work typed, then
        make sure the pid is actually gone (a flapping process must
        not beat its ghost lease back to life)."""
        self.routing = False
        self._mark_dead("quarantined")
        if self.proc is not None and self.proc.poll() is None:
            if _flags._VALUES["FLAGS_observability"]:
                _smetrics.record_fleet_event("proc_kill", role=self.role,
                                             pid=self.pid)
                _flight.default_flight().record(
                    "proc_kill", replica=self.name, role=self.role,
                    pid=self.pid)
            try:
                self.proc.kill()
            except OSError:
                pass
            self.proc.wait(timeout=10.0)
        self._ctl.close()
        self._col.close()

    def close(self, timeout: Optional[float] = None) -> None:
        self.routing = False
        t = 10.0 if timeout is None else float(timeout)
        deadline = time.perf_counter() + t
        # let queued work finish and its results flow back first
        while time.perf_counter() < deadline:
            with self._lock:
                if not self._pending or not self._alive:
                    break
            time.sleep(0.02)
        if self._alive:
            try:
                self._ctl.call("shutdown", retry=False, timeout_s=t)
            except Exception:  # noqa: BLE001 — already gone is fine
                pass
        with self._lock:
            self._closed = True
        if self.proc is not None:
            try:
                self.proc.wait(timeout=max(1.0, t))
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10.0)
        self._mark_dead("closed")
        self._ctl.close()
        self._col.close()


class ProcSpawner:
    """Factory for replica processes, pluggable straight into
    ``Fleet(spawner.prefill, spawner.decode, ...)``.  Writes the model
    artifact (params + config + per-role kwargs) once; each spawn
    launches the entrypoint, waits for the ``SERVING <endpoint> <pid>``
    handshake (child stderr goes to a per-replica log file for
    post-mortems), and wraps the process in a `ProcReplica`."""

    def __init__(self, params, cfg, prefill_kwargs: Optional[Dict] = None,
                 decode_kwargs: Optional[Dict] = None,
                 master_endpoint: Optional[str] = None,
                 startup_timeout_s: float = 120.0,
                 call_timeout_s: float = 30.0, max_retries: int = 3,
                 workdir: Optional[str] = None):
        self.dir = workdir or tempfile.mkdtemp(prefix="paddle_procfleet_")
        self.master_endpoint = master_endpoint
        self.startup_timeout_s = float(startup_timeout_s)
        self.call_timeout_s = float(call_timeout_s)
        self.max_retries = int(max_retries)
        self.artifact_path = os.path.join(self.dir, "artifact.pkl")
        with open(self.artifact_path, "wb") as f:
            pickle.dump({"params": params, "cfg": cfg,
                         "prefill": dict(prefill_kwargs or {}),
                         "decode": dict(decode_kwargs or {})}, f,
                        protocol=pickle.HIGHEST_PROTOCOL)
        self.replicas: List[ProcReplica] = []

    def prefill(self, name: str) -> ProcReplica:
        return self._spawn("prefill", name)

    def decode(self, name: str) -> ProcReplica:
        return self._spawn("decode", name)

    def _spawn(self, role: str, name: str) -> ProcReplica:
        cmd = [sys.executable, "-m", "paddle_tpu.serving.fleet.proc",
               "--role", role, "--name", name,
               "--artifact", self.artifact_path]
        if self.master_endpoint:
            cmd += ["--master", self.master_endpoint]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        log_path = os.path.join(self.dir, f"{name}.log")
        logf = open(log_path, "w")
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=logf, text=True)
        logf.close()  # the child holds the fd
        line_box: List[str] = []
        done = threading.Event()

        def _read():
            for line in proc.stdout:
                if line.startswith("SERVING "):
                    line_box.append(line.strip())
                    done.set()
                    break
            done.set()
            # keep draining so the child never blocks on a full pipe
            for _ in proc.stdout:
                pass

        threading.Thread(target=_read, daemon=True,
                         name=f"procfleet-{name}-stdout").start()
        if not done.wait(self.startup_timeout_s) or not line_box:
            proc.kill()
            tail = ""
            try:
                with open(log_path) as f:
                    tail = "".join(f.readlines()[-20:])
            except OSError:
                pass
            raise RuntimeError(
                f"replica process {name} failed to start "
                f"(no SERVING handshake within "
                f"{self.startup_timeout_s}s)\n{tail}")
        _, endpoint, pid = line_box[0].split()
        rep = ProcReplica(name, role, proc, endpoint, int(pid),
                          spawner=self,
                          call_timeout_s=self.call_timeout_s,
                          max_retries=self.max_retries)
        self.replicas.append(rep)
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_fleet_event("proc_spawn", role=role,
                                         pid=int(pid))
            _flight.default_flight().record(
                "proc_spawn", replica=name, role=role, pid=int(pid),
                endpoint=endpoint)
        return rep

    def close(self) -> None:
        """Kill any replica process still running (normal shutdown goes
        through `ProcReplica.close`; this is the safety net)."""
        for rep in self.replicas:
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.kill()
                try:
                    rep.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass


if __name__ == "__main__":  # pragma: no cover — subprocess entrypoint
    sys.exit(main())
