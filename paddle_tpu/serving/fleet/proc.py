"""Replicas as real OS processes behind the same `FleetReplica` seam.

PR 15 split serving into prefill/decode replica classes but every
replica stayed an in-process thread, so the banked ``lost_requests=0``
invariant had only ever been proven against cooperative thread death.
This module closes the gap:

- **Entrypoint** — ``python -m paddle_tpu.serving.fleet.proc --role
  {prefill,decode} --name N --artifact F [--master host:port]`` loads a
  pickled artifact (params + DecodeConfig + per-role kwargs), builds
  the real thread replica inside the child, joins the
  ``ReplicaDirectory`` over ``RemoteMaster`` (heartbeats die WITH the
  process — lease expiry is the second death detector), serves the
  frame protocol, and prints ``SERVING <endpoint> <pid>``.
- **Data plane** — every fleet verb (submit/collect, drain/resume,
  swap_params, audit, shutdown) crosses the length-prefixed frame
  sub-protocol (`elastic.rpc.FrameClient`/`FrameServer`): pickle
  frames carry numpy, so a `Handoff`'s `SeqExport` payload and a
  `GeneratedSequence`'s logits cross sockets byte-identical.  Replica-
  side typed errors re-raise by NAME on the broker via the frame
  plane's error registry.  ``submit`` is idempotent (client-minted
  request id, server-side dedup) and ``collect`` is ack-based, so the
  client's bounded-backoff retry can re-send either after a torn
  response without duplicating or dropping work.
- **`ProcReplica`** — the broker-side proxy implementing the
  `FleetReplica` surface (`submit`→local Future, queue_depth, drain /
  resume / quarantine / close / swap_params, health, a pool facade
  backed by the ``audit`` verb), so `Fleet`/`FleetController`/
  serve_bench run UNCHANGED over processes.  One collector thread per
  replica drains finished futures; ANY transport failure marks the
  replica dead and fails every in-flight future with
  `ReplicaKilledError` — socket peers degrade typed, never hang.

Chaos is now SIGKILL-grade: ``FAULT_SERVE_PROC_KILL=<name>`` makes the
named child SIGKILL itself at its next batch start (no cleanup, no
atexit — a vanished PID), and `ProcReplica.quarantine` SIGKILLs a live
pid outright.  Cross-process handoffs ship the FULL payload
(``skip_tokens == 0`` — prefix reservations stay an in-process
optimization), which keeps them reroutable to any surviving decode
replica; the fleet routes the unplanned destination at dispatch time.
"""

from __future__ import annotations

import argparse
import logging
import os
import pickle
import signal
import subprocess
import sys
import tempfile
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

from ... import flags as _flags
from ...elastic.rpc import FrameClient, FrameError, register_error, serve_frames
from ...observability import flight as _flight
from ...resilience import faultinject as _finject
from .. import metrics as _smetrics
from .handoff import Handoff, HandoffDropError
from .replica import (
    FleetQueueFullError,
    ReplicaDrainingError,
    ReplicaKilledError,
)

_log = logging.getLogger("paddle_tpu.serving.fleet")

__all__ = ["ProcReplica", "ProcSpawner", "main"]

# fleet-typed errors cross the frame plane by name (the registry lives
# in elastic.rpc; registering here avoids an elastic→serving layering
# inversion)
for _cls in (ReplicaKilledError, ReplicaDrainingError,
             FleetQueueFullError, HandoffDropError):
    register_error(_cls)

_TRANSPORT_ERRORS = (ConnectionError, TimeoutError, OSError)


# -- child side: the verb service -------------------------------------------

class _ReplicaService:
    """Frame-verb dispatcher wrapped around a real (thread) replica,
    running INSIDE the replica process."""

    def __init__(self, rep):
        self.rep = rep
        self._lock = threading.Lock()
        self._pending: Dict[str, Future] = {}
        # rid -> ("ok", result) | ("err", exception): held until the
        # broker ACKs, so a collect response lost mid-write re-delivers
        self._done: Dict[str, Tuple] = {}

    def dispatch(self, verb: str, **kwargs):
        fn = getattr(self, f"v_{verb}", None)
        if fn is None:
            raise ValueError(f"unknown verb {verb!r}")
        return fn(**kwargs)

    def v_ping(self) -> Dict:
        return {"pid": os.getpid(), "name": self.rep.name,
                "role": self.rep.role}

    def v_health(self) -> Dict:
        h = dict(self.rep.health())
        h["pid"] = os.getpid()
        return h

    def v_submit(self, rid: str, item) -> Dict:
        with self._lock:
            if rid in self._pending or rid in self._done:
                return {"dup": True}  # idempotent retry after torn resp
        fut = self.rep.submit(item)  # typed errors re-raise by name
        with self._lock:
            self._pending[rid] = fut
        fut.add_done_callback(lambda f, rid=rid: self._finish(rid, f))
        return {"queued": True}

    def _finish(self, rid: str, fut: Future) -> None:
        exc = fut.exception()
        if exc is None:
            entry = ("ok", fut.result())
        else:
            try:  # probe: an unpicklable exception must not tear collect
                pickle.dumps(exc)
            except Exception:  # noqa: BLE001 — degrade to name+message
                exc = RuntimeError(f"{type(exc).__name__}: {exc}")
            entry = ("err", exc)
        with self._lock:
            self._pending.pop(rid, None)
            self._done[rid] = entry

    def v_collect(self, ack=(), wait_s: float = 0.0) -> Dict:
        """Ack-then-poll: drop the rids the broker safely resolved,
        then return every finished-unacked entry (briefly blocking up
        to `wait_s` when none are ready).  Piggybacks the health
        snapshot so the broker's cached queue_depth/shed stay fresh
        without extra round-trips."""
        with self._lock:
            for rid in ack:
                self._done.pop(rid, None)
        deadline = time.perf_counter() + max(0.0, float(wait_s))
        while True:
            with self._lock:
                done = dict(self._done)
            if done or time.perf_counter() >= deadline:
                break
            time.sleep(0.005)
        return {"done": done, "health": self.rep.health()}

    def v_begin_drain(self) -> Dict:
        self.rep.begin_drain()
        return {}

    def v_drain(self, timeout_s: Optional[float] = None) -> Dict:
        return {"drained": bool(self.rep.drain(timeout_s))}

    def v_resume(self) -> Dict:
        self.rep.resume()
        return {}

    def v_swap_params(self, params, timeout_s: float = 5.0) -> Dict:
        self.rep.swap_params(params, timeout=timeout_s)
        return {}

    def v_audit(self) -> Dict:
        """The fleet audit, server-side: clear the prefix cache (pinned
        cache pages are a feature; pages nobody owns are a leak), then
        report pool residency + invariants."""
        rep = self.rep
        if rep.cache is not None:
            rep.cache.clear()
        inv = rep.pool.check_invariants()
        return {"used_pages": int(rep.pool.used_pages),
                "ok": bool(inv["ok"])}

    def v_shutdown(self, timeout_s: float = 10.0) -> Dict:
        def _exit():
            try:
                self.rep.close(timeout_s)
            finally:
                os._exit(0)

        threading.Thread(target=_exit, daemon=True).start()
        return {"__close__": True}


def _arm_proc_kill(rep) -> None:
    """FAULT_SERVE_PROC_KILL: SIGKILL ourselves at the next batch start
    — mid-prefill/mid-decode from the broker's perspective, since the
    submits that built this batch already ACKed."""
    if not os.environ.get("FAULT_SERVE_PROC_KILL"):
        return
    orig = rep._process

    def chaos_process(batch):
        if _finject.serve_proc_kill(rep.name):
            _log.warning("replica %s: chaos SIGKILL (pid %d)",
                         rep.name, os.getpid())
            # let the submit responses that built this batch finish
            # writing first: the kill must land mid-WORK (queued items
            # ACKed, results never coming), not mid-handshake
            time.sleep(0.05)
            os.kill(os.getpid(), signal.SIGKILL)
        orig(batch)

    rep._process = chaos_process


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.fleet.proc",
        description="one fleet replica as an OS process")
    ap.add_argument("--role", required=True,
                    choices=("prefill", "decode"))
    ap.add_argument("--name", required=True)
    ap.add_argument("--artifact", required=True,
                    help="pickle: {params, cfg, prefill: kwargs, "
                         "decode: kwargs}")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--master", default=None,
                    help="elastic master endpoint (host:port) to "
                         "heartbeat through; omit for directory-less "
                         "fleets")
    ap.add_argument("--max-silence", type=float, default=2.0)
    args = ap.parse_args(argv)

    with open(args.artifact, "rb") as f:
        art = pickle.load(f)
    from .replica import DecodeReplica, PrefillReplica

    cls = PrefillReplica if args.role == "prefill" else DecodeReplica
    rep = cls(args.name, art["params"], art["cfg"],
              **art.get(args.role, {}))
    _arm_proc_kill(rep)
    service = _ReplicaService(rep)
    srv = serve_frames(service.dispatch, host=args.host, port=args.port)
    if args.master:
        from ...elastic.rpc import RemoteMaster
        from ..distributed import ReplicaDirectory

        rep.join_directory(ReplicaDirectory(
            RemoteMaster(args.master), max_silence_s=args.max_silence))
    # the handshake line the spawner waits for — everything above
    # (imports, pool allocation, directory join) already succeeded
    print(f"SERVING {srv.endpoint} {os.getpid()}", flush=True)
    try:
        while rep.alive:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    return 0


# -- broker side: spawner + proxy -------------------------------------------

class _RemotePoolView:
    """The `rep.pool` facade the fleet audit reads (`used_pages`,
    `check_invariants`), backed by one `audit` verb per snapshot (the
    cache-clear happens server-side).  A dead process's pool died with
    it: the view reports empty/ok, matching the audit's thread-fleet
    convention of skipping corpses."""

    def __init__(self, rep: "ProcReplica"):
        self._rep = rep

    @property
    def used_pages(self) -> int:
        return self._rep._audit()["used_pages"]

    def check_invariants(self) -> Dict:
        return {"ok": self._rep._audit()["ok"]}


class ProcReplica:
    """Broker-side proxy for one replica process — the `FleetReplica`
    seam over the frame plane.  `submit` mints a request id, registers
    a local Future, and sends the item; ONE collector thread per
    replica drains finished results back into those futures.  Any
    transport-level failure (refused connect after retries, reset,
    torn frame, timeout) marks the replica dead and fails every
    pending future with `ReplicaKilledError` — the exact degradation
    contract the thread fleet's chaos kill established, now proven
    against a vanished PID."""

    def __init__(self, name: str, role: str, proc: subprocess.Popen,
                 endpoint: str, pid: int, spawner=None,
                 call_timeout_s: float = 30.0,
                 max_retries: int = 3):
        self.name = name
        self.role = role
        self.proc = proc
        self.endpoint = endpoint
        self.pid = int(pid)
        self.routing = True
        self.directory = None
        self.plan_handoff = None   # set by Fleet on prefill; unused —
        # process prefills export unplanned (dest=None, full payload)
        # and the fleet routes the handoff at dispatch time
        self.cache = None          # audit clears the cache server-side
        self.pool = _RemotePoolView(self)
        self._spawner = spawner
        self._lock = threading.Lock()
        self._pending: Dict[str, Future] = {}
        self._acks: List[str] = []
        self._next_rid = 0
        self._alive = True
        self._closed = False
        self._draining = False
        self._shed = 0
        self._processed = 0
        self._qdepth_remote = 0
        self._audit_cache: Optional[Tuple[float, Dict]] = None
        # separate connections: collect long-polls server-side, and a
        # submit must never queue behind that wait
        self._ctl = FrameClient(endpoint, timeout=call_timeout_s,
                                max_retries=max_retries)
        self._col = FrameClient(endpoint, timeout=call_timeout_s,
                                max_retries=max_retries)
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True,
            name=f"procfleet-{name}-collect")
        self._collector.start()

    # -- liveness surface ----------------------------------------------

    @property
    def alive(self) -> bool:
        return self._alive

    @property
    def draining(self) -> bool:
        return self._draining

    def queue_depth(self) -> int:
        # outstanding = submitted-not-collected on that process; the
        # local view IS the broker's routing signal, no RPC needed
        with self._lock:
            return len(self._pending)

    def health(self) -> Dict:
        if not self._alive:
            return {"state": "BROKEN", "role": self.role,
                    "queue_depth": 0, "alive": False,
                    "shed": self._shed, "processed": self._processed,
                    "errors": 0, "pid": self.pid}
        try:
            return self._ctl.call("health", timeout=5.0)
        except _TRANSPORT_ERRORS as e:
            self._mark_dead(f"health probe failed: {e}")
            return self.health()

    def join_directory(self, directory) -> None:
        # the process registered ITSELF at startup (--master): its
        # heartbeats must die with the pid, not with the broker.  Keep
        # the handle so fleet-side deregistration works
        self.directory = directory

    # -- request path ---------------------------------------------------

    def submit(self, item) -> Future:
        with self._lock:
            if not self._alive:
                raise ReplicaKilledError(
                    f"replica {self.name} (pid {self.pid}) is dead")
            if self._draining or self._closed or not self.routing:
                raise ReplicaDrainingError(
                    f"replica {self.name} is draining")
            rid = f"{self.name}-{self._next_rid}"
            self._next_rid += 1
            fut: Future = Future()
            self._pending[rid] = fut
        try:
            self._ctl.call("submit", rid=rid, item=item)
        except _TRANSPORT_ERRORS as e:
            with self._lock:
                self._pending.pop(rid, None)
            self._mark_dead(f"submit transport failure: {e}")
            raise ReplicaKilledError(
                f"replica {self.name} (pid {self.pid}) died during "
                f"submit: {e}") from e
        except Exception as e:
            # replica-side typed rejection (draining/full/ValueError),
            # re-raised by name: the item never queued there
            with self._lock:
                self._pending.pop(rid, None)
                if isinstance(e, FleetQueueFullError):
                    self._shed += 1
            raise
        return fut

    def _collect_loop(self) -> None:
        while True:
            with self._lock:
                if self._closed or not self._alive:
                    return
                ack, self._acks = self._acks, []
            try:
                resp = self._col.call("collect", ack=ack, wait_s=0.2,
                                      timeout=15.0)
            except _TRANSPORT_ERRORS as e:
                self._mark_dead(f"collect transport failure: {e}")
                return
            except Exception as e:  # noqa: BLE001 — a verb-level error
                # here means a protocol bug, not a death; log and retry
                _log.warning("replica %s collect error: %s",
                             self.name, e)
                time.sleep(0.05)
                continue
            h = resp.get("health") or {}
            with self._lock:
                self._shed = int(h.get("shed", self._shed))
                self._processed = int(h.get("processed",
                                            self._processed))
                self._qdepth_remote = int(h.get("queue_depth", 0))
            for rid, entry in (resp.get("done") or {}).items():
                with self._lock:
                    fut = self._pending.pop(rid, None)
                    self._acks.append(rid)
                if fut is None:
                    continue
                if fut.set_running_or_notify_cancel():
                    if entry[0] == "ok":
                        fut.set_result(entry[1])
                    else:
                        fut.set_exception(entry[1])

    def _mark_dead(self, reason: str) -> None:
        with self._lock:
            if not self._alive:
                return
            self._alive = False
            leftovers, self._pending = self._pending, {}
        # routing stays ON, matching the thread replica's _die: the
        # controller reads alive=False + routing=True as a fresh corpse
        # and quarantines it (which is what turns routing off).  The
        # dispatch path never places on a dead replica regardless.
        level = logging.INFO if reason == "closed" and not leftovers \
            else logging.WARNING
        _log.log(
            level,
            "replica %s (pid %d) dead: %s; failing %d in-flight items "
            "over", self.name, self.pid, reason, len(leftovers))
        err = ReplicaKilledError(
            f"replica {self.name} (pid {self.pid}) died: {reason}")
        for fut in leftovers.values():
            if fut.set_running_or_notify_cancel():
                fut.set_exception(err)
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_fleet_event("proc_exit", role=self.role,
                                         pid=self.pid)
            _flight.default_flight().record(
                "proc_exit", replica=self.name, role=self.role,
                pid=self.pid, reason=reason)

    # -- drain / upgrade / stop ----------------------------------------

    def begin_drain(self) -> None:
        self._draining = True
        try:
            self._ctl.call("begin_drain")
        except _TRANSPORT_ERRORS as e:
            self._mark_dead(f"begin_drain transport failure: {e}")

    def drain(self, timeout: Optional[float] = None) -> bool:
        self.begin_drain()
        if not self._alive:
            return True  # nothing queued survives a dead process
        t = 30.0 if timeout is None else float(timeout)
        try:
            resp = self._ctl.call("drain", timeout=t + 10.0,
                                  timeout_s=t)
            drained = bool(resp["drained"])
        except _TRANSPORT_ERRORS as e:
            self._mark_dead(f"drain transport failure: {e}")
            return True
        if not drained:
            return False
        # drained server-side; wait for the collector to deliver the
        # last results so the caller sees resolved futures
        deadline = time.perf_counter() + t
        while time.perf_counter() < deadline:
            with self._lock:
                if not self._pending or not self._alive:
                    return True
            time.sleep(0.01)
        return not self._pending

    def resume(self) -> None:
        try:
            self._ctl.call("resume")
        except _TRANSPORT_ERRORS as e:
            self._mark_dead(f"resume transport failure: {e}")
            return
        self._draining = False

    def swap_params(self, new_params, timeout: float = 5.0) -> None:
        self._ctl.call("swap_params", params=new_params,
                       timeout=float(timeout) + 30.0,
                       timeout_s=timeout)

    def _audit(self) -> Dict:
        with self._lock:
            cached = self._audit_cache
            if cached is not None \
                    and time.perf_counter() - cached[0] < 0.2:
                return cached[1]
        if not self._alive:
            return {"used_pages": 0, "ok": True}
        try:
            out = self._ctl.call("audit", timeout=10.0)
        except _TRANSPORT_ERRORS as e:
            self._mark_dead(f"audit transport failure: {e}")
            return {"used_pages": 0, "ok": True}
        with self._lock:
            self._audit_cache = (time.perf_counter(), out)
        return out

    def reserve_prefix(self, prompt):
        # no cross-process prefix reservation: the payload ships whole,
        # which is exactly what keeps process handoffs reroutable
        return None

    def quarantine(self) -> None:
        """SIGKILL-grade quarantine: fail in-flight work typed, then
        make sure the pid is actually gone (a flapping process must
        not beat its ghost lease back to life)."""
        self.routing = False
        self._mark_dead("quarantined")
        if self.proc is not None and self.proc.poll() is None:
            if _flags._VALUES["FLAGS_observability"]:
                _smetrics.record_fleet_event("proc_kill", role=self.role,
                                             pid=self.pid)
                _flight.default_flight().record(
                    "proc_kill", replica=self.name, role=self.role,
                    pid=self.pid)
            try:
                self.proc.kill()
            except OSError:
                pass
            self.proc.wait(timeout=10.0)
        self._ctl.close()
        self._col.close()

    def close(self, timeout: Optional[float] = None) -> None:
        self.routing = False
        t = 10.0 if timeout is None else float(timeout)
        deadline = time.perf_counter() + t
        # let queued work finish and its results flow back first
        while time.perf_counter() < deadline:
            with self._lock:
                if not self._pending or not self._alive:
                    break
            time.sleep(0.02)
        if self._alive:
            try:
                self._ctl.call("shutdown", retry=False, timeout_s=t)
            except Exception:  # noqa: BLE001 — already gone is fine
                pass
        with self._lock:
            self._closed = True
        if self.proc is not None:
            try:
                self.proc.wait(timeout=max(1.0, t))
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10.0)
        self._mark_dead("closed")
        self._ctl.close()
        self._col.close()


class ProcSpawner:
    """Factory for replica processes, pluggable straight into
    ``Fleet(spawner.prefill, spawner.decode, ...)``.  Writes the model
    artifact (params + config + per-role kwargs) once; each spawn
    launches the entrypoint, waits for the ``SERVING <endpoint> <pid>``
    handshake (child stderr goes to a per-replica log file for
    post-mortems), and wraps the process in a `ProcReplica`."""

    def __init__(self, params, cfg, prefill_kwargs: Optional[Dict] = None,
                 decode_kwargs: Optional[Dict] = None,
                 master_endpoint: Optional[str] = None,
                 startup_timeout_s: float = 120.0,
                 call_timeout_s: float = 30.0, max_retries: int = 3,
                 workdir: Optional[str] = None):
        self.dir = workdir or tempfile.mkdtemp(prefix="paddle_procfleet_")
        self.master_endpoint = master_endpoint
        self.startup_timeout_s = float(startup_timeout_s)
        self.call_timeout_s = float(call_timeout_s)
        self.max_retries = int(max_retries)
        self.artifact_path = os.path.join(self.dir, "artifact.pkl")
        with open(self.artifact_path, "wb") as f:
            pickle.dump({"params": params, "cfg": cfg,
                         "prefill": dict(prefill_kwargs or {}),
                         "decode": dict(decode_kwargs or {})}, f,
                        protocol=pickle.HIGHEST_PROTOCOL)
        self.replicas: List[ProcReplica] = []

    def prefill(self, name: str) -> ProcReplica:
        return self._spawn("prefill", name)

    def decode(self, name: str) -> ProcReplica:
        return self._spawn("decode", name)

    def _spawn(self, role: str, name: str) -> ProcReplica:
        cmd = [sys.executable, "-m", "paddle_tpu.serving.fleet.proc",
               "--role", role, "--name", name,
               "--artifact", self.artifact_path]
        if self.master_endpoint:
            cmd += ["--master", self.master_endpoint]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        log_path = os.path.join(self.dir, f"{name}.log")
        logf = open(log_path, "w")
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=logf, text=True)
        logf.close()  # the child holds the fd
        line_box: List[str] = []
        done = threading.Event()

        def _read():
            for line in proc.stdout:
                if line.startswith("SERVING "):
                    line_box.append(line.strip())
                    done.set()
                    break
            done.set()
            # keep draining so the child never blocks on a full pipe
            for _ in proc.stdout:
                pass

        threading.Thread(target=_read, daemon=True,
                         name=f"procfleet-{name}-stdout").start()
        if not done.wait(self.startup_timeout_s) or not line_box:
            proc.kill()
            tail = ""
            try:
                with open(log_path) as f:
                    tail = "".join(f.readlines()[-20:])
            except OSError:
                pass
            raise RuntimeError(
                f"replica process {name} failed to start "
                f"(no SERVING handshake within "
                f"{self.startup_timeout_s}s)\n{tail}")
        _, endpoint, pid = line_box[0].split()
        rep = ProcReplica(name, role, proc, endpoint, int(pid),
                          spawner=self,
                          call_timeout_s=self.call_timeout_s,
                          max_retries=self.max_retries)
        self.replicas.append(rep)
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_fleet_event("proc_spawn", role=role,
                                         pid=int(pid))
            _flight.default_flight().record(
                "proc_spawn", replica=name, role=role, pid=int(pid),
                endpoint=endpoint)
        return rep

    def close(self) -> None:
        """Kill any replica process still running (normal shutdown goes
        through `ProcReplica.close`; this is the safety net)."""
        for rep in self.replicas:
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.kill()
                try:
                    rep.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass


if __name__ == "__main__":  # pragma: no cover — subprocess entrypoint
    sys.exit(main())
