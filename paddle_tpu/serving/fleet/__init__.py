"""Disaggregated prefill/decode serving behind an elastic fleet.

Prefill is compute-bound and decode is bandwidth-bound; one replica
class serves both badly.  This package splits them (ISSUE 15):

- **Disaggregation** — :class:`PrefillReplica` runs chunked
  whole-prompt prefill only, :class:`DecodeReplica` runs the
  continuous-batching loop only, and the KV **handoff** moves a
  prefilled sequence between their pools:
  ``KVCachePool.export_seq``/``import_seq`` stage the sequence's pages
  + lengths + int8 scales through host numpy buffers (the same path a
  cross-process data plane will use; on-mesh pools keep the page
  writes device-side), the decode-side admission charges the imported
  footprint atomically, and prefix-cache composition ships only the
  unshared tail — the destination re-attaches shared pages from its
  own cache by hash, refcount-pinned for the transfer
  (:class:`~paddle_tpu.serving.fleet.handoff.PrefixReservation`).
  Disaggregated output is token-identical to the monolithic
  ``ContinuousBatchingLoop`` (tests/test_fleet.py pins the
  GQA × int8 × prefix-hit matrix).
- **Elasticity** — :class:`Fleet` fronts both classes behind one
  ``submit()`` with fail-over-never-lose brokering, and
  :class:`FleetController` rides the elastic master's heartbeat/lease
  plane (replicas publish queue depth / shed rate / health in their
  beat payloads; the controller reads them in-process or over
  ``RemoteMaster``): scale-up on sustained queue growth or shedding,
  scale-down and **rolling weight upgrades** through the zero-loss
  drain handoff, dead replicas quarantined (not crashed into) and
  replaced.  Chaos knobs ``FAULT_SERVE_REPLICA_KILL`` /
  ``FAULT_SERVE_HANDOFF_DROP`` drive the degradation tests;
  ``serve_bench --disagg`` / ``--fleet`` bank handoff bytes/seq, TTFT
  under bursty load, and ``lost_requests=0`` on the 0/2/3 gate.
"""

from .controller import AutoscalePolicy, FleetController
from .fleet import Fleet, NoReplicaAvailableError
from .handoff import Handoff, HandoffDropError, PrefixReservation
from .proc import ProcReplica, ProcSpawner
from .replica import (
    DecodeReplica,
    FleetQueueFullError,
    FleetReplica,
    PrefillReplica,
    ReplicaDrainingError,
    ReplicaKilledError,
)

__all__ = [
    "AutoscalePolicy",
    "DecodeReplica",
    "Fleet",
    "FleetController",
    "FleetQueueFullError",
    "FleetReplica",
    "Handoff",
    "HandoffDropError",
    "NoReplicaAvailableError",
    "PrefillReplica",
    "PrefixReservation",
    "ProcReplica",
    "ProcSpawner",
    "ReplicaDrainingError",
    "ReplicaKilledError",
]
