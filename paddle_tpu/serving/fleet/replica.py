"""Disaggregated serving replicas: prefill-only and decode-only.

Prefill is compute-bound (one big causal pass per prompt) and decode is
bandwidth-bound (one KV page stream per token), so one replica class
serves both badly: co-scheduling them couples a long prompt's compute
burst to every in-flight sequence's per-token latency, and capacity
planning has to size one pool for two very different residencies.  The
fleet splits them:

- :class:`PrefillReplica` runs chunked whole-prompt prefill ONLY: admit
  a group of prompts, write their K/V into its own (transient) pool —
  whole-prompt ``prefill_step`` when nothing is cached and no chunk cap
  binds, ``chunk_prefill_step`` otherwise, exactly the monolithic
  loop's arithmetic — choose each sequence's first token against the
  final logits (greedy/biased argmax or the seeded sampling epilogue,
  so the choice is what a monolithic loop would have made), then
  EXPORT the sequence (``KVCachePool.export_seq``) and free it.  Its
  prefix cache makes repeated prefixes cost one prefill; its pool is
  sized for prompts in flight, not sessions.
- :class:`DecodeReplica` runs the continuous-batching loop ONLY:
  submitted :class:`~paddle_tpu.serving.fleet.handoff.Handoff`\\ s are
  admitted straight into decode — the loop imports the shipped pages
  (one atomic claim), re-attaches reserved shared-prefix pages from its
  OWN cache, emits the already-chosen first token, and the sequence
  decodes like any locally-prefilled one.  Its pool is sized for
  concurrent sessions' KV residency.

Both classes ride one worker thread (:class:`FleetReplica` — the
in-process stand-in for a replica process this PR; the payloads and
the control plane are already cross-process-shaped), heartbeat the
elastic master through a ``ReplicaDirectory`` with a status payload
(queue depth, shed count, health state — the autoscaler's signals),
and degrade quarantine-not-crash: a poisoned prefill evicts one
request, a chaos replica kill (FAULT_SERVE_REPLICA_KILL) fails queued
work typed so the fleet fails it over, never silently loses it.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ... import flags as _flags
from ...resilience import faultinject as _finject
from .. import prefill_sched as _psched
from ..adapters import AdapterError, AdapterNotRegisteredError
from ..generate import (
    ContinuousBatchingLoop,
    DecodeConfig,
    DecodeRequest,
    chunk_prefill_step,
    prefill_step,
)
from ..kvcache import KVCachePool
from ..prefixcache import PrefixCache
from ..sampling import apply_bias, sample_rows
from .handoff import Handoff, PrefixReservation

_log = logging.getLogger("paddle_tpu.serving.fleet")

__all__ = [
    "DecodeReplica",
    "FleetQueueFullError",
    "FleetReplica",
    "PrefillReplica",
    "ReplicaDrainingError",
    "ReplicaKilledError",
]


class ReplicaKilledError(RuntimeError):
    """The replica died (chaos FAULT_SERVE_REPLICA_KILL or a real
    worker-thread death): its queued work fails with this so the fleet
    can fail it over to survivors — zero requests lost."""


class ReplicaDrainingError(RuntimeError):
    """The replica is draining (scale-down or rolling upgrade) and no
    longer admits work; the fleet routes elsewhere."""


class FleetQueueFullError(RuntimeError):
    """The replica's bounded queue is full — counted as shed, which is
    one of the autoscaler's scale-up signals."""


class FleetReplica:
    """One worker-thread replica: bounded queue, drain/resume, chaos
    kill, and heartbeat-with-payload on the elastic master's plane."""

    role = "?"

    def __init__(self, name: str, max_batch: int = 4,
                 queue_cap: int = 256, beat_every_s: float = 0.05):
        self.name = name
        self.max_batch = int(max_batch)
        self.queue_cap = int(queue_cap)
        self.routing = True          # fleet-level routing claim
        self.directory = None        # ReplicaDirectory once joined
        self._beat_every_s = float(beat_every_s)
        self._cond = threading.Condition()
        self._queue: List[Tuple[object, Future]] = []
        self._draining = False
        self._stopped = False
        self._busy = False
        self._alive = True
        self._shed = 0
        self._processed = 0
        self._errors = 0
        self._beat_thread: Optional[threading.Thread] = None
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name=f"fleet-{name}")
        self._thread.start()

    # -- membership / liveness -----------------------------------------

    def join_directory(self, directory) -> None:
        """Start heartbeating ``replica/<name>`` with a status payload.
        Beats run on their OWN thread, independent of the worker: a
        long decode batch must not go lease-silent and get a
        healthy-but-busy replica quarantined exactly when it is
        busiest."""
        self.directory = directory
        directory.register(self.name, payload=self._payload())
        if self._beat_thread is None:
            self._beat_thread = threading.Thread(
                target=self._beat_loop, daemon=True,
                name=f"fleet-{self.name}-beat")
            self._beat_thread.start()

    def _beat_loop(self) -> None:
        while True:
            with self._cond:
                if self._stopped or not self._alive:
                    return
                self._cond.wait(self._beat_every_s)
                if self._stopped or not self._alive:
                    return
            self._beat()  # outside the lock: _payload() re-takes it

    def _payload(self) -> Dict:
        h = self.health()
        return {"role": self.role, "state": h["state"],
                "queue_depth": h["queue_depth"], "shed": self._shed,
                "processed": self._processed}

    def _beat(self) -> None:
        d = self.directory
        if d is None or self._stopped or not self._alive:
            # a quarantined/stopped replica must go SILENT: one more
            # beat would re-register the ghost lease the controller
            # just deregistered
            return
        try:
            d.beat(self.name, payload=self._payload())
        except Exception as e:  # noqa: BLE001 — a flapping master must
            # not kill the replica; the lease lapses and the controller
            # notices through expired() instead
            _log.warning("replica %s heartbeat failed: %s", self.name, e)

    @property
    def alive(self) -> bool:
        return self._alive

    @property
    def draining(self) -> bool:
        return self._draining

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue) + (1 if self._busy else 0)

    def health(self) -> Dict:
        with self._cond:
            q = len(self._queue) + (1 if self._busy else 0)
        state = ("BROKEN" if not self._alive
                 else "DRAINING" if self._draining else "SERVING")
        return {"state": state, "role": self.role, "queue_depth": q,
                "alive": self._alive, "shed": self._shed,
                "processed": self._processed, "errors": self._errors}

    # -- admission ------------------------------------------------------

    def _submit_item(self, item) -> Future:
        fut: Future = Future()
        with self._cond:
            if not self._alive:
                raise ReplicaKilledError(
                    f"replica {self.name} is dead")
            if self._draining or self._stopped or not self.routing:
                raise ReplicaDrainingError(
                    f"replica {self.name} is draining")
            if len(self._queue) >= self.queue_cap:
                self._shed += 1
                raise FleetQueueFullError(
                    f"replica {self.name} queue full "
                    f"({self.queue_cap})")
            self._queue.append((item, fut))
            self._cond.notify_all()
        return fut

    # -- drain / upgrade / stop ----------------------------------------

    def begin_drain(self) -> None:
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admissions, then wait for queued + in-flight work to
        finish.  Returns True when fully drained (timeout=0 polls)."""
        self.begin_drain()
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        with self._cond:
            while self._queue or self._busy:
                wait = 0.1
                if deadline is not None:
                    wait = deadline - time.perf_counter()
                    if wait <= 0:
                        return False
                    wait = min(wait, 0.1)
                self._cond.wait(wait)
        return True

    def resume(self) -> None:
        """Re-admit work after a drain (the rolling-upgrade rejoin)."""
        with self._cond:
            self._draining = False
            self._cond.notify_all()

    def close(self, timeout: Optional[float] = None) -> None:
        self.drain(timeout)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(5.0)
        if self._beat_thread is not None:
            self._beat_thread.join(1.0)

    def quarantine(self) -> None:
        """Permanently silence a quarantined replica: stop admissions
        AND heartbeats (an alive-but-flapping replica would otherwise
        keep beating and re-register the lease the controller just
        deregistered — counted live forever with routing off, so the
        class never gets its replacement), and fail queued work over
        typed.  An in-flight batch still finishes and resolves its
        futures; the worker thread then exits on its own."""
        self.routing = False
        with self._cond:
            self._alive = False
            self._stopped = True
            leftovers, self._queue = self._queue, []
            self._cond.notify_all()
        if leftovers:
            _log.warning(
                "replica %s quarantined; failing %d queued items over",
                self.name, len(leftovers))
        err = ReplicaKilledError(f"replica {self.name} quarantined")
        for item, fut in leftovers:
            self._cleanup_item(item)
            if fut.set_running_or_notify_cancel():
                fut.set_exception(err)

    # -- worker ---------------------------------------------------------

    def _worker(self) -> None:
        while True:
            if _finject.serve_replica_kill(self.name):
                self._die()
                return
            batch = None
            with self._cond:
                if self._queue:
                    batch = self._take_locked()
                    self._busy = bool(batch)
                elif self._stopped:
                    self._cond.notify_all()
                    return
                else:
                    self._cond.notify_all()  # wake drain()/close()
                    self._cond.wait(self._beat_every_s)
            if batch:
                try:
                    self._process(batch)
                except BaseException as e:  # noqa: BLE001 — a raise
                    # costs this batch, never the replica (the loop /
                    # prefill steps already freed their pages)
                    self._errors += 1
                    _log.warning(
                        "replica %s batch failed (%s: %s)", self.name,
                        type(e).__name__, e)
                    for item, fut in batch:
                        self._cleanup_item(item)
                        if fut.set_running_or_notify_cancel():
                            fut.set_exception(e)
                finally:
                    with self._cond:
                        self._busy = False
                        self._cond.notify_all()
            # no beat here: the beat thread owns the lease cadence

    def _die(self) -> None:
        """Chaos replica kill: the worker thread exits WITHOUT restart
        (a dead process has no supervisor).  Queued work fails typed so
        the fleet fails it over — quarantine-not-crash."""
        with self._cond:
            self._alive = False
            self._stopped = True
            leftovers, self._queue = self._queue, []
            self._cond.notify_all()
        _log.warning(
            "replica %s killed (chaos); failing %d queued items over",
            self.name, len(leftovers))
        err = ReplicaKilledError(f"replica {self.name} killed")
        for item, fut in leftovers:
            self._cleanup_item(item)
            if fut.set_running_or_notify_cancel():
                fut.set_exception(err)

    # subclass hooks
    def _take_locked(self) -> List:
        n = min(len(self._queue), self.max_batch)
        batch, self._queue = self._queue[:n], self._queue[n:]
        return batch

    def _process(self, batch: List) -> None:
        raise NotImplementedError

    def _cleanup_item(self, item) -> None:
        """Undo any cross-replica state a failed/killed item holds."""


@dataclasses.dataclass
class _Job:
    req: DecodeRequest
    fut: Future
    seq_id: int
    pos: int = 0          # prompt tokens already covered (cache hits)
    matched: int = 0      # of which served by the prefix cache
    row: Optional[np.ndarray] = None
    aslot: int = 0        # adapter pool slot (0 = base model)


def _choose_first(req: DecodeRequest, row: np.ndarray) -> int:
    """The first generated token, chosen exactly as the monolithic
    loop's emit path would: (bias-shifted) greedy argmax, or the
    seeded sampling epilogue at token index 0 for non-greedy params —
    so a handoff sequence's stream is replay-identical."""
    p = req.sampling
    if p is None or p.greedy:
        return int(apply_bias(row, p).argmax())
    return int(sample_rows(
        np.asarray([apply_bias(row, p)]), [p], [0])[0])


class PrefillReplica(FleetReplica):
    """Chunked whole-prompt prefill only; emits Handoffs."""

    role = "prefill"

    def __init__(self, name: str, params: Dict, cfg: DecodeConfig,
                 num_pages: int = 64, page_size: int = 8,
                 dtype: str = "float32", max_batch: int = 4,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: bool = True, plan_handoff=None,
                 queue_cap: int = 256, beat_every_s: float = 0.05,
                 adapter_pool=None):
        self.params = params
        self.cfg = cfg
        self.adapter_pool = adapter_pool
        self.pool = KVCachePool(
            num_pages, page_size, cfg.n_layer, cfg.n_head, cfg.head_dim,
            dtype=dtype, name=f"{name}-pool",
            num_kv_heads=cfg.num_kv_heads)
        self.cache = PrefixCache(self.pool) if prefix_cache else None
        self._chunk = int(
            prefill_chunk if prefill_chunk is not None
            else _flags._VALUES["FLAGS_serving_prefill_chunk"])
        # plan_handoff(req) -> (dest_name, PrefixReservation|None) |
        # None — the fleet's broker picks the destination replica and
        # reserves its cached prefix RIGHT BEFORE export, so the
        # payload ships only the unshared tail
        self.plan_handoff = plan_handoff
        self._next_seq = 0
        self.steps = 0
        self.prefills = 0
        self.quarantined = 0
        self.exported_bytes = 0
        self.skipped_tokens = 0
        super().__init__(name, max_batch=max_batch, queue_cap=queue_cap,
                         beat_every_s=beat_every_s)

    def submit(self, req: DecodeRequest) -> Future:
        """Enqueue one request; the Future resolves to a Handoff (or a
        typed error).  Request-shape validation happens HERE so one bad
        request never fails a co-prefilled group."""
        if not len(req.prompt):
            raise ValueError("empty prompt")
        total = len(req.prompt) + req.max_new_tokens
        if total > self.cfg.max_length:
            raise ValueError(
                f"prompt+max_new={total} exceeds max_length "
                f"{self.cfg.max_length}")
        if req.sampling is not None \
                and req.sampling.max_bias_token() >= self.cfg.vocab_size:
            raise ValueError(
                f"logit_bias token {req.sampling.max_bias_token()} >= "
                f"vocab_size {self.cfg.vocab_size}")
        need = KVCachePool.pages_needed(len(req.prompt),
                                        self.pool.page_size)
        if need > self.pool.num_pages:
            raise ValueError(
                f"prompt needs {need} pages worst-case but replica "
                f"{self.name}'s pool has {self.pool.num_pages}")
        aid = getattr(req, "adapter_id", None)
        if aid is not None:
            if self.adapter_pool is None:
                raise ValueError(
                    f"request wants adapter {aid!r} but replica "
                    f"{self.name} has no adapter_pool")
            if not self.adapter_pool.loadable(aid):
                raise AdapterNotRegisteredError(
                    f"adapter {aid!r} is not loadable on replica "
                    f"{self.name} — register/publish it first")
        return self._submit_item(req)

    def swap_params(self, new_params: Dict,
                    timeout: float = 5.0) -> None:
        """Rolling-upgrade arm: replace the weights of a DRAINED
        replica.  The prefix cache is invalidated (its K/V was computed
        with the old weights) and the pool must come up empty."""
        with self._cond:
            if not self._draining or self._queue or self._busy:
                raise RuntimeError(
                    f"replica {self.name}: drain before swap_params")
        if self.cache is not None:
            self.cache.clear()
        deadline = time.perf_counter() + timeout
        while self.pool.used_pages and time.perf_counter() < deadline:
            time.sleep(0.01)
        if self.pool.used_pages:
            raise RuntimeError(
                f"replica {self.name}: {self.pool.used_pages} pages "
                "still live after drain — cannot swap params")
        self.params = new_params

    def publish_adapter(self, adapter_id: str, weights: Dict) -> None:
        """Rolling-upgrade arm for ONE adapter: register-or-replace it
        on a DRAINED replica (the in-flight guard is the pool's own
        ``AdapterInUseError``).  The prefix cache is cleared — cached
        K/V under the old adapter version is content-stale."""
        with self._cond:
            if not self._draining or self._queue or self._busy:
                raise RuntimeError(
                    f"replica {self.name}: drain before publish_adapter")
        if self.adapter_pool is None:
            raise ValueError(
                f"replica {self.name} has no adapter_pool")
        self.adapter_pool.publish(adapter_id, weights)
        if self.cache is not None:
            self.cache.clear()

    def retire_adapter(self, adapter_id: str) -> None:
        """Drop one adapter from a DRAINED replica; its namespace's
        cached prefixes go with it."""
        with self._cond:
            if not self._draining or self._queue or self._busy:
                raise RuntimeError(
                    f"replica {self.name}: drain before retire_adapter")
        if self.adapter_pool is None:
            raise ValueError(
                f"replica {self.name} has no adapter_pool")
        self.adapter_pool.retire(adapter_id)
        if self.cache is not None:
            self.cache.clear()

    def _take_locked(self) -> List:
        """Build one co-admitted group that conservatively fits the
        pool (the head request is always taken: the cache's pressure
        reclaimer may still make room, and an impossible request must
        fail loudly rather than deadlock the queue)."""
        group: List = []
        free = self.pool.free_pages
        while self._queue and len(group) < self.max_batch:
            req, fut = self._queue[0]
            need = KVCachePool.pages_needed(
                len(req.prompt), self.pool.page_size)
            if group and need > free:
                break
            self._queue.pop(0)
            group.append((req, fut))
            free -= need
        return group

    def _process(self, group: List) -> None:
        jobs: List[_Job] = []
        try:
            self._prefill_jobs(group, jobs)
        except BaseException:
            # a mid-group raise (pool exhaustion under pressure, a
            # model-step failure) costs this batch, never the pool:
            # quarantined jobs freed their pages and left the list,
            # exported jobs were freed and popped — release whatever
            # is still allocated BEFORE the worker's handler fails the
            # futures, or the pages leak forever and swap_params can
            # never see an empty pool again
            for j in jobs:
                self.pool.free_seq(j.seq_id)
                if self.cache is not None:
                    self.cache.forget_seq(j.seq_id)
                self._release_adapter(j)
            raise

    def _release_adapter(self, j: _Job) -> None:
        if j.aslot and self.adapter_pool is not None:
            self.adapter_pool.release(j.req.adapter_id)
            j.aslot = 0

    def _adapter_args(self, sel: Sequence[_Job]):
        """(adapters, adapter_slots) for one step group — (None, None)
        when every row is base model, so the no-tenant path stays the
        pre-adapter arithmetic exactly."""
        if self.adapter_pool is None or not any(j.aslot for j in sel):
            return None, None
        return (self.adapter_pool.device_arrays(),
                [j.aslot for j in sel])

    def _prefill_jobs(self, group: List, jobs: List[_Job]) -> None:
        for req, fut in group:
            aid = getattr(req, "adapter_id", None)
            aslot = 0
            if aid is not None:
                # acquire BEFORE any page is claimed: an adapter that
                # went corrupt/unloadable since submit rejects typed
                # with zero pool footprint
                try:
                    aslot = self.adapter_pool.acquire(aid)
                except AdapterError as err:
                    if fut.set_running_or_notify_cancel():
                        fut.set_exception(err)
                    continue
            seq_id = self._next_seq
            self._next_seq += 1
            self.pool.allocate(seq_id)
            matched = 0
            if self.cache is not None:
                m = self.cache.match(req.prompt, adapter_id=aid)
                matched = self.cache.attach(seq_id, m)
            jobs.append(_Job(req, fut, seq_id, pos=matched,
                             matched=matched, aslot=aslot))

        def quarantine(sel: Sequence[_Job], logits, step_idx: int):
            """Evict non-finite rows through the shared blast radius
            (prefill_sched.evict_nonfinite — the monolithic loop runs
            the SAME code, so the split cannot drift); failing the
            job's future typed is this replica's own bookkeeping."""

            def on_evict(i: int, err: BaseException, _now: float) -> None:
                j = sel[i]
                self.quarantined += 1
                jobs.remove(j)
                self._release_adapter(j)
                if j.fut.set_running_or_notify_cancel():
                    j.fut.set_exception(err)

            logits, finite, _ = _psched.evict_nonfinite(
                self.pool, self.cache, [j.seq_id for j in sel],
                [j.matched for j in sel], logits, step_idx, on_evict)
            return logits, finite

        # whole-prompt fast path for uncached prompts with no chunk
        # cap; chunk steps for cache-hit tails and capped prompts —
        # the monolithic loop's exact split, so logits match it
        whole = [j for j in jobs
                 if _psched.whole_eligible(j.pos, self._chunk)]
        if whole:
            step_idx = self.steps
            ad, asl = self._adapter_args(whole)
            logits = prefill_step(
                self.params, self.cfg, self.pool,
                [j.seq_id for j in whole],
                [list(j.req.prompt) for j in whole],
                adapters=ad, adapter_slots=asl)
            self.steps += 1
            logits, finite = quarantine(whole, logits, step_idx)
            for i, j in enumerate(whole):
                if finite[i]:
                    j.pos = len(j.req.prompt)
                    j.row = np.asarray(logits[i])
        while True:
            sel = [j for j in jobs if j.pos < len(j.req.prompt)]
            if not sel:
                break
            idx, chunks, starts = _psched.plan_chunks(
                [j.req.prompt for j in sel], [j.pos for j in sel],
                self._chunk)
            use = [sel[i] for i in idx]
            step_idx = self.steps
            ad, asl = self._adapter_args(use)
            logits = chunk_prefill_step(
                self.params, self.cfg, self.pool,
                [j.seq_id for j in use], chunks, starts,
                adapters=ad, adapter_slots=asl)
            self.steps += 1
            logits, finite = quarantine(use, logits, step_idx)
            for i, j in enumerate(use):
                if not finite[i]:
                    continue
                j.pos += len(chunks[i])
                if j.pos >= len(j.req.prompt):
                    j.row = np.asarray(logits[i])

        while jobs:  # pop as exported: a raise frees only the rest
            j = jobs[0]
            aid = getattr(j.req, "adapter_id", None)
            if self.cache is not None:
                self.cache.insert(j.seq_id, j.req.prompt,
                                  adapter_id=aid)
            tok = _choose_first(j.req, j.row)
            dest = res = None
            if self.plan_handoff is not None:
                plan = self.plan_handoff(j.req)
                if plan is not None:
                    dest, res = plan
            skip = res.tokens if res is not None else 0
            payload = self.pool.export_seq(j.seq_id, skip_tokens=skip,
                                           adapter_id=aid)
            self.pool.free_seq(j.seq_id)
            self._release_adapter(j)
            jobs.pop(0)
            hd = Handoff(j.req, tok, j.row, payload, reservation=res,
                         src=self.name, dest=dest)
            self.prefills += 1
            self._processed += 1
            self.exported_bytes += payload.nbytes()
            self.skipped_tokens += skip
            if j.fut.set_running_or_notify_cancel():
                j.fut.set_result(hd)


class DecodeReplica(FleetReplica):
    """Continuous-batching decode only; consumes Handoffs."""

    role = "decode"

    def __init__(self, name: str, params: Dict, cfg: DecodeConfig,
                 num_pages: int = 64, page_size: int = 8,
                 dtype: str = "float32", max_batch: int = 4,
                 prefix_cache: bool = True,
                 paged_impl: Optional[str] = None, check_every: int = 0,
                 speculate: Optional[int] = None, queue_cap: int = 256,
                 beat_every_s: float = 0.05, adapter_pool=None):
        self.cfg = cfg
        self.adapter_pool = adapter_pool
        self.pool = KVCachePool(
            num_pages, page_size, cfg.n_layer, cfg.n_head, cfg.head_dim,
            dtype=dtype, name=f"{name}-pool",
            num_kv_heads=cfg.num_kv_heads)
        self.cache = PrefixCache(self.pool) if prefix_cache else None
        # outstanding transfer reservations, registered as an external
        # owner so a mid-transfer invariant audit stays green
        self._reserved: Dict[int, PrefixReservation] = {}
        self.pool.register_owner(self._reservation_holds)
        self.loop = ContinuousBatchingLoop(
            params, cfg, self.pool, max_batch=max_batch,
            paged_impl=paged_impl, prefix_cache=self.cache,
            check_every=check_every,
            speculate=0 if speculate is None else speculate,
            adapter_pool=adapter_pool)
        self.decoded = 0
        super().__init__(name, max_batch=max_batch, queue_cap=queue_cap,
                         beat_every_s=beat_every_s)

    @property
    def params(self) -> Dict:
        return self.loop.params

    def _reservation_holds(self) -> Dict[int, int]:
        holds: Dict[int, int] = {}
        for r in list(self._reserved.values()):
            for p in r.pages:
                holds[p] = holds.get(p, 0) + 1
        return holds

    def reserve_prefix(self, prompt, adapter_id: Optional[str] = None
                       ) -> Optional[PrefixReservation]:
        """Pin the longest FULL-page cached prefix of `prompt` for an
        incoming transfer: the matched pages gain one refcount hold
        each, so LRU eviction cannot invalidate them between the
        export decision and the import.  None when nothing usable is
        cached (the payload then ships everything).  The match runs in
        `adapter_id`'s namespace — cached K/V is variant-specific."""
        if self.cache is None or not self._alive or self._draining:
            return None
        with self.pool._lock:
            m = self.cache.match(prompt, adapter_id=adapter_id)
            full = m.tokens - m.tokens % self.pool.page_size
            if not full:
                return None
            n = full // self.pool.page_size
            pages, keys = list(m.pages[:n]), list(m.keys[:n])
            self.pool.retain_pages(pages)
            res = PrefixReservation(keys=keys, pages=pages, tokens=full)
            res._registry = self._reserved
            res._owner_pool = self.pool  # lets a broker holding this
            # handle release it without knowing which replica pinned it
            self._reserved[id(res)] = res
        return res

    def submit(self, hd: Handoff) -> Future:
        """Enqueue one handoff; the Future resolves to the finished
        GeneratedSequence.  Whole-pool fit is validated HERE so one
        impossible request never fails a co-decoded batch."""
        req = hd.request
        need = KVCachePool.pages_needed(
            len(req.prompt) + req.max_new_tokens - hd.matched_tokens,
            self.pool.page_size)
        if need > self.pool.num_pages:
            raise ValueError(
                f"request needs {need} pages worst-case but replica "
                f"{self.name}'s pool has {self.pool.num_pages}")
        aid = getattr(req, "adapter_id", None)
        if aid is not None:
            if self.adapter_pool is None:
                raise ValueError(
                    f"handoff wants adapter {aid!r} but replica "
                    f"{self.name} has no adapter_pool")
            if not self.adapter_pool.loadable(aid):
                raise AdapterNotRegisteredError(
                    f"adapter {aid!r} is not loadable on replica "
                    f"{self.name} — register/publish it first")
        return self._submit_item(hd)

    def swap_params(self, new_params: Dict,
                    timeout: float = 5.0) -> None:
        """Rolling-upgrade arm: replace the weights of a DRAINED
        replica.  The prefix cache is invalidated and the pool must
        come up empty (in-flight transfer reservations get `timeout`
        to fail over and release)."""
        with self._cond:
            if not self._draining or self._queue or self._busy:
                raise RuntimeError(
                    f"replica {self.name}: drain before swap_params")
        if self.cache is not None:
            self.cache.clear()
        deadline = time.perf_counter() + timeout
        while self.pool.used_pages and time.perf_counter() < deadline:
            time.sleep(0.01)
        if self.pool.used_pages:
            raise RuntimeError(
                f"replica {self.name}: {self.pool.used_pages} pages "
                "still live after drain — cannot swap params")
        self.loop.params = new_params

    def publish_adapter(self, adapter_id: str, weights: Dict) -> None:
        """Rolling-upgrade arm for ONE adapter (see PrefillReplica)."""
        with self._cond:
            if not self._draining or self._queue or self._busy:
                raise RuntimeError(
                    f"replica {self.name}: drain before publish_adapter")
        if self.adapter_pool is None:
            raise ValueError(
                f"replica {self.name} has no adapter_pool")
        self.adapter_pool.publish(adapter_id, weights)
        if self.cache is not None:
            self.cache.clear()

    def retire_adapter(self, adapter_id: str) -> None:
        """Drop one adapter from a DRAINED replica."""
        with self._cond:
            if not self._draining or self._queue or self._busy:
                raise RuntimeError(
                    f"replica {self.name}: drain before retire_adapter")
        if self.adapter_pool is None:
            raise ValueError(
                f"replica {self.name} has no adapter_pool")
        self.adapter_pool.retire(adapter_id)
        if self.cache is not None:
            self.cache.clear()

    def _take_locked(self) -> List:
        # the loop's own admission controller handles batching; hand it
        # a generous slice so continuous batching keeps occupancy high
        n = min(len(self._queue), max(4 * self.max_batch, 16))
        batch, self._queue = self._queue[:n], self._queue[n:]
        return batch

    def _process(self, batch: List) -> None:
        reqs = []
        for hd, _ in batch:
            r = hd.request
            reqs.append(DecodeRequest(
                prompt=list(r.prompt),
                max_new_tokens=r.max_new_tokens, trace_id=r.trace_id,
                sampling=r.sampling, handoff=hd,
                adapter_id=getattr(r, "adapter_id", None)))
        results = self.loop.run(reqs)
        for (hd, fut), res in zip(batch, results):
            self.decoded += 1
            self._processed += 1
            if fut.set_running_or_notify_cancel():
                fut.set_result(res)

    def _cleanup_item(self, hd) -> None:
        # a killed/failed handoff's transfer reservation must not pin
        # cache pages forever
        try:
            hd.release(self.pool)
        except Exception:  # noqa: BLE001 — cleanup is best-effort
            pass
