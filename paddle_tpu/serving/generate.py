"""Continuous-batching autoregressive decode over the paged KV cache.

Serving an autoregressive transformer one request at a time recomputes
full-sequence attention every token (O(S^2) per generated token) and —
worse for TPU throughput — runs at batch 1.  This module fixes both:

- **KV caching**: each generated token's per-layer K/V lands in the
  KVCachePool (kvcache.py); decode attention is one Sq=1 query against
  the cached keys through kernels/paged_attention.py, which routes to
  the existing flash_attention ragged ``k_lengths`` tier.
- **Continuous batching**: the loop keeps up to ``max_batch`` sequences
  in flight and admits a waiting sequence the moment a finished one
  retires (its pages return to the free pool) — batch occupancy stays
  high across mixed-length workloads instead of draining to 1 while the
  longest straggler finishes (the occupancy-dominates-throughput result
  of arxiv 2605.25645).

The model is the decoder half of models/transformer.py as a jax-level
step function: post-norm residual blocks (LayerNorm(x + sublayer(x)),
matching _Builder.sublayer), scaled embedding + sinusoid positions
(matching _Builder.embed; the table is literally
models.transformer._sinusoid_table), tied input/output embeddings, no
cross-attention.  Every step feeds ONE token per active sequence —
prefill is token-by-token through the same path (a batched prefill pass
is a follow-up; it changes arithmetic order, so the parity oracle would
need its own batched reference).

``full_decode`` is the correctness oracle: per-sequence greedy decode
that recomputes the whole prefix each token with ordinary causal
attention and no cache.  tests/test_serving.py holds the paged loop to
it within fp32 tolerance.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import flags as _flags
from ..kernels.flash_attention import _reference_attention
from ..kernels.paged_attention import paged_decode_attention
from ..models.transformer import _sinusoid_table
from . import metrics as _smetrics
from .kvcache import KVCachePool

__all__ = [
    "DecodeConfig",
    "DecodeRequest",
    "GeneratedSequence",
    "ContinuousBatchingLoop",
    "init_decode_params",
    "full_forward",
    "full_decode",
]


@dataclasses.dataclass
class DecodeConfig:
    """Decoder-only slice of models.transformer.TransformerConfig."""

    vocab_size: int = 128
    d_model: int = 32
    n_head: int = 4
    n_layer: int = 2
    d_inner: int = 64
    max_length: int = 96
    eos_id: Optional[int] = None  # None: sequences retire on max_new only

    @property
    def head_dim(self) -> int:
        if self.d_model % self.n_head:
            raise ValueError("d_model must divide by n_head")
        return self.d_model // self.n_head


def init_decode_params(cfg: DecodeConfig, seed: int = 0) -> Dict:
    """Deterministic fp32 params; weights at 1/sqrt(fan_in) scale."""
    rng = np.random.RandomState(seed)

    def mat(d_in, d_out):
        return (rng.standard_normal((d_in, d_out)) / np.sqrt(d_in)).astype(
            np.float32)

    d, f = cfg.d_model, cfg.d_inner
    layers = []
    for _ in range(cfg.n_layer):
        layers.append({
            "wq": mat(d, d), "wk": mat(d, d), "wv": mat(d, d),
            "wo": mat(d, d),
            "ln1_g": np.ones(d, np.float32), "ln1_b": np.zeros(d, np.float32),
            "w1": mat(d, f), "b1": np.zeros(f, np.float32),
            "w2": mat(f, d), "b2": np.zeros(d, np.float32),
            "ln2_g": np.ones(d, np.float32), "ln2_b": np.zeros(d, np.float32),
        })
    return {
        "embed": (rng.standard_normal((cfg.vocab_size, d)) / np.sqrt(d)
                  ).astype(np.float32),
        "pos": _sinusoid_table(cfg.max_length, d),
        "layers": layers,
    }


def _layernorm(x, g, b, eps: float = 1e-5):
    import jax.numpy as jnp

    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * g + b


def full_forward(params: Dict, cfg: DecodeConfig, tokens) -> np.ndarray:
    """Oracle forward: full-sequence causal attention, no cache.
    tokens [S] int -> logits [S, V]."""
    import jax.numpy as jnp

    tokens = np.asarray(tokens, np.int32)
    S = tokens.shape[0]
    if S > cfg.max_length:
        raise ValueError(f"sequence length {S} > max_length {cfg.max_length}")
    d, H, Dh = cfg.d_model, cfg.n_head, cfg.head_dim
    h = jnp.asarray(params["embed"])[tokens] * np.sqrt(d) \
        + jnp.asarray(params["pos"])[:S]
    for lp in params["layers"]:
        q = (h @ lp["wq"]).reshape(S, H, Dh).transpose(1, 0, 2)[None]
        k = (h @ lp["wk"]).reshape(S, H, Dh).transpose(1, 0, 2)[None]
        v = (h @ lp["wv"]).reshape(S, H, Dh).transpose(1, 0, 2)[None]
        attn = _reference_attention(q, k, v, causal=True, scale=Dh ** -0.5)
        attn = attn[0].transpose(1, 0, 2).reshape(S, d)
        h = _layernorm(h + attn @ lp["wo"], lp["ln1_g"], lp["ln1_b"])
        ff = jnp.maximum(h @ lp["w1"] + lp["b1"], 0.0) @ lp["w2"] + lp["b2"]
        h = _layernorm(h + ff, lp["ln2_g"], lp["ln2_b"])
    return np.asarray(h @ jnp.asarray(params["embed"]).T)


def full_decode(params: Dict, cfg: DecodeConfig, prompt: Sequence[int],
                max_new_tokens: int) -> Tuple[List[int], List[np.ndarray]]:
    """Greedy per-sequence decode, recomputing the full prefix each token
    (the O(S^2)-per-token baseline the paged path must match).  Returns
    (generated tokens, the [V] logits row behind each of them)."""
    tokens = [int(t) for t in prompt]
    out: List[int] = []
    rows: List[np.ndarray] = []
    for _ in range(max_new_tokens):
        row = full_forward(params, cfg, tokens)[-1]
        nxt = int(row.argmax())
        rows.append(row)
        out.append(nxt)
        tokens.append(nxt)
        if cfg.eos_id is not None and nxt == cfg.eos_id:
            break
    return out, rows


def decode_step(params: Dict, cfg: DecodeConfig, pool: KVCachePool,
                seq_ids: Sequence[int], tokens, positions,
                force: str = "auto") -> np.ndarray:
    """One continuous-batching step: feed token[i] at position[i] for
    every active sequence, append its K/V to the pool, and return the
    next-token logits [B, V].  All sequences share the batch regardless
    of phase — a prefilling sequence and a deep-decode sequence differ
    only in k_lengths."""
    import jax.numpy as jnp

    tokens = np.asarray(tokens, np.int32)
    positions = np.asarray(positions, np.int32)
    B = tokens.shape[0]
    d, H, Dh = cfg.d_model, cfg.n_head, cfg.head_dim
    h = jnp.asarray(params["embed"])[tokens] * np.sqrt(d) \
        + jnp.asarray(params["pos"])[positions]
    pages, slots = pool.append_token(seq_ids)
    tables, lengths = pool.page_table_batch(seq_ids)
    for li, lp in enumerate(params["layers"]):
        q = (h @ lp["wq"]).reshape(B, H, Dh)
        k = (h @ lp["wk"]).reshape(B, H, Dh)
        v = (h @ lp["wv"]).reshape(B, H, Dh)
        pool.write_kv(li, pages, slots, k, v)
        attn = paged_decode_attention(
            q[:, :, None, :], pool.k_pages[li], pool.v_pages[li],
            tables, lengths, scale=Dh ** -0.5, force=force,
        )  # [B, H, 1, Dh]
        attn = attn[:, :, 0, :].reshape(B, d)
        h = _layernorm(h + attn @ lp["wo"], lp["ln1_g"], lp["ln1_b"])
        ff = jnp.maximum(h @ lp["w1"] + lp["b1"], 0.0) @ lp["w2"] + lp["b2"]
        h = _layernorm(h + ff, lp["ln2_g"], lp["ln2_b"])
    return np.asarray(h @ jnp.asarray(params["embed"]).T)


@dataclasses.dataclass
class DecodeRequest:
    prompt: Sequence[int]
    max_new_tokens: int


@dataclasses.dataclass
class GeneratedSequence:
    """One finished sequence: generated tokens + the logits row behind
    each (the parity surface vs full_decode), and latency accounting."""

    seq_id: int
    prompt: List[int]
    tokens: List[int] = dataclasses.field(default_factory=list)
    logits: List[np.ndarray] = dataclasses.field(default_factory=list)
    admitted_at: float = 0.0
    ttft_s: Optional[float] = None
    finished_at: float = 0.0


class _Active:
    __slots__ = ("req", "seq_id", "pos", "result")

    def __init__(self, req: DecodeRequest, seq_id: int, result: GeneratedSequence):
        self.req = req
        self.seq_id = seq_id
        self.pos = 0  # next position to feed
        self.result = result


class ContinuousBatchingLoop:
    """Admit-as-they-retire greedy decode over one KVCachePool.

    Admission control is reservation-based: a request is admitted only
    when the pool can cover EVERY admitted sequence's worst-case
    footprint (ceil((len(prompt)+max_new)/page_size) pages), so
    append_token can never raise mid-decode — a sequence, once admitted,
    always runs to completion.  Waiting requests admit in FIFO order the
    moment retirements free enough pages."""

    def __init__(self, params: Dict, cfg: DecodeConfig, pool: KVCachePool,
                 max_batch: int = 4, force: str = "auto"):
        self.params = params
        self.cfg = cfg
        self.pool = pool
        self.max_batch = int(max_batch)
        self.force = force
        self._next_seq_id = 0
        self.steps = 0
        self._occupancy_sum = 0.0

    def _footprint(self, req: DecodeRequest) -> int:
        total = len(req.prompt) + req.max_new_tokens
        if total > self.cfg.max_length:
            raise ValueError(
                f"prompt+max_new={total} exceeds max_length "
                f"{self.cfg.max_length}")
        return KVCachePool.pages_needed(total, self.pool.page_size)

    def run(self, requests: Sequence[DecodeRequest]) -> List[GeneratedSequence]:
        obs_on = _flags._VALUES["FLAGS_observability"]
        waiting: List[Tuple[DecodeRequest, GeneratedSequence]] = []
        results: List[GeneratedSequence] = []
        for req in requests:
            if not len(req.prompt):
                raise ValueError("empty prompt")
            # validate EVERY request (max_length AND whole-pool fit)
            # before any work: a mid-run raise would strand allocated
            # pages and throw away already-finished sequences' results
            need = self._footprint(req)
            if need > self.pool.num_pages:
                from .kvcache import PagePoolExhausted

                raise PagePoolExhausted(
                    f"request needs {need} pages worst-case but the pool "
                    f"has {self.pool.num_pages} total")
            seq = GeneratedSequence(seq_id=-1, prompt=[int(t) for t in req.prompt])
            results.append(seq)
            waiting.append((req, seq))
        active: List[_Active] = []
        reserved_pages = 0

        while waiting or active:
            # admit (FIFO) while a slot and a full worst-case reservation fit
            while waiting and len(active) < self.max_batch:
                req, seq = waiting[0]
                need = self._footprint(req)
                if reserved_pages + need > self.pool.num_pages:
                    break  # wait for retirements
                waiting.pop(0)
                seq.seq_id = self._next_seq_id
                self._next_seq_id += 1
                self.pool.allocate(seq.seq_id)
                seq.admitted_at = time.perf_counter()
                active.append(_Active(req, seq.seq_id, seq))
                reserved_pages += need
                if obs_on:
                    _smetrics.record_sequence("admitted")
            # NOTE: waiting-but-nothing-active cannot happen — the
            # up-front validation guarantees the head request fits an
            # empty pool, so admission always progresses

            # one token per active sequence (mixed prefill/decode batch)
            t0 = time.perf_counter()
            seq_ids = [a.seq_id for a in active]
            tokens = [
                (a.result.prompt[a.pos] if a.pos < len(a.result.prompt)
                 else a.result.tokens[-1])
                for a in active
            ]
            positions = [a.pos for a in active]
            logits = decode_step(
                self.params, self.cfg, self.pool, seq_ids, tokens,
                positions, force=self.force)
            self.steps += 1
            self._occupancy_sum += len(active) / float(self.max_batch)
            now = time.perf_counter()

            retired: List[_Active] = []
            for i, a in enumerate(active):
                a.pos += 1
                if a.pos < len(a.result.prompt):
                    continue  # still prefilling; logits unused
                row = np.asarray(logits[i])
                nxt = int(row.argmax())
                a.result.tokens.append(nxt)
                a.result.logits.append(row)
                if a.result.ttft_s is None:
                    a.result.ttft_s = now - a.result.admitted_at
                    if obs_on:
                        _smetrics.record_ttft(a.result.ttft_s)
                if obs_on:
                    _smetrics.record_token(now - t0)
                done = (len(a.result.tokens) >= a.req.max_new_tokens
                        or (self.cfg.eos_id is not None
                            and nxt == self.cfg.eos_id))
                if done:
                    retired.append(a)
            for a in retired:
                active.remove(a)
                a.result.finished_at = now
                self.pool.free_seq(a.seq_id)
                reserved_pages -= self._footprint(a.req)
                if obs_on:
                    _smetrics.record_sequence("retired")
        return results

    def mean_occupancy(self) -> float:
        return self._occupancy_sum / self.steps if self.steps else 0.0
