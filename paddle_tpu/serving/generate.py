"""Continuous-batching autoregressive decode over the paged KV cache.

Serving an autoregressive transformer one request at a time recomputes
full-sequence attention every token (O(S^2) per generated token) and —
worse for TPU throughput — runs at batch 1.  This module fixes both:

- **KV caching**: each generated token's per-layer K/V lands in the
  KVCachePool (kvcache.py); decode attention is one Sq=1 query against
  the cached keys through kernels/paged_attention.py —
  FLAGS_serving_paged_impl (or the loop's ``paged_impl``) selects the
  pallas ragged page-streaming kernel vs the reference gather, with the
  envelope/fallback contract documented there.
- **Batched prefill**: an admitted prompt's K/V is written by ONE
  whole-prompt causal pass (``prefill_step`` — O(1) model steps per
  prompt instead of one step per prompt token), ragged prompts padded
  to the co-admitted max and masked via the flash ``k_lengths`` tier.
  ``prefill="token"`` keeps the old token-by-token path as the A/B arm
  and parity oracle.
- **Continuous batching**: the loop keeps up to ``max_batch`` sequences
  in flight and admits a waiting sequence the moment a finished one
  retires (its pages return to the free pool) — batch occupancy stays
  high across mixed-length workloads instead of draining to 1 while the
  longest straggler finishes (the occupancy-dominates-throughput result
  of arxiv 2605.25645).

The model is the decoder half of models/transformer.py as a jax-level
step function: post-norm residual blocks (LayerNorm(x + sublayer(x)),
matching _Builder.sublayer), scaled embedding + sinusoid positions
(matching _Builder.embed; the table is literally
models.transformer._sinusoid_table), tied input/output embeddings, no
cross-attention.

``full_decode`` is the correctness oracle: per-sequence greedy decode
that recomputes the whole prefix each token with ordinary causal
attention and no cache.  tests/test_serving.py holds the paged loop to
it within fp32 tolerance — and, because batched prefill changes
arithmetic order (one padded causal pass vs Sq=1 steps), the prefill
parity suite additionally pins ``prefill_step`` to ``full_forward``
(the batched-reference oracle) and batched-vs-token generations to
token identity.

ISSUE 13 adds SPECULATIVE DECODING and the per-request SAMPLING
contract:

- ``ContinuousBatchingLoop(speculate=d)`` (default
  ``FLAGS_serving_speculate``) arms draft-model-free speculation: a
  prompt-lookup drafter (serving/speculative.py — pure host n-gram
  matching over prompt + generation history, no second model, no
  extra HBM) proposes up to ``d`` continuation tokens per generating
  sequence, and ``verify_step`` feeds the last committed token plus
  the draft block through ONE model step — Sq = 1+d ragged query rows
  per sequence through ``paged_decode_attention(q_lengths=)``, the
  page stream still reading each live KV page once.  For GREEDY rows
  acceptance is longest-prefix-match against the model's own (biased)
  argmax, so every emitted token is argmax given an exactly-correct
  prefix: greedy speculative decode is TOKEN-IDENTICAL to
  ``full_decode`` by construction, and the existing oracle keeps
  pinning correctness.  Rejected draft tokens roll back as pure host
  bookkeeping — ``KVCachePool.truncate_seq`` shrinks the page table
  atomically (refcount/CoW-aware, int8 scales cleared with freed
  pages) — which continuous batching already tolerates as ragged
  per-sequence progress.  EOS / stop sequences / max_new are checked
  after EVERY emitted token, so a stop landing inside an accepted
  draft block retires the sequence at that position with the surplus
  fed tokens truncated from the page table.
- ``DecodeRequest.sampling`` (serving/sampling.py SamplingParams)
  widens the decode contract: temperature/top-k/top-p through ONE
  jitted sampling epilogue per step, logit bias (greedy included),
  stop sequences, per-request max_new.

ISSUE 16 makes speculation distribution-exact and UNIVERSAL:

- SAMPLED (temp>0) rows draft too.  Their verify outcome goes through
  the exact accept/resample epilogue (``sampling.spec_sample_rows``,
  one fused jitted call for every drafted sampled row of the batch):
  draft token t accepts with probability ``min(1, p_target(t) /
  p_draft(t))`` — the target probability itself under the
  prompt-lookup drafter's point-mass proposal — and a rejection
  resamples the residual ``max(0, p_target - p_draft)`` renormalized,
  so emitted tokens are DISTRIBUTION-IDENTICAL to unspeculated
  sampling while the (seed, token-index)-keyed Gumbel stream stays
  replayable (bonus/no-draft draws use the plain epilogue's unsalted
  key, so a never-drafting sequence keeps its old stream byte for
  byte).  Per-row accepted counts come back from the same fused call
  — no per-sequence host sync.
- SPMD programs speculate.  A program exposing ``verify_step(pool,
  seq_ids, blocks, start_positions, pad_to=)`` (e.g.
  ``serving.distributed.ShardedDecodeProgram``) runs the multi-token
  verify under its own mesh; only a custom program WITHOUT one
  degrades the loop to d=0 — surfaced as a
  ``paddle_tpu_serving_spec_disabled_total{reason=}`` counter and a
  flight event, never just a log line.
- The default drafter rides the prefix cache's trie as a shared
  CORPUS (``PromptLookupDrafter(corpus=prefix_cache)``):
  shared-prefix fleet traffic drafts from continuations other
  sequences already decoded, with per-request fallback to
  own-history matching.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import flags as _flags
from ..kernels.flash_attention import (
    NEG_INF,
    _reference_attention,
    flash_attention,
)
from ..kernels.paged_attention import (
    PAD_START,
    attention_bytes_per_step,
    gather_kv_pages,
    paged_decode_attention,
    repeat_kv,
    resolve_paged_impl,
)
from ..observability import flight as _flight
from ..observability import requesttrace as _rtrace
from ..models.transformer import _sinusoid_table
from . import metrics as _smetrics
from . import prefill_sched as _psched
from .adapters import AdapterError
from .kvcache import KVCachePool
from .sampling import (
    SamplingParams,
    apply_bias,
    sample_rows,
    spec_sample_rows,
    stop_hit,
)
from .speculative import PromptLookupDrafter

_log = logging.getLogger("paddle_tpu.serving")

__all__ = [
    "DecodeConfig",
    "DecodeRequest",
    "GeneratedSequence",
    "ContinuousBatchingLoop",
    "NonFiniteSequenceError",
    "init_decode_params",
    "full_forward",
    "full_decode",
    "window_mask",
    "prefill_step",
    "chunk_prefill_step",
    "verify_step",
]


class NonFiniteSequenceError(RuntimeError):
    """One sequence's decode logits went non-finite: that sequence was
    QUARANTINED — evicted from the continuous batch, its pages returned
    to the pool — while its batch-mates decode on.  The batch-granular
    counterpart of resilience.NonFiniteStepError: a poisoned sequence
    costs one request, never the batch (and never the engine)."""

    def __init__(self, seq_id: int, step: int):
        self.seq_id = seq_id
        self.step = step
        super().__init__(
            f"sequence {seq_id} produced non-finite logits at loop step "
            f"{step}; it was evicted from the batch (pages freed) and "
            "its batch-mates decoded on")

    def __reduce__(self):
        # default Exception pickling replays args=(message,), which does
        # not match this two-arg __init__; the process fleet ships these
        # across sockets inside GeneratedSequence.error
        return (type(self), (self.seq_id, self.step))


@dataclasses.dataclass
class DecodeConfig:
    """Decoder-only slice of models.transformer.TransformerConfig.

    ``n_kv_head`` (None: n_head — classic MHA) enables grouped-query /
    multi-query attention: K/V project to n_kv_head heads, the KV pool
    stores and streams H_q/H_kv x less, and query head h reads KV head
    ``h // (n_head/n_kv_head)``."""

    vocab_size: int = 128
    d_model: int = 32
    n_head: int = 4
    n_layer: int = 2
    d_inner: int = 64
    max_length: int = 96
    eos_id: Optional[int] = None  # None: sequences retire on max_new only
    n_kv_head: Optional[int] = None  # None: n_head (no grouping)

    @property
    def head_dim(self) -> int:
        if self.d_model % self.n_head:
            raise ValueError("d_model must divide by n_head")
        return self.d_model // self.n_head

    @property
    def num_kv_heads(self) -> int:
        h_kv = self.n_kv_head if self.n_kv_head is not None else self.n_head
        from ..kernels.paged_attention import _group_size

        _group_size(self.n_head, h_kv)  # typed GroupedHeadsError raise
        return h_kv

    @property
    def group_size(self) -> int:
        """Query heads per KV head (1 without grouping)."""
        return self.n_head // self.num_kv_heads


def init_decode_params(cfg: DecodeConfig, seed: int = 0) -> Dict:
    """Deterministic fp32 params; weights at 1/sqrt(fan_in) scale."""
    rng = np.random.RandomState(seed)

    def mat(d_in, d_out):
        return (rng.standard_normal((d_in, d_out)) / np.sqrt(d_in)).astype(
            np.float32)

    d, f = cfg.d_model, cfg.d_inner
    d_kv = cfg.num_kv_heads * cfg.head_dim  # K/V project to H_kv heads
    layers = []
    for _ in range(cfg.n_layer):
        layers.append({
            "wq": mat(d, d), "wk": mat(d, d_kv), "wv": mat(d, d_kv),
            "wo": mat(d, d),
            "ln1_g": np.ones(d, np.float32), "ln1_b": np.zeros(d, np.float32),
            "w1": mat(d, f), "b1": np.zeros(f, np.float32),
            "w2": mat(f, d), "b2": np.zeros(d, np.float32),
            "ln2_g": np.ones(d, np.float32), "ln2_b": np.zeros(d, np.float32),
        })
    return {
        "embed": (rng.standard_normal((cfg.vocab_size, d)) / np.sqrt(d)
                  ).astype(np.float32),
        "pos": _sinusoid_table(cfg.max_length, d),
        "layers": layers,
    }


def _layernorm(x, g, b, eps: float = 1e-5):
    import jax.numpy as jnp

    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * g + b


def full_forward(params: Dict, cfg: DecodeConfig, tokens,
                 mask=None) -> np.ndarray:
    """Oracle forward: full-sequence causal attention, no cache.
    tokens [S] int -> logits [S, V].  ``mask`` (optional [S, S] bool,
    query x key) REPLACES the causal mask — the windowed-decode oracle
    passes ``window_mask`` so sliding-window + attention-sink parity
    checks against dense arithmetic, not against another paged path."""
    import jax.numpy as jnp

    tokens = np.asarray(tokens, np.int32)
    S = tokens.shape[0]
    if S > cfg.max_length:
        raise ValueError(f"sequence length {S} > max_length {cfg.max_length}")
    d, H, Dh = cfg.d_model, cfg.n_head, cfg.head_dim
    Hkv, G = cfg.num_kv_heads, cfg.group_size
    if mask is not None:
        mask = jnp.asarray(np.asarray(mask, bool))[None, None]  # [1,1,S,S]
    h = jnp.asarray(params["embed"])[tokens] * np.sqrt(d) \
        + jnp.asarray(params["pos"])[:S]
    for lp in params["layers"]:
        q = (h @ lp["wq"]).reshape(S, H, Dh).transpose(1, 0, 2)[None]
        k = (h @ lp["wk"]).reshape(S, Hkv, Dh).transpose(1, 0, 2)[None]
        v = (h @ lp["wv"]).reshape(S, Hkv, Dh).transpose(1, 0, 2)[None]
        k, v = repeat_kv(k, v, G)  # GQA: query head h reads KV head h//G
        if mask is None:
            attn = _reference_attention(q, k, v, causal=True,
                                        scale=Dh ** -0.5)
        else:
            import jax

            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (Dh ** -0.5)
            scores = jnp.where(mask, scores, NEG_INF)
            attn = jnp.einsum("bhqk,bhkd->bhqd",
                              jax.nn.softmax(scores, axis=-1), v)
        attn = attn[0].transpose(1, 0, 2).reshape(S, d)
        h = _layernorm(h + attn @ lp["wo"], lp["ln1_g"], lp["ln1_b"])
        ff = jnp.maximum(h @ lp["w1"] + lp["b1"], 0.0) @ lp["w2"] + lp["b2"]
        h = _layernorm(h + ff, lp["ln2_g"], lp["ln2_b"])
    return np.asarray(h @ jnp.asarray(params["embed"]).T)


def window_mask(S: int, prompt_len: int, window: int, sinks: int,
                page_size: int) -> np.ndarray:
    """The [S, S] query x key visibility the long-context serving path
    implements (ISSUE 20) — THE contract shared by the kernel's
    per-page mask, the pool's eviction rule, and the oracle:

    - prompt queries (position < prompt_len) attend fully causal:
      window/sinks shape DECODE attention only, so prefill K/V content
      is identical to the unwindowed model's;
    - a decode query at position p sees key j iff ``j <= p`` AND j's
      PAGE is a sink page (``(j // page_size) * page_size < sinks``) or
      overlaps the trailing window
      (``page_start + page_size > p + 1 - window``).

    Page-granular on purpose: the paged kernel decides visibility per
    page start (one scalar compare per DMA'd page), and the pool drops
    exactly the pages this mask can never light again — which is what
    makes windowed paged decode token-identical to ``full_decode`` of
    the same mask rather than merely close."""
    if window < 1:
        raise ValueError(f"window must be >= 1 token, got {window}")
    j = np.arange(S)
    p = np.arange(S)[:, None]
    page_start = (j // page_size) * page_size
    vis = (j[None, :] <= p) & (
        (p < prompt_len)
        | (page_start[None, :] < sinks)
        | (page_start[None, :] + page_size > p + 1 - window))
    return vis


def full_decode(params: Dict, cfg: DecodeConfig, prompt: Sequence[int],
                max_new_tokens: int, window: Optional[int] = None,
                sinks: int = 0, page_size: int = 1,
                ) -> Tuple[List[int], List[np.ndarray]]:
    """Greedy per-sequence decode, recomputing the full prefix each token
    (the O(S^2)-per-token baseline the paged path must match).  Returns
    (generated tokens, the [V] logits row behind each of them).
    ``window``/``sinks``/``page_size`` (ISSUE 20) apply the
    page-granular sliding-window + attention-sink decode mask — the
    oracle the windowed paged loop must be token-identical to."""
    tokens = [int(t) for t in prompt]
    out: List[int] = []
    rows: List[np.ndarray] = []
    for _ in range(max_new_tokens):
        mask = (window_mask(len(tokens), len(prompt), window, sinks,
                            page_size)
                if window is not None else None)
        row = full_forward(params, cfg, tokens, mask=mask)[-1]
        nxt = int(row.argmax())
        rows.append(row)
        out.append(nxt)
        tokens.append(nxt)
        if cfg.eos_id is not None and nxt == cfg.eos_id:
            break
    return out, rows


def _apply_adapters(y, x, name, li, adapters, slots):
    """Per-row batched-LoRA delta (ISSUE 19): add each row's
    ``(x @ A) @ B`` for projection `name` at layer `li`, gathering the
    row's A/B from the packed pool arrays by its adapter slot — the
    same scalar-prefetch page-table idiom as paged attention, so ONE
    step mixes tenants.  Slot 0 is the pool's permanent all-zero
    identity: base-model rows ride the same einsum and add exact fp32
    zeros (no masking, no divergent compile shape).  ``adapters=None``
    is the guaranteed zero-cost path — today's code byte for byte."""
    if adapters is None:
        return y
    import jax.numpy as jnp

    A, B = adapters[name]
    Al = A[slots, li]  # [B, d_in, r] per-row gather
    Bl = B[slots, li]  # [B, r, d_out]
    if x.ndim == 2:
        return y + jnp.einsum("br,bro->bo",
                              jnp.einsum("bd,bdr->br", x, Al), Bl)
    return y + jnp.einsum("bsr,bro->bso",
                          jnp.einsum("bsd,bdr->bsr", x, Al), Bl)


def _adapter_slot_array(adapters, adapter_slots):
    """Validate + stage the per-row slot vector for one step call."""
    if adapters is None:
        return None
    import jax.numpy as jnp

    if adapter_slots is None:
        raise ValueError("adapters without adapter_slots")
    return jnp.asarray(np.asarray(adapter_slots, np.int32))


def _step_tables(pool: KVCachePool, seq_ids: Sequence[int],
                 windows, sinks, table_block: Optional[int]):
    """One step's page-table view + windowing operands (ISSUE 20).
    Returns ``(tables, lengths, kw)`` where ``tables`` is a flat
    [B, max_pages] array or a TwoLevelTables and ``kw`` is the extra
    kwargs dict for ``paged_decode_attention``.  Flat tables ship
    explicit per-page starts whenever a row is windowed OR any table
    was evicted (implicit ``i * page_size`` positions stop being true
    then); a TwoLevelTables always carries its starts."""
    windowed = windows is not None
    kw = {}
    if windowed:
        kw["windows"] = np.asarray(windows, np.int32)
        kw["sinks"] = (np.asarray(sinks, np.int32)
                       if sinks is not None
                       else np.zeros(len(seq_ids), np.int32))
    if table_block:
        tables, lengths = pool.two_level_tables(seq_ids, table_block)
    elif windowed:
        tables, starts, lengths = pool.page_tables_with_starts(seq_ids)
        kw["page_starts"] = starts
    else:
        tables, lengths = pool.page_table_batch(seq_ids)
    return tables, lengths, kw


def decode_step(params: Dict, cfg: DecodeConfig, pool: KVCachePool,
                seq_ids: Sequence[int], tokens, positions,
                force: str = "auto", impl: Optional[str] = None,
                adapters=None, adapter_slots=None,
                windows=None, sinks=None,
                table_block: Optional[int] = None) -> np.ndarray:
    """One continuous-batching step: feed token[i] at position[i] for
    every active sequence, append its K/V to the pool, and return the
    next-token logits [B, V].  All sequences share the batch regardless
    of phase — a prefilling sequence and a deep-decode sequence differ
    only in k_lengths.  `impl` selects the paged-attention path (None:
    FLAGS_serving_paged_impl).  ``adapters``/``adapter_slots`` (an
    AdapterPool's ``device_arrays()`` + row i's slot index) apply each
    row's low-rank tenant deltas per projection — None is the base
    model, unchanged.  ``windows``/``sinks`` ([B] int arrays; a
    non-windowed row passes ``PAD_START``/0) apply the per-row
    sliding-window + attention-sink decode mask; ``table_block`` routes
    the page tables through the two-level SMEM layout (ISSUE 20)."""
    import jax.numpy as jnp

    tokens = np.asarray(tokens, np.int32)
    positions = np.asarray(positions, np.int32)
    B = tokens.shape[0]
    d, H, Dh = cfg.d_model, cfg.n_head, cfg.head_dim
    Hkv = cfg.num_kv_heads
    aslots = _adapter_slot_array(adapters, adapter_slots)
    h = jnp.asarray(params["embed"])[tokens] * np.sqrt(d) \
        + jnp.asarray(params["pos"])[positions]
    pages, slots = pool.append_token(seq_ids)
    tables, lengths, wkw = _step_tables(pool, seq_ids, windows, sinks,
                                        table_block)
    for li, lp in enumerate(params["layers"]):
        q = _apply_adapters(h @ lp["wq"], h, "wq", li, adapters,
                            aslots).reshape(B, H, Dh)
        k = _apply_adapters(h @ lp["wk"], h, "wk", li, adapters,
                            aslots).reshape(B, Hkv, Dh)
        v = _apply_adapters(h @ lp["wv"], h, "wv", li, adapters,
                            aslots).reshape(B, Hkv, Dh)
        pool.write_kv(li, pages, slots, k, v)
        k_scales, v_scales = pool.layer_scales(li)
        attn = paged_decode_attention(
            q[:, :, None, :], pool.k_pages[li], pool.v_pages[li],
            tables, lengths, scale=Dh ** -0.5, impl=impl, force=force,
            k_scales=k_scales, v_scales=v_scales, **wkw,
        )  # [B, H, 1, Dh]
        attn = attn[:, :, 0, :].reshape(B, d)
        h = _layernorm(h + _apply_adapters(attn @ lp["wo"], attn, "wo",
                                           li, adapters, aslots),
                       lp["ln1_g"], lp["ln1_b"])
        u = jnp.maximum(_apply_adapters(h @ lp["w1"], h, "w1", li,
                                        adapters, aslots) + lp["b1"],
                        0.0)
        ff = _apply_adapters(u @ lp["w2"], u, "w2", li, adapters,
                             aslots) + lp["b2"]
        h = _layernorm(h + ff, lp["ln2_g"], lp["ln2_b"])
    return np.asarray(h @ jnp.asarray(params["embed"]).T)


def prefill_step(params: Dict, cfg: DecodeConfig, pool: KVCachePool,
                 seq_ids: Sequence[int], prompts: Sequence[Sequence[int]],
                 force: str = "auto", adapters=None,
                 adapter_slots=None) -> np.ndarray:
    """Batched whole-prompt prefill: ONE causal pass over every prompt
    (ragged lengths padded to the co-admitted max, masked through the
    flash ``k_lengths`` tier) writes each prompt token's per-layer K/V
    into the pool and returns the next-token logits [B, V] after each
    prompt — the logits token-by-token prefill would only reach after
    len(prompt) model steps.  Padded rows compute garbage that is never
    read: attention masks them as keys, their K/V is never written
    (only the claimed (page, slot)s are), and the returned row is
    gathered at each sequence's true last position."""
    import jax.numpy as jnp

    lens = np.asarray([len(p) for p in prompts], np.int32)
    if not len(lens) or lens.min() < 1:
        raise ValueError("prefill needs non-empty prompts")
    B, Smax = len(prompts), int(lens.max())
    if Smax > cfg.max_length:
        # before append_tokens: a failed prefill must not leave claimed
        # slots with no K/V behind (the pool's atomicity contract)
        raise ValueError(
            f"prompt length {Smax} > max_length {cfg.max_length}")
    d, H, Dh = cfg.d_model, cfg.n_head, cfg.head_dim
    Hkv, G = cfg.num_kv_heads, cfg.group_size
    tokens = np.zeros((B, Smax), np.int32)
    for i, p in enumerate(prompts):
        tokens[i, :lens[i]] = p
    # flat (sequence order, token order) claim — matches append_tokens
    pages, slots = pool.append_tokens(seq_ids, lens)
    b_idx = np.repeat(np.arange(B), lens)
    t_idx = np.concatenate([np.arange(n) for n in lens])
    aslots = _adapter_slot_array(adapters, adapter_slots)

    h = jnp.asarray(params["embed"])[tokens] * np.sqrt(d) \
        + jnp.asarray(params["pos"])[None, :Smax]  # [B, Smax, d]
    for li, lp in enumerate(params["layers"]):
        q = _apply_adapters(h @ lp["wq"], h, "wq", li, adapters,
                            aslots).reshape(B, Smax, H, Dh)
        k = _apply_adapters(h @ lp["wk"], h, "wk", li, adapters,
                            aslots).reshape(B, Smax, Hkv, Dh)
        v = _apply_adapters(h @ lp["wv"], h, "wv", li, adapters,
                            aslots).reshape(B, Smax, Hkv, Dh)
        # valid tokens only ([T, H_kv, Dh] rows in claim order) reach
        # the pool (an int8 pool quantizes them on the way in)
        pool.write_kv(li, pages, slots, k[b_idx, t_idx], v[b_idx, t_idx])
        kh, vh = repeat_kv(k.transpose(0, 2, 1, 3),
                           v.transpose(0, 2, 1, 3), G)
        attn = flash_attention(
            q.transpose(0, 2, 1, 3), kh, vh, causal=True,
            scale=Dh ** -0.5, k_lengths=lens, force=force)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, Smax, d)
        h = _layernorm(h + _apply_adapters(attn @ lp["wo"], attn, "wo",
                                           li, adapters, aslots),
                       lp["ln1_g"], lp["ln1_b"])
        u = jnp.maximum(_apply_adapters(h @ lp["w1"], h, "w1", li,
                                        adapters, aslots) + lp["b1"],
                        0.0)
        ff = _apply_adapters(u @ lp["w2"], u, "w2", li, adapters,
                             aslots) + lp["b2"]
        h = _layernorm(h + ff, lp["ln2_g"], lp["ln2_b"])
    h_last = h[jnp.arange(B), lens - 1]  # [B, d] true last positions
    return np.asarray(h_last @ jnp.asarray(params["embed"]).T)


def chunk_prefill_step(params: Dict, cfg: DecodeConfig, pool: KVCachePool,
                       seq_ids: Sequence[int],
                       chunks: Sequence[Sequence[int]],
                       start_positions: Sequence[int],
                       adapters=None, adapter_slots=None) -> np.ndarray:
    """Suffix/chunk prefill: process ``chunks[i]`` consecutive prompt
    tokens for sequence i starting at absolute position
    ``start_positions[i]`` — which need NOT be 0.  The chunk's queries
    attend over everything the sequence's page table already holds (a
    prefix-cache-attached shared prefix, earlier chunks of a long
    prompt) PLUS the chunk itself causally, so prefill can resume
    mid-prompt: the prefix-cache hit path pays model compute only for
    the unshared tail, and the chunked-prefill scheduler splits a long
    prompt across engine steps.

    The chunk's per-layer K/V lands in the pool through the same atomic
    ``append_tokens`` claim as every other write — a shared
    partially-filled tail page copy-on-writes right there.  Attention
    is the explicit reference tier: gather the sequence's pages and
    mask by absolute position (key j visible to query at position p
    iff j <= p — cached prefix fully visible, in-chunk causal, padding
    and unwritten slots masked).  A pallas chunk kernel is future work;
    decode steps keep the paged impl selection.

    Returns the logits [B, V] at each sequence's LAST chunk token —
    meaningful only for chunks that complete their prompt."""
    import jax
    import jax.numpy as jnp

    lens = np.asarray([len(c) for c in chunks], np.int32)
    if not len(lens) or lens.min() < 1:
        raise ValueError("chunk prefill needs non-empty chunks")
    starts = np.asarray(start_positions, np.int32)
    B, Cmax = len(chunks), int(lens.max())
    if int((starts + lens).max()) > cfg.max_length:
        # before append_tokens: a failed chunk must not leave claimed
        # slots with no K/V behind (the pool's atomicity contract)
        raise ValueError(
            f"chunk reaches position {int((starts + lens).max())} > "
            f"max_length {cfg.max_length}")
    d, H, Dh = cfg.d_model, cfg.n_head, cfg.head_dim
    Hkv, G = cfg.num_kv_heads, cfg.group_size
    tokens = np.zeros((B, Cmax), np.int32)
    for i, c in enumerate(chunks):
        tokens[i, :lens[i]] = c
    for s in seq_ids:
        if getattr(pool._tables[s], "starts", None) is not None:
            # the gather below places key j at implicit position j —
            # an evicted (compacted) table's pages no longer sit there,
            # so the mask would light the wrong keys silently
            raise ValueError(
                f"sequence {s} is window-evicted — chunk prefill over "
                "a compacted page table is unsupported (windows shape "
                "decode only; prefill before evicting)")
    pages, slots = pool.append_tokens(seq_ids, lens)
    tables, _total = pool.page_table_batch(seq_ids)
    b_idx = np.repeat(np.arange(B), lens)
    t_idx = np.concatenate([np.arange(n) for n in lens])
    S = tables.shape[1] * pool.page_size
    pos = starts[:, None] + np.arange(Cmax)[None, :]  # absolute positions
    pos_c = np.minimum(pos, cfg.max_length - 1)  # padded rows: clamp only
    # key j visible to query (b, i) iff j <= pos[b, i]; the jnp.where
    # also neutralizes NaN scores from masked garbage (padding pages)
    mask = jnp.asarray(np.arange(S)[None, None, :] <= pos[:, :, None])
    aslots = _adapter_slot_array(adapters, adapter_slots)
    h = jnp.asarray(params["embed"])[tokens] * np.sqrt(d) \
        + jnp.asarray(params["pos"])[pos_c]  # [B, Cmax, d]
    scale = Dh ** -0.5
    for li, lp in enumerate(params["layers"]):
        q = _apply_adapters(h @ lp["wq"], h, "wq", li, adapters,
                            aslots).reshape(B, Cmax, H, Dh)
        k = _apply_adapters(h @ lp["wk"], h, "wk", li, adapters,
                            aslots).reshape(B, Cmax, Hkv, Dh)
        v = _apply_adapters(h @ lp["wv"], h, "wv", li, adapters,
                            aslots).reshape(B, Cmax, Hkv, Dh)
        pool.write_kv(li, pages, slots, k[b_idx, t_idx], v[b_idx, t_idx])
        k_scales, v_scales = pool.layer_scales(li)
        k_full = gather_kv_pages(pool.k_pages[li], tables,
                                 scales=k_scales)  # [B, H_kv, S, Dh]
        v_full = gather_kv_pages(pool.v_pages[li], tables,
                                 scales=v_scales)
        k_full, v_full = repeat_kv(k_full, v_full, G)
        scores = jnp.einsum("bihd,bhjd->bhij", q, k_full) * scale
        scores = jnp.where(mask[:, None], scores, NEG_INF)
        w = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhij,bhjd->bihd", w, v_full).reshape(B, Cmax, d)
        h = _layernorm(h + _apply_adapters(attn @ lp["wo"], attn, "wo",
                                           li, adapters, aslots),
                       lp["ln1_g"], lp["ln1_b"])
        u = jnp.maximum(_apply_adapters(h @ lp["w1"], h, "w1", li,
                                        adapters, aslots) + lp["b1"],
                        0.0)
        ff = _apply_adapters(u @ lp["w2"], u, "w2", li, adapters,
                             aslots) + lp["b2"]
        h = _layernorm(h + ff, lp["ln2_g"], lp["ln2_b"])
    h_last = h[jnp.arange(B), lens - 1]  # [B, d] true last chunk tokens
    return np.asarray(h_last @ jnp.asarray(params["embed"]).T)


def verify_step(params: Dict, cfg: DecodeConfig, pool: KVCachePool,
                seq_ids: Sequence[int], blocks: Sequence[Sequence[int]],
                start_positions: Sequence[int], force: str = "auto",
                impl: Optional[str] = None,
                pad_to: Optional[int] = None,
                adapters=None, adapter_slots=None,
                windows=None, sinks=None,
                table_block: Optional[int] = None) -> np.ndarray:
    """One speculative verify step: sequence i feeds ``blocks[i]`` —
    its last committed token plus d_i drafted continuations — starting
    at absolute position ``start_positions[i]``, appends every fed
    token's per-layer K/V to the pool (ONE atomic ``append_tokens``
    claim), and returns the logits [B, Sq_max, V] at every fed
    position: row t predicts the token at position start+t+1, which is
    exactly what draft token t+1 claims to be.  Ragged draft depths
    ride the ``q_lengths`` arm of ``paged_decode_attention`` — the KV
    page stream is the SAME as a single-token step's (each live page
    reads once per sequence), which is the amortization speculation
    banks.  Rows past ``len(blocks[i])`` are padding garbage the
    caller must ignore.  A block of length 1 is exactly ``decode_step``
    for that sequence, so mixed draft/no-draft batches share the step.

    The caller owns acceptance and ROLLBACK: rejected tokens' K/V
    stays claimed until ``pool.truncate_seq`` undoes it (the loop does
    both in the same scheduler turn)."""
    import jax.numpy as jnp

    lens = np.asarray([len(b) for b in blocks], np.int32)
    if not len(lens) or lens.min() < 1:
        raise ValueError("verify needs >= 1 fed token per sequence")
    starts = np.asarray(start_positions, np.int32)
    # pad_to pins the query width to one static shape (the loop passes
    # speculate+1) so the jitted finite scan and the memoized pallas
    # kernel compile ONCE per batch size instead of once per distinct
    # ragged draft mix — the padded rows are q_lengths-masked garbage
    # either way
    B, Sqm = len(blocks), int(lens.max())
    if pad_to is not None:
        if pad_to < Sqm:
            raise ValueError(f"pad_to {pad_to} < longest block {Sqm}")
        Sqm = int(pad_to)
    if int((starts + lens).max()) > cfg.max_length:
        # before append_tokens: a failed verify must not leave claimed
        # slots with no K/V behind (the pool's atomicity contract)
        raise ValueError(
            f"verify block reaches position {int((starts + lens).max())} "
            f"> max_length {cfg.max_length}")
    d, H, Dh = cfg.d_model, cfg.n_head, cfg.head_dim
    Hkv = cfg.num_kv_heads
    tokens = np.zeros((B, Sqm), np.int32)
    for i, b in enumerate(blocks):
        tokens[i, :lens[i]] = b
    pages, slots = pool.append_tokens(seq_ids, lens)
    tables, lengths, wkw = _step_tables(pool, seq_ids, windows, sinks,
                                        table_block)
    if not table_block and tables.shape[1] % 8:
        # bucket the table width to multiples of 8 pages: decode compile
        # shapes change once per 8 pages of growth instead of every
        # page, so the verify kernels reach steady state quickly (the
        # padded entries are dummy page-0 walks fully masked by
        # ``lengths`` — the existing zero-padded-table contract).  A
        # two-level table buckets at block granularity already, and its
        # explicit-starts arm pads with PAD_START (the position mask
        # kills the dummy walks when implicit positions no longer hold)
        padded = -(-tables.shape[1] // 8) * 8
        grow = padded - tables.shape[1]
        tables = np.pad(tables, ((0, 0), (0, grow)))
        if "page_starts" in wkw:
            wkw["page_starts"] = np.pad(
                wkw["page_starts"], ((0, 0), (0, grow)),
                constant_values=PAD_START)
    b_idx = np.repeat(np.arange(B), lens)
    t_idx = np.concatenate([np.arange(n) for n in lens])
    # stable-shape writes: pad the scatter to B*Sqm rows by REPEATING
    # the last claimed (page, slot) and its row — duplicate scatter
    # indices carrying identical values are a no-op, and the fixed row
    # count means the write kernels compile once per (B, Sqm) instead
    # of once per distinct ragged draft mix
    T = len(b_idx)
    pad_rows = B * Sqm - T
    if pad_rows:
        b_idx = np.concatenate([b_idx, np.full(pad_rows, b_idx[-1])])
        t_idx = np.concatenate([t_idx, np.full(pad_rows, t_idx[-1])])
        pages = np.concatenate([pages, np.full(pad_rows, pages[-1],
                                                pages.dtype)])
        slots = np.concatenate([slots, np.full(pad_rows, slots[-1],
                                                slots.dtype)])
    pos = starts[:, None] + np.arange(Sqm)[None, :]
    pos_c = np.minimum(pos, cfg.max_length - 1)  # padded rows: clamp only
    aslots = _adapter_slot_array(adapters, adapter_slots)
    h = jnp.asarray(params["embed"])[tokens] * np.sqrt(d) \
        + jnp.asarray(params["pos"])[pos_c]  # [B, Sqm, d]
    for li, lp in enumerate(params["layers"]):
        q = _apply_adapters(h @ lp["wq"], h, "wq", li, adapters,
                            aslots).reshape(B, Sqm, H, Dh)
        k = _apply_adapters(h @ lp["wk"], h, "wk", li, adapters,
                            aslots).reshape(B, Sqm, Hkv, Dh)
        v = _apply_adapters(h @ lp["wv"], h, "wv", li, adapters,
                            aslots).reshape(B, Sqm, Hkv, Dh)
        # valid rows (plus the identical-value padding) in claim order
        pool.write_kv(li, pages, slots, k[b_idx, t_idx], v[b_idx, t_idx])
        k_scales, v_scales = pool.layer_scales(li)
        attn = paged_decode_attention(
            q.transpose(0, 2, 1, 3), pool.k_pages[li], pool.v_pages[li],
            tables, lengths, scale=Dh ** -0.5, impl=impl, force=force,
            k_scales=k_scales, v_scales=v_scales, q_lengths=lens, **wkw,
        )  # [B, H, Sqm, Dh]
        attn = attn.transpose(0, 2, 1, 3).reshape(B, Sqm, d)
        h = _layernorm(h + _apply_adapters(attn @ lp["wo"], attn, "wo",
                                           li, adapters, aslots),
                       lp["ln1_g"], lp["ln1_b"])
        u = jnp.maximum(_apply_adapters(h @ lp["w1"], h, "w1", li,
                                        adapters, aslots) + lp["b1"],
                        0.0)
        ff = _apply_adapters(u @ lp["w2"], u, "w2", li, adapters,
                             aslots) + lp["b2"]
        h = _layernorm(h + ff, lp["ln2_g"], lp["ln2_b"])
    return np.asarray(h @ jnp.asarray(params["embed"]).T)  # [B, Sqm, V]


@dataclasses.dataclass
class DecodeRequest:
    prompt: Sequence[int]
    max_new_tokens: int
    # carried through from Engine.submit when the decode loop fronts an
    # engine; None (the default) mints a fresh id at run() when
    # FLAGS_observability is on
    trace_id: Optional[str] = None
    # per-request sampling contract (serving/sampling.py) — None is
    # exact greedy, the full_decode-oracle arm; non-greedy params
    # auto-disable speculation for THIS sequence only
    sampling: Optional[SamplingParams] = None
    # disaggregated serving (serving/fleet): a prefilled-elsewhere
    # payload.  The carrier must expose ``matched_tokens`` (prefix
    # tokens the destination re-attaches from its own cache),
    # ``admit(pool, prefix_cache, seq_id)`` (attach + import the
    # shipped pages), and ``first_token``/``first_logits`` (the token
    # the prefill side already chose and the row behind it).  The loop
    # then skips prefill entirely: admission imports the pages, emits
    # the first token, and the sequence decodes like any other
    handoff: Optional[object] = None
    # tiered KV cache (serving/kvtier): the multi-turn session this
    # request continues.  When the loop carries a session_manager,
    # admission asks it to resume the session's retained KV (resident
    # in the pool, or parked in the host tier) and retirement keeps the
    # sequence's pages resident for the next turn instead of freeing
    # them.  None (the default) is the ordinary one-shot request
    session: Optional[object] = None
    # multi-tenant serving (serving/adapters): the model VARIANT this
    # request decodes under.  The loop acquires it from its
    # AdapterPool at admission (an unloadable/corrupt adapter rejects
    # typed BEFORE any KV page is claimed) and every step applies the
    # variant's low-rank deltas to just this request's rows.  None
    # (the default) is the base model — the guaranteed zero-cost path
    adapter_id: Optional[str] = None
    # long-context serving (ISSUE 20): sliding-window decode attention.
    # A decode query sees the last `window` tokens (page-granular: any
    # page overlapping the window) plus the first `sinks` tokens' pages
    # (attention sinks); prefill stays full attention.  The loop evicts
    # pages the mask can never light again before each decode step, so
    # a 128k-context sequence's per-step KV traffic and page residency
    # are bounded by window + sinks, not context length.  None (the
    # default) is full attention — exactly today's path.  Output is
    # token-identical to full_decode under the SAME window_mask.
    window: Optional[int] = None
    sinks: int = 0


@dataclasses.dataclass
class GeneratedSequence:
    """One finished sequence: generated tokens + the logits row behind
    each (the parity surface vs full_decode), and latency accounting.
    `error` is set (NonFiniteSequenceError) when the sequence was
    quarantined instead of retiring cleanly — its tokens/logits stop at
    the last finite step."""

    seq_id: int
    prompt: List[int]
    tokens: List[int] = dataclasses.field(default_factory=list)
    logits: List[np.ndarray] = dataclasses.field(default_factory=list)
    admitted_at: float = 0.0
    ttft_s: Optional[float] = None
    finished_at: float = 0.0
    error: Optional[Exception] = None
    # request trace id (None when FLAGS_observability was off): the join
    # key into the merged trace, metric exemplars, and flight events
    trace_id: Optional[str] = None


class _Active:
    __slots__ = ("req", "seq_id", "pos", "result", "rt", "matched",
                 "charged", "whole", "chunk_mode", "inserted",
                 "drafted", "accepted", "aslot", "spec_source")

    def __init__(self, req: DecodeRequest, seq_id: int,
                 result: GeneratedSequence, rt=None):
        self.req = req
        self.seq_id = seq_id
        self.pos = 0  # next position to feed
        self.result = result
        self.rt = rt  # RequestTrace (None with observability off)
        self.matched = 0   # prompt tokens served from the prefix cache
        self.charged = 0   # pages this admission reserved (prefix-aware)
        self.whole = False       # whole-prompt prefill_step at admission
        self.chunk_mode = False  # tail/capped prefill via chunk steps
        self.inserted = False    # prompt pages offered to the cache
        self.drafted = 0   # speculative tokens proposed for this seq
        self.accepted = 0  # ... of which the verifier accepted
        self.aslot = 0     # adapter device slot (0 = base-model identity)
        self.spec_source = "own"  # n-gram source of the LAST proposal


class ContinuousBatchingLoop:
    """Admit-as-they-retire greedy decode over one KVCachePool.

    Admission control is reservation-based: a request is admitted only
    when the pool can cover EVERY admitted sequence's worst-case
    footprint (ceil((len(prompt)+max_new)/page_size) pages), so
    append_token can never raise mid-decode — a sequence, once admitted,
    always runs to completion.  Waiting requests admit in FIFO order the
    moment retirements free enough pages.

    ``prefill="batched"`` (default) runs each co-admitted group's
    prompts through ONE whole-prompt ``prefill_step`` — prefill model
    steps per admission group are O(1) instead of O(max prompt len),
    counted separately in ``prefill_steps``/``decode_steps``.
    ``prefill="token"`` is the original token-by-token arm (the parity
    oracle and A/B baseline).  ``paged_impl`` selects the decode
    attention path (None: FLAGS_serving_paged_impl; resolved against
    the pool geometry once, so metrics are labeled with the impl that
    actually runs).

    ``prefix_cache`` (a serving.PrefixCache over the same pool) turns
    shared-prefix prompts into page reuse: admission matches the
    longest cached prefix, attaches its pages read-only (refcount++,
    charged ZERO fresh pages for matched full pages), and prefill
    covers only the unshared tail via ``chunk_prefill_step`` (the
    token arm and SPMD programs resume at the matched position
    instead).  Completed prefills insert their prompt pages back into
    the cache; retirement frees only refcount-zero pages; a
    quarantined hit invalidates its cached chain.  ``prefill_chunk``
    (None: FLAGS_serving_prefill_chunk; 0 = uncapped) bounds the
    PREFILL tokens any single engine step may process, and the
    scheduler alternates chunk and decode steps when both kinds of
    work exist — long prompts stop stalling in-flight sequences'
    per-token latency.  Counters: ``prefix_hits``/``prefix_misses``,
    ``cached_prefill_tokens``, ``prefill_tokens``,
    ``max_prefill_tokens_step``.

    Fault isolation: every step's logits pass a per-ROW jitted
    finite-check (resilience.sentinel.rows_finite — ONE fused jit call
    per step, no per-sequence host sync); a non-finite row QUARANTINES
    only that sequence (its result carries NonFiniteSequenceError, its
    pages return to the pool) while batch-mates decode on.  Any
    exception escaping a prefill/decode step frees every stepping
    sequence's pages before propagating — a raise can cost the run,
    never pool pages.  ``check_every=N`` additionally audits the pool
    (KVCachePool.check_invariants) every N steps and repairs detected
    leaks via reclaim_orphans."""

    def __init__(self, params: Dict, cfg: DecodeConfig, pool: KVCachePool,
                 max_batch: int = 4, force: str = "auto",
                 paged_impl: Optional[str] = None,
                 prefill: str = "batched", check_every: int = 0,
                 program=None, prefix_cache=None,
                 prefill_chunk: Optional[int] = None,
                 speculate: Optional[int] = None, drafter=None,
                 session_manager=None, adapter_pool=None,
                 table_block: Optional[int] = None,
                 prefill_flops: Optional[float] = None):
        if prefill not in ("batched", "token"):
            raise ValueError(
                f"prefill must be 'batched' or 'token', got {prefill!r}")
        if prefix_cache is not None and prefix_cache.pool is not pool:
            raise ValueError(
                "prefix_cache is wired to a different pool — shared "
                "pages and refcounts must live in the pool this loop "
                "appends to")
        if session_manager is not None:
            if session_manager.pool is not pool:
                raise ValueError(
                    "session_manager is wired to a different pool — "
                    "sessions spill from and resume into the pool this "
                    "loop appends to")
            if session_manager.cache is not None \
                    and session_manager.cache is not prefix_cache:
                raise ValueError(
                    "session_manager carries a different prefix cache "
                    "than the loop — spill-time pins and resume-time "
                    "attaches must agree on one trie")
        if adapter_pool is not None and program is not None:
            raise ValueError(
                "SPMD program loops do not support adapter_pool — the "
                "per-row adapter gather lives in this module's step "
                "functions, not in custom programs (yet)")
        self.params = params
        self.cfg = cfg if cfg is not None else getattr(program, "cfg", None)
        if self.cfg is None:
            raise ValueError("pass cfg (or a program that carries one)")
        if getattr(pool, "num_kv_heads", None) not in (
                None, self.cfg.num_kv_heads):
            raise ValueError(
                f"pool holds {pool.num_kv_heads} KV heads but the model "
                f"projects {self.cfg.num_kv_heads} (cfg.n_kv_head) — a "
                "mismatched pool would scatter K/V across wrong heads")
        self.pool = pool
        self.max_batch = int(max_batch)
        self.force = force
        self.prefill = prefill
        self.check_every = int(check_every)
        # program: an object exposing decode_step(pool, seq_ids, tokens,
        # positions) and prefill_step(pool, seq_ids, prompts) — e.g.
        # serving.distributed.ShardedDecodeProgram.  The loop's
        # admission / quarantine / retirement / watchdog machinery is
        # step-implementation-agnostic, so the SPMD program rides it
        # unchanged; None keeps this module's single-device math.
        self.program = program
        if program is not None:
            self.paged_impl = program.resolve_impl(pool)
        else:
            self.paged_impl = resolve_paged_impl(
                paged_impl, pool.page_size, self.cfg.head_dim,
                pool.k_pages.dtype)
        self.prefix_cache = prefix_cache
        # tiered KV cache (serving/kvtier.TieredSessionManager):
        # requests carrying a .session resume retained KV at admission
        # and keep their pages resident at retirement
        self.session_manager = session_manager
        # multi-tenant adapters (serving/adapters.AdapterPool):
        # requests carrying an adapter_id acquire their variant at
        # admission and decode through per-row low-rank deltas
        self.adapter_pool = adapter_pool
        # prefill-token cap per engine step (0 = uncapped); None reads
        # FLAGS_serving_prefill_chunk
        self._prefill_chunk = int(
            prefill_chunk if prefill_chunk is not None
            else _flags._VALUES["FLAGS_serving_prefill_chunk"])
        if self._prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0")
        # compute-budgeted chunked prefill (ISSUE 20): bound each chunk
        # step's ESTIMATED ATTENTION WORK (token·resident-position
        # units — prefill_sched.plan_chunks) instead of / on top of its
        # token count, so a 100-token chunk at a 100k-token resident
        # prefix stops costing 1000x a cold one under the same cap.
        # None keeps the pure token budget
        self._prefill_flops = (float(prefill_flops)
                               if prefill_flops is not None else None)
        if self._prefill_flops is not None and self._prefill_flops <= 0:
            raise ValueError("prefill_flops must be > 0 (or None)")
        if self._prefill_flops is not None and not self._prefill_chunk:
            # the FLOP budget rides the chunk-step scheduler; without a
            # token cap, whole-prompt prefill bypasses plan_chunks
            # entirely and the budget would silently never apply
            raise ValueError(
                "prefill_flops needs chunked prefill — also pass a "
                "nonzero prefill_chunk (it still clamps tokens; the "
                "FLOP budget binds where it is tighter)")
        # two-level page tables (ISSUE 20): route decode/verify steps'
        # scalar-prefetch tables through the [B, ceil(P/block)] L1 +
        # per-block L2 layout, bounding SMEM by LIVE table blocks.
        # None keeps flat tables — mandatory for SPMD programs (their
        # step functions own their table plumbing)
        self._table_block = int(table_block) if table_block else None
        if table_block is not None and int(table_block) < 1:
            raise ValueError("table_block must be >= 1 (or None)")
        if self._table_block and program is not None:
            raise ValueError(
                "table_block is not supported with a custom program — "
                "the program's decode_step owns its page-table layout")
        # speculative decoding (ISSUE 13/16): d draft tokens per
        # generating sequence per step, verified in one multi-token
        # model step.  None reads FLAGS_serving_speculate; 0 disables.
        # Program-driven (SPMD) loops speculate through the program's
        # own verify_step; only a custom program WITHOUT one degrades
        # to d=0 — surfaced as a spec_disabled counter + flight event
        # so a fleet where speculation quietly stopped paying stays
        # diagnosable (ISSUE 16 bugfix: this used to be a log line)
        self._speculate = int(
            speculate if speculate is not None
            else _flags._VALUES["FLAGS_serving_speculate"])
        if self._speculate < 0:
            raise ValueError("speculate must be >= 0")
        if self._speculate and program is not None \
                and not hasattr(program, "verify_step"):
            _log.info(
                "program %s exposes no verify_step — speculative "
                "decoding degrades to d=0 for this loop",
                type(program).__name__)
            if _flags._VALUES["FLAGS_observability"]:
                _smetrics.record_spec_disabled("program_no_verify")
                _flight.default_flight().record(
                    "spec_disabled", reason="program_no_verify",
                    program=type(program).__name__)
            self._speculate = 0
        self.drafter = drafter if drafter is not None else (
            PromptLookupDrafter(
                max_draft=self._speculate,
                corpus=(prefix_cache if hasattr(
                    prefix_cache, "ngram_continuation") else None))
            if self._speculate else None)
        self._next_seq_id = 0
        self.steps = 0
        self.prefill_steps = 0
        self.decode_steps = 0
        self.quarantined = 0
        self.reclaimed_pages = 0
        self.invariant_violations = 0
        self._occupancy_sum = 0.0
        # prefix-cache / chunked-prefill accounting (serve_bench banks
        # hit rate + cached tokens; tests counter-assert the chunk cap)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.cached_prefill_tokens = 0
        self.prefill_tokens = 0
        self.max_prefill_tokens_step = 0
        self._prefer_prefill = True
        # speculation accounting (serve_bench banks acceptance_rate and
        # tokens/step; traces/flight carry the per-sequence split)
        self.spec_steps = 0
        self.drafted_tokens = 0
        self.accepted_tokens = 0
        self.rolled_back_tokens = 0
        # tiered-session accounting (serve_bench banks the resume hit
        # rate of the multi-turn workload off these)
        self.session_resumes = 0
        self.session_resumed_tokens = 0
        self.session_fresh = 0
        # multi-tenant adapter accounting (serve_bench --tenants banks
        # hit rate and gather bytes/step off these + the pool's stats)
        self.adapter_rejects = 0
        self.adapter_rows = 0
        self.adapter_gather_bytes = 0.0
        # long-context accounting (ISSUE 20): window/sink eviction
        # volume, and decode-step wall times taken WHILE chunked
        # prefill work was still pending — the per-step latency hit a
        # long prefill inflicts on in-flight sequences, the number the
        # compute budget exists to bound (serve_bench banks its p99)
        self.pages_evicted = 0
        self._decode_durs_during_prefill: List[float] = []
        # widest page-table walk any decode/verify step paid (max over
        # steps of the batch's max live-page count) — post-eviction,
        # so serve_bench can price the analytic decode bytes/step a
        # windowed long context actually streams
        self.max_decode_table_pages = 0

    def decode_step_p99_during_prefill_s(self) -> float:
        """p99 decode-step wall time over steps that ran while chunked
        prefill was pending (0.0 when no such step ran)."""
        durs = self._decode_durs_during_prefill
        if not durs:
            return 0.0
        return float(np.percentile(np.asarray(durs), 99))

    def acceptance_rate(self) -> float:
        """Accepted / drafted speculative tokens (0.0 before any
        draft) — the number that decides whether speculation paid."""
        return (self.accepted_tokens / self.drafted_tokens
                if self.drafted_tokens else 0.0)

    def _max_new(self, a: "_Active") -> int:
        """Effective generation cap: the request's max_new_tokens,
        tightened by SamplingParams.max_new when present."""
        p = a.req.sampling
        if p is not None and p.max_new is not None:
            return min(a.req.max_new_tokens, p.max_new)
        return a.req.max_new_tokens

    def _spec_room(self, a: "_Active") -> int:
        """Draft tokens sequence `a` may carry THIS step: capped by the
        loop's d and by the sequence's remaining generation headroom
        (the worst-case admission reservation must still cover the
        transiently-fed block — ceil((prompt+max_new)/page_size) pages
        bound pos+1+d), and zero while the prompt still prefills.
        Sampled (temp>0) rows draft too — their verify outcome goes
        through the exact accept/resample epilogue instead of the
        greedy longest-prefix walk (ISSUE 16)."""
        if not self._speculate or a.pos < len(a.result.prompt):
            return 0
        return min(self._speculate,
                   self._max_new(a) - len(a.result.tokens))

    def _footprint(self, req: DecodeRequest, matched: int = 0) -> int:
        """Worst-case pages a request pulls from the FREE list.  With
        `matched` prompt tokens served by the prefix cache, only the
        unshared region is charged: the matched FULL pages attach
        refcounted (no free-list pressure), and the pages for
        everything past them — including the copy-on-write replacement
        of a shared partial tail page — are exactly
        ceil((total - matched_full) / page_size)."""
        total = len(req.prompt) + req.max_new_tokens
        if total > self.cfg.max_length:
            raise ValueError(
                f"prompt+max_new={total} exceeds max_length "
                f"{self.cfg.max_length}")
        matched_full = (int(matched) // self.pool.page_size) \
            * self.pool.page_size
        return KVCachePool.pages_needed(total - matched_full,
                                        self.pool.page_size)

    def run(self, requests: Sequence[DecodeRequest]) -> List[GeneratedSequence]:
        obs_on = _flags._VALUES["FLAGS_observability"]
        waiting: List[Tuple[DecodeRequest, GeneratedSequence, object]] = []
        results: List[GeneratedSequence] = []
        for req in requests:
            if not len(req.prompt):
                raise ValueError("empty prompt")
            if req.sampling is not None \
                    and req.sampling.max_bias_token() >= self.cfg.vocab_size:
                # part of the same validate-before-any-work pass: an
                # out-of-vocab bias id would IndexError mid-step and
                # cost the whole batch instead of this one request
                raise ValueError(
                    f"logit_bias token {req.sampling.max_bias_token()} "
                    f">= vocab_size {self.cfg.vocab_size}")
            if req.adapter_id is not None and self.adapter_pool is None:
                # operator config error, not a per-request one: a loop
                # with no pool can never serve ANY adapter request, so
                # fail the run up front like every other validate check
                raise ValueError(
                    f"request names adapter {req.adapter_id!r} but the "
                    "loop carries no adapter_pool")
            if req.window is not None:
                if req.window < 1:
                    raise ValueError(
                        f"window must be >= 1 token, got {req.window}")
                if self.program is not None:
                    raise ValueError(
                        "windowed decode is not supported with a "
                        "custom program — its step functions own the "
                        "attention mask")
            if req.sinks < 0:
                raise ValueError(f"sinks must be >= 0, got {req.sinks}")
            if req.sinks and req.window is None:
                raise ValueError(
                    "sinks without a window has no meaning — sink "
                    "pages are the exception to a window's eviction")
            # validate EVERY request (max_length AND whole-pool fit)
            # before any work: a mid-run raise would strand allocated
            # pages and throw away already-finished sequences' results.
            # A handoff's reserved prefix pages are refcount-pinned on
            # THIS pool, so (unlike a mere cache match, which eviction
            # could still void) they are safe to subtract here
            need = self._footprint(
                req, int(getattr(req.handoff, "matched_tokens", 0))
                if req.handoff is not None else 0)
            if need > self.pool.num_pages:
                from .kvcache import PagePoolExhausted

                raise PagePoolExhausted(
                    f"request needs {need} pages worst-case but the pool "
                    f"has {self.pool.num_pages} total")
            seq = GeneratedSequence(seq_id=-1, prompt=[int(t) for t in req.prompt])
            rt = None
            if obs_on:
                # sequence lifecycle trace: queued (here) -> admitted ->
                # prefill -> decode -> retired/quarantined
                rt = _rtrace.default_request_tracer().start(
                    name="sequence", trace_id=req.trace_id)
                seq.trace_id = rt.trace_id
            results.append(seq)
            waiting.append((req, seq, rt))
        active: List[_Active] = []
        reserved_pages = 0

        def quarantine(batch: List[_Active], logits,
                       step_idx: int) -> Tuple[np.ndarray, set, float]:
            """Evict every non-finite row of this step's logits through
            the shared blast radius (prefill_sched.evict_nonfinite:
            chaos poisoning, the ONE fused [B]-bool scan before the
            single host materialization, page scrub+free, prefix-chain
            quarantine, the quarantined-sequence metric); what is THIS
            loop's alone — batch removal, the result's error/timestamps,
            drafter release, reservation accounting, trace finish —
            rides the on_evict callback.  Returns (host logits, the
            surviving row indices, the post-sync step-end timestamp)."""
            nonlocal reserved_pages

            def on_evict(i: int, err: BaseException, now: float) -> None:
                nonlocal reserved_pages
                a = batch[i]
                active.remove(a)
                err.trace_id = a.result.trace_id
                a.result.error = err
                a.result.finished_at = now
                if getattr(self.drafter, "stateful", False):
                    self.drafter.release(a.seq_id)
                if self.session_manager is not None \
                        and a.req.session is not None:
                    # the evictor already scrubbed + freed the pool
                    # side — reset the session so its next turn
                    # prefills fresh instead of resuming poisoned KV
                    self.session_manager.on_quarantine(a.req.session)
                if a.aslot and self.adapter_pool is not None:
                    self.adapter_pool.release(a.req.adapter_id)
                reserved_pages -= a.charged
                self.quarantined += 1
                if obs_on:
                    _flight.default_flight().record(
                        "quarantine", seq_id=a.seq_id, step=step_idx,
                        trace_id=a.result.trace_id)
                    kept = False
                    if a.rt is not None:
                        # quarantined sequences are forced-keep: the
                        # poisoned request is the one worth reading
                        a.rt.annotate(tokens=len(a.result.tokens),
                                      quarantined_step=step_idx)
                        kept = _rtrace.default_request_tracer().finish(
                            a.rt, outcome="quarantined", t_end=now)
                    if a.result.ttft_s is not None:
                        _smetrics.record_ttft(
                            a.result.ttft_s,
                            trace_id=(a.result.trace_id if kept
                                      else None))

            logits, finite, now = _psched.evict_nonfinite(
                self.pool, self.prefix_cache,
                [a.seq_id for a in batch], [a.matched for a in batch],
                logits, step_idx, on_evict)
            return logits, {i for i in range(len(batch)) if finite[i]}, now

        def emit(a: _Active, row: np.ndarray, t0: float, now: float,
                 tok: Optional[int] = None) -> bool:
            """Record one generated token; True when the sequence is
            done (effective max_new, EOS, or a stop sequence — checked
            after EVERY token, so a stop emitted from inside an
            accepted draft block retires the sequence right there).
            `tok` is the already-chosen token for sampled sequences and
            the speculative walk; None takes the (bias-shifted) greedy
            argmax — exactly full_decode's choice when no bias."""
            params = a.req.sampling
            if tok is None:
                tok = int(apply_bias(row, params).argmax())
            a.result.tokens.append(tok)
            a.result.logits.append(row)
            if a.result.ttft_s is None:
                a.result.ttft_s = now - a.result.admitted_at
                if obs_on and a.rt is not None:
                    a.rt.event("sequence.prefill",
                               a.result.admitted_at, now)
            if obs_on:
                _smetrics.record_token(now - t0, impl=self.paged_impl)
            return (len(a.result.tokens) >= self._max_new(a)
                    or (self.cfg.eos_id is not None
                        and tok == self.cfg.eos_id)
                    or stop_hit(a.result.tokens, params))

        def emit_batch(pairs, t0: float, now: float) -> List[_Active]:
            """Emit one token for every (sequence, logits-row) pair —
            non-greedy rows resolved by the ONE jitted sampling
            epilogue call this step, greedy rows by host argmax (the
            oracle's arithmetic).  Returns the finished sequences."""
            toks: List[Optional[int]] = [None] * len(pairs)
            sampled = [(j, a, row) for j, (a, row) in enumerate(pairs)
                       if a.req.sampling is not None
                       and not a.req.sampling.greedy]
            if sampled:
                rows = np.stack([apply_bias(r, a.req.sampling)
                                 for _, a, r in sampled])
                chosen = sample_rows(
                    rows, [a.req.sampling for _, a, _ in sampled],
                    [len(a.result.tokens) for _, a, _ in sampled])
                for (j, _, _), tk in zip(sampled, chosen):
                    toks[j] = int(tk)
            done: List[_Active] = []
            for (a, row), tk in zip(pairs, toks):
                if emit(a, row, t0, now, tok=tk):
                    done.append(a)
            return done

        def retire(batch: List[_Active], now: float) -> None:
            nonlocal reserved_pages
            for a in batch:
                active.remove(a)
                a.result.finished_at = now
                resident = False
                if self.session_manager is not None \
                        and a.req.session is not None:
                    # tiered session: the manager adopts the retired
                    # sequence's pages (they stay resident for the
                    # next turn, spillable to the host tier under
                    # pressure) — the reservation charge still drops,
                    # the pages move into the manager-locked set the
                    # admission bound sets aside
                    resident = self.session_manager.on_retire(
                        a.req.session, a.seq_id, a.result.prompt,
                        a.result.tokens, trace_id=a.result.trace_id,
                        adapter_id=a.req.adapter_id)
                if not resident:
                    self.pool.free_seq(a.seq_id)
                if a.aslot and self.adapter_pool is not None:
                    self.adapter_pool.release(a.req.adapter_id)
                reserved_pages -= a.charged
                if self.prefix_cache is not None:
                    self.prefix_cache.forget_seq(a.seq_id)
                if getattr(self.drafter, "stateful", False):
                    self.drafter.release(a.seq_id)
                if obs_on:
                    _smetrics.record_sequence("retired")
                    kept = False
                    if a.rt is not None:
                        if a.result.ttft_s is not None:
                            a.rt.event(
                                "sequence.decode",
                                a.result.admitted_at + a.result.ttft_s,
                                now, tokens=len(a.result.tokens))
                        a.rt.annotate(tokens=len(a.result.tokens))
                        if a.drafted:
                            # where speculation paid or thrashed for
                            # THIS request — tail-kept traces carry it
                            a.rt.annotate(
                                drafted=a.drafted, accepted=a.accepted,
                                rejected=a.drafted - a.accepted)
                        kept = _rtrace.default_request_tracer().finish(
                            a.rt, outcome="ok", t_end=now)
                    if a.result.ttft_s is not None:
                        # observed at retirement, where the sampling
                        # verdict is known: the exemplar must reference
                        # a trace that exists in the merged trace
                        _smetrics.record_ttft(
                            a.result.ttft_s,
                            trace_id=(a.result.trace_id if kept
                                      else None))

        def adapter_args(group: List[_Active]):
            """Per-step adapter inputs for one stepping group: (the
            pool's packed device arrays, row i's slot index) — or
            (None, None), the guaranteed zero-cost identity path, when
            no row carries an adapter.  Also banks the analytic
            gather-bytes accounting serve_bench --tenants reports."""
            if self.adapter_pool is None \
                    or not any(a.aslot for a in group):
                return None, None
            asl = np.asarray([a.aslot for a in group], np.int32)
            rows = int((asl > 0).sum())
            self.adapter_rows += rows
            gb = self.adapter_pool.gather_bytes_per_step(rows)
            self.adapter_gather_bytes += gb
            if obs_on:
                _smetrics.record_adapter_gather_bytes(gb)
            return self.adapter_pool.device_arrays(), asl

        def window_args(group: List[_Active]):
            """Per-step (windows, sinks) [B] int32 operands — or (None,
            None), the zero-cost full-attention path, when no row in
            the group is a GENERATING windowed sequence.  A windowed
            sequence still prefilling (token arm) rides full attention
            this step (PAD_START row), exactly the prefill-is-full
            contract."""
            if not any(a.req.window is not None
                       and a.pos >= len(a.result.prompt) for a in group):
                return None, None
            win = np.full(len(group), PAD_START, np.int32)
            snk = np.zeros(len(group), np.int32)
            for i, a in enumerate(group):
                if a.req.window is not None \
                        and a.pos >= len(a.result.prompt):
                    win[i] = a.req.window
                    snk[i] = a.req.sinks
            return win, snk

        def evict_windowed(group: List[_Active]) -> None:
            """Drop every GENERATING windowed sequence's dead interior
            pages before the step's appends: a page entirely past the
            sinks and entirely outside every future query's window can
            never be read again (window_mask is monotone in the query
            position), so the paged walk shrinks to sinks + window
            pages no matter how deep the context runs."""
            for a in group:
                w = a.req.window
                if w is not None and a.pos >= len(a.result.prompt):
                    self.pages_evicted += self.pool.evict_interior(
                        a.seq_id, w, a.req.sinks)

        try:
            while waiting or active:
                # admit (FIFO) while a slot and a worst-case reservation
                # fit.  The reservation is PREFIX-AWARE: a cached-prefix
                # hit charges only the unshared tail, and the bound
                # additionally sets aside every live attached page no
                # admission charge covers (pool.uncharged_live_pages —
                # ground truth off the allocator map, so a cache entry
                # being dropped cannot hide a still-attached page;
                # slightly conservative, never over-committed)
                newly: List[_Active] = []
                while waiting and len(active) < self.max_batch:
                    req, seq, rt = waiting[0]
                    hd = req.handoff
                    mgr = self.session_manager
                    plan = None
                    m = None
                    matched = 0
                    if hd is not None:
                        # disaggregated handoff: the destination-side
                        # cache match was reserved by the handoff
                        # broker; the payload ships only the tail
                        matched = int(getattr(hd, "matched_tokens", 0))
                    else:
                        if mgr is not None and req.session is not None:
                            # tiered session: can retained KV (pool-
                            # resident or host-parked) serve this turn?
                            # Planning pins the session against the
                            # spill writer until admit/abort
                            plan = mgr.plan_resume(
                                req.session, seq.prompt,
                                adapter_id=req.adapter_id)
                        if plan is not None:
                            # parked resumes discount only the prefix
                            # pages pinned across the park (they attach
                            # without free-list pressure — the handoff
                            # reservation argument); a RESIDENT resume
                            # charges its full footprint, conservative
                            # but sound once its pages stop being
                            # manager-locked
                            matched = plan.charge_matched
                        elif self.prefix_cache is not None:
                            # namespaced by adapter: LoRA on wq/wk/wv
                            # changes K/V content, so a base-model
                            # cached prefix must never serve a tenant
                            m = self.prefix_cache.match(
                                req.prompt, adapter_id=req.adapter_id)
                            matched = m.tokens
                    need = self._footprint(req, matched)
                    locked = (self.pool.uncharged_live_pages()
                              if (self.prefix_cache is not None
                                  or mgr is not None) else 0)
                    if mgr is not None:
                        # idle sessions' resident pages are set aside
                        # like live attached pages — no admission
                        # charge covers them, but make_room below can
                        # spill them to the host tier on demand
                        locked += mgr.locked_pages()
                    if reserved_pages + need > self.pool.num_pages - locked:
                        if plan is not None:
                            mgr.abort_resume(plan)
                        if mgr is not None:
                            short = (reserved_pages + need
                                     - (self.pool.num_pages - locked))
                            if mgr.make_room(short) > 0:
                                continue  # re-plan against freed pages
                        break  # wait for retirements
                    waiting.pop(0)
                    aslot = 0
                    if req.adapter_id is not None:
                        try:
                            # pin the variant (faulting it in if cold)
                            # BEFORE any page is claimed: an unloadable
                            # / corrupt / pool-full adapter is a typed
                            # per-request rejection that costs nothing
                            aslot = self.adapter_pool.acquire(
                                req.adapter_id)
                        except AdapterError as err:
                            if plan is not None:
                                mgr.abort_resume(plan)
                            now_r = time.perf_counter()
                            err.trace_id = seq.trace_id
                            seq.error = err
                            seq.finished_at = now_r
                            self.adapter_rejects += 1
                            if obs_on:
                                _smetrics.record_adapter_event("reject")
                                _flight.default_flight().record(
                                    "adapter_reject",
                                    adapter=req.adapter_id,
                                    trace_id=seq.trace_id)
                                if rt is not None:
                                    _rtrace.default_request_tracer() \
                                        .finish(rt, outcome="rejected",
                                                t_end=now_r)
                            continue
                    if plan is not None and plan.kind == "resident":
                        # the session's sequence (and its pages) are
                        # still in the pool — continue it instead of
                        # allocating a fresh table
                        seq.seq_id = plan.session.seq_id
                    else:
                        seq.seq_id = self._next_seq_id
                        self._next_seq_id += 1
                        self.pool.allocate(seq.seq_id)
                    if hd is not None:
                        # attach the reserved shared prefix (if any)
                        # and import the shipped pages — ONE atomic
                        # claim charges the imported footprint.  A
                        # payload stamped with another adapter rejects
                        # typed here (AdapterMismatchError) — one
                        # request's problem, never the batch's
                        try:
                            hd.admit(self.pool, self.prefix_cache,
                                     seq.seq_id)
                        except AdapterError as err:
                            self.pool.free_seq(seq.seq_id)
                            hd.release(self.pool)
                            if aslot and self.adapter_pool is not None:
                                self.adapter_pool.release(req.adapter_id)
                            now_r = time.perf_counter()
                            err.trace_id = seq.trace_id
                            seq.error = err
                            seq.finished_at = now_r
                            self.adapter_rejects += 1
                            if obs_on:
                                _smetrics.record_adapter_event("reject")
                                _flight.default_flight().record(
                                    "adapter_reject",
                                    adapter=req.adapter_id,
                                    trace_id=seq.trace_id)
                                if rt is not None:
                                    _rtrace.default_request_tracer() \
                                        .finish(rt, outcome="rejected",
                                                t_end=now_r)
                            continue
                        if matched:
                            self.prefix_hits += 1
                            self.cached_prefill_tokens += matched
                        elif self.prefix_cache is not None:
                            self.prefix_misses += 1
                    elif plan is not None:
                        # resume the session's KV: resident tables
                        # continue in place (truncated where the new
                        # prompt diverges); parked payloads re-attach
                        # their pinned prefix and import the tail — a
                        # corrupt/lost payload degrades to the prefix
                        # alone (typed, counted), never garbage
                        matched = mgr.resume(plan, seq.seq_id,
                                             trace_id=seq.trace_id)
                        self.session_resumes += 1
                        self.session_resumed_tokens += matched
                        if self.prefix_cache is not None:
                            if matched:
                                self.prefix_hits += 1
                                self.cached_prefill_tokens += matched
                            else:
                                self.prefix_misses += 1
                    elif m is not None:
                        matched = self.prefix_cache.attach(seq.seq_id, m)
                        if matched:
                            self.prefix_hits += 1
                            self.cached_prefill_tokens += matched
                        else:
                            self.prefix_misses += 1
                    if mgr is not None and req.session is not None \
                            and hd is None and plan is None:
                        self.session_fresh += 1
                    seq.admitted_at = time.perf_counter()
                    a = _Active(req, seq.seq_id, seq, rt=rt)
                    a.pos = matched
                    a.matched = matched
                    a.charged = need
                    a.aslot = aslot
                    # whole-prompt prefill keeps its one-pass fast path
                    # when nothing is cached and no chunk cap binds;
                    # everything else goes through chunk steps (or, for
                    # an SPMD program, token-fed decode steps — the
                    # program's prefill starts at position 0)
                    a.whole = (hd is None and self.prefill == "batched"
                               and _psched.whole_eligible(
                                   matched, self._prefill_chunk))
                    a.chunk_mode = (hd is None
                                    and self.prefill == "batched"
                                    and not a.whole
                                    and self.program is None)
                    active.append(a)
                    newly.append(a)
                    reserved_pages += need
                    if obs_on:
                        _smetrics.record_sequence("admitted")
                        extra = ({"adapter": req.adapter_id}
                                 if req.adapter_id is not None else {})
                        _flight.default_flight().record(
                            "admit", seq_id=seq.seq_id,
                            trace_id=seq.trace_id,
                            prompt_len=len(seq.prompt),
                            cached_tokens=matched,
                            reserved_pages=reserved_pages, **extra)
                        if matched:
                            _flight.default_flight().record(
                                "prefix_hit", seq_id=seq.seq_id,
                                trace_id=seq.trace_id, tokens=matched)
                        if rt is not None:
                            rt.event("sequence.queued", rt.t0,
                                     seq.admitted_at)
                            rt.annotate(seq_id=seq.seq_id,
                                        prompt_len=len(seq.prompt),
                                        cached_tokens=matched)
                    if hd is not None:
                        # the prompt's K/V is fully present (imported +
                        # re-attached) and the prefill side already
                        # chose the first token against its own logits
                        # — emit it here and let the sequence join the
                        # decode batch at position len(prompt)
                        a.pos = len(seq.prompt)
                        self._cache_insert(a)
                        now0 = time.perf_counter()
                        if emit(a, np.asarray(hd.first_logits),
                                seq.admitted_at, now0,
                                tok=int(hd.first_token)):
                            retire([a], now0)
                # NOTE: waiting-but-nothing-active cannot happen — the
                # up-front validation guarantees the head request fits an
                # empty pool (locked pages are 0 with no live readers,
                # and manager-locked sessions spill to the host tier via
                # make_room before admission gives up), so admission
                # always progresses

                whole_group = [a for a in newly if a.whole]
                if whole_group:
                    # ONE whole-prompt pass for the co-admitted group:
                    # every prompt token's K/V lands in the pool and each
                    # sequence gets its first generated token — O(1)
                    # model steps per admission group vs O(max prompt
                    # len) token-by-token
                    t0 = time.perf_counter()
                    step_idx = self.steps
                    if self.program is not None:
                        logits = self.program.prefill_step(
                            self.pool, [a.seq_id for a in whole_group],
                            [a.result.prompt for a in whole_group])
                    else:
                        ad, asl = adapter_args(whole_group)
                        logits = prefill_step(
                            self.params, self.cfg, self.pool,
                            [a.seq_id for a in whole_group],
                            [a.result.prompt for a in whole_group],
                            force=self.force, adapters=ad,
                            adapter_slots=asl)
                    self.steps += 1
                    self.prefill_steps += 1
                    ntok = sum(len(a.result.prompt) for a in whole_group)
                    self.prefill_tokens += ntok
                    self.max_prefill_tokens_step = max(
                        self.max_prefill_tokens_step, ntok)
                    self._occupancy_sum += \
                        len(whole_group) / float(self.max_batch)
                    logits, ok, now = quarantine(whole_group, logits,
                                                 step_idx)
                    pairs = []
                    for i, a in enumerate(whole_group):
                        a.pos = len(a.result.prompt)
                        if i not in ok:
                            continue  # quarantined at prefill
                        self._cache_insert(a)
                        pairs.append((a, np.asarray(logits[i])))
                    retire(emit_batch(pairs, t0, now), now)
                    if obs_on:
                        self._note_attention_bytes()
                    self._watchdog()
                    continue  # re-admit into freed slots before decoding

                if not active:
                    continue

                # chunk-mode sequences (cached-prefix tails, capped long
                # prompts) prefill through chunk steps; everyone else —
                # generating sequences and token-arm/program prefillers —
                # steps through the decode path.  When both kinds of
                # work exist the scheduler ALTERNATES, so a long
                # prompt's chunks interleave with in-flight sequences'
                # decode steps instead of stalling them
                chunkers = [a for a in active if a.chunk_mode
                            and a.pos < len(a.result.prompt)]
                decodable = [a for a in active if not (
                    a.chunk_mode and a.pos < len(a.result.prompt))]
                if chunkers and (not decodable or self._prefer_prefill):
                    t0 = time.perf_counter()
                    step_idx = self.steps
                    idx, chunks, starts = _psched.plan_chunks(
                        [a.result.prompt for a in chunkers],
                        [a.pos for a in chunkers], self._prefill_chunk,
                        flop_budget=self._prefill_flops)
                    sel = [chunkers[i] for i in idx]
                    ad, asl = adapter_args(sel)
                    logits = chunk_prefill_step(
                        self.params, self.cfg, self.pool,
                        [a.seq_id for a in sel], chunks, starts,
                        adapters=ad, adapter_slots=asl)
                    self.steps += 1
                    self.prefill_steps += 1
                    ntok = sum(len(c) for c in chunks)
                    self.prefill_tokens += ntok
                    self.max_prefill_tokens_step = max(
                        self.max_prefill_tokens_step, ntok)
                    self._occupancy_sum += len(sel) / float(self.max_batch)
                    logits, ok, now = quarantine(sel, logits, step_idx)
                    pairs = []
                    for i, a in enumerate(sel):
                        if i not in ok:
                            continue  # quarantined at this chunk
                        a.pos += len(chunks[i])
                        if a.pos >= len(a.result.prompt):
                            self._cache_insert(a)
                            pairs.append((a, np.asarray(logits[i])))
                    retire(emit_batch(pairs, t0, now), now)
                    if obs_on:
                        self._note_attention_bytes()
                    self._watchdog()
                    self._prefer_prefill = False
                    continue

                # one token per stepping sequence — or, with speculation
                # armed, 1+d_i tokens for generating greedy sequences
                # (DRAFT phase: prompt-lookup proposals, pure host).
                # Under prefill="token" (and program-driven
                # cached-prefix tails) a still-prefilling sequence and a
                # deep-decode sequence share the batch and differ only
                # in k_lengths / q_lengths.  The chunk cap bounds how
                # many prefill tokens (one per prefilling sequence
                # here) ride one step
                batch = list(decodable)
                if self._prefill_chunk:
                    pre = [a for a in batch
                           if a.pos < len(a.result.prompt)]
                    if len(pre) > self._prefill_chunk:
                        keep = set(
                            id(a) for a in pre[:self._prefill_chunk])
                        batch = [a for a in batch
                                 if a.pos >= len(a.result.prompt)
                                 or id(a) in keep]
                if not batch:
                    continue
                evict_windowed(batch)
                blocks: List[List[int]] = []
                for a in batch:
                    if a.pos < len(a.result.prompt):
                        blocks.append([a.result.prompt[a.pos]])
                        continue
                    blk = [a.result.tokens[-1]]
                    room = self._spec_room(a)
                    if room > 0 and self.drafter is not None:
                        # clamp to room: a custom drafter ignoring its
                        # max_draft must not breach the pad_to width or
                        # the admission page reservation.  A stateful
                        # drafter (PromptLookupDrafter) gets the seq_id
                        # so its incremental suffix index answers the
                        # probe in O(d) instead of re-scanning the
                        # whole context every step
                        ctx = list(a.result.prompt) + a.result.tokens
                        if getattr(self.drafter, "stateful", False):
                            # adapter-aware drafters probe the corpus
                            # trie within the request's namespace only
                            # — cross-tenant continuations must not
                            # leak through draft proposals
                            if getattr(self.drafter, "adapter_aware",
                                       False):
                                proposal = self.drafter.draft(
                                    ctx, room, seq_id=a.seq_id,
                                    adapter_id=a.req.adapter_id)
                            else:
                                proposal = self.drafter.draft(
                                    ctx, room, seq_id=a.seq_id)
                        else:
                            proposal = self.drafter.draft(ctx, room)
                        if len(proposal):
                            # draft-source attribution (ISSUE 20): who
                            # proposed THIS block — labels the verify
                            # outcome so own-vs-corpus acceptance is a
                            # dashboard ratio
                            a.spec_source = getattr(
                                self.drafter, "last_source", "own")
                        blk += list(proposal)[:room]
                    blocks.append(blk)
                t0 = time.perf_counter()
                step_idx = self.steps
                seq_ids = [a.seq_id for a in batch]

                if max(len(b) for b in blocks) > 1:
                    # VERIFY phase: one multi-token model step feeds
                    # every sequence's block (ragged q_lengths); each
                    # emitted token is the model's own argmax given an
                    # exactly-verified prefix, so greedy output is
                    # token-identical to full_decode with up to d_i+1
                    # tokens committed per step
                    drafted_now = sum(len(b) - 1 for b in blocks)
                    if obs_on:
                        for a, b in zip(batch, blocks):
                            if len(b) > 1:
                                _flight.default_flight().record(
                                    "draft", seq_id=a.seq_id,
                                    step=step_idx, tokens=len(b) - 1,
                                    source=a.spec_source,
                                    trace_id=a.result.trace_id)
                    if self.program is not None:
                        logits3 = self.program.verify_step(
                            self.pool, seq_ids, blocks,
                            [a.pos for a in batch],
                            pad_to=self._speculate + 1)
                    else:
                        ad, asl = adapter_args(batch)
                        win, snk = window_args(batch)
                        logits3 = verify_step(
                            self.params, self.cfg, self.pool, seq_ids,
                            blocks, [a.pos for a in batch],
                            force=self.force, impl=self.paged_impl,
                            pad_to=self._speculate + 1,
                            adapters=ad, adapter_slots=asl,
                            windows=win, sinks=snk,
                            table_block=self._table_block)
                        self.max_decode_table_pages = max(
                            self.max_decode_table_pages,
                            max(len(self.pool._tables[a.seq_id].pages)
                                for a in batch))
                    self.steps += 1
                    self.decode_steps += 1
                    self.spec_steps += 1
                    self.drafted_tokens += drafted_now
                    ntok = sum(1 for a in batch
                               if a.pos < len(a.result.prompt))
                    if ntok:
                        self.prefill_tokens += ntok
                        self.max_prefill_tokens_step = max(
                            self.max_prefill_tokens_step, ntok)
                    self._occupancy_sum += \
                        len(batch) / float(self.max_batch)
                    logits3, ok, now = quarantine(batch, logits3,
                                                  step_idx)
                    if chunkers:
                        self._decode_durs_during_prefill.append(now - t0)
                    pairs = []
                    spec_rows: List[Tuple[int, _Active]] = []
                    retired: List[_Active] = []
                    for i, a in enumerate(batch):
                        blk = blocks[i]
                        start = a.pos
                        if i not in ok:
                            continue  # quarantined (pages already freed)
                        if a.pos < len(a.result.prompt):
                            a.pos += 1
                            if a.pos == len(a.result.prompt):
                                self._cache_insert(a)
                                pairs.append(
                                    (a, np.asarray(logits3[i, 0])))
                            continue
                        params_i = a.req.sampling
                        if params_i is not None and not params_i.greedy:
                            if len(blk) == 1:
                                # un-drafted sampled row riding the
                                # step at d=0 — the PLAIN epilogue
                                # (unsalted key) keeps its stream
                                # byte-identical to an unspeculated run
                                a.pos += 1
                                pairs.append(
                                    (a, np.asarray(logits3[i, 0])))
                                continue
                            # drafted sampled row: the fused
                            # accept/resample epilogue decides it below
                            spec_rows.append((i, a))
                            continue
                        # ACCEPTANCE walk (longest prefix match): row t
                        # predicts position start+t+1 — emit its argmax
                        # and keep walking only while it matches the
                        # draft (whose K/V is then already committed)
                        accepted = 0
                        done = False
                        for t in range(len(blk)):
                            row = np.asarray(logits3[i, t])
                            tok = int(apply_bias(row, params_i).argmax())
                            fed = t + 1 < len(blk) and tok == blk[t + 1]
                            if fed:
                                accepted += 1
                            done = emit(a, row, t0, now, tok=tok)
                            if done or not fed:
                                break
                        drafted = len(blk) - 1
                        a.drafted += drafted
                        a.accepted += accepted
                        self.accepted_tokens += accepted
                        # ROLLBACK: rejected draft tokens (and fed
                        # tokens past an in-block EOS/stop) leave the
                        # page table atomically — pure host bookkeeping
                        new_len = start + 1 + accepted
                        rolled = start + len(blk) - new_len
                        if rolled:
                            self.pool.truncate_seq(a.seq_id, new_len)
                            self.rolled_back_tokens += rolled
                        a.pos = new_len
                        if obs_on and drafted:
                            _smetrics.record_spec(drafted, accepted,
                                                  source=a.spec_source)
                            _flight.default_flight().record(
                                "verify", seq_id=a.seq_id,
                                step=step_idx, accepted=accepted,
                                rejected=drafted - accepted,
                                source=a.spec_source,
                                trace_id=a.result.trace_id)
                            if rolled:
                                _flight.default_flight().record(
                                    "rollback", seq_id=a.seq_id,
                                    step=step_idx, tokens=rolled,
                                    length=new_len,
                                    trace_id=a.result.trace_id)
                        if done:
                            retired.append(a)
                    if spec_rows:
                        # EXACT SPECULATIVE SAMPLING (ISSUE 16): one
                        # fused accept/resample call decides every
                        # drafted sampled row — per-row accepted counts
                        # come back device-side (no per-sequence host
                        # sync), then the host walk mirrors the greedy
                        # walk's emit/rollback bookkeeping exactly
                        # (EOS/stop inside an accepted prefix retires
                        # at that position and truncates the surplus)
                        sqw = self._speculate + 1
                        sub = np.stack([
                            np.stack([
                                apply_bias(np.asarray(logits3[i, t]),
                                           a.req.sampling)
                                for t in range(sqw)])
                            for i, a in spec_rows])
                        acc, spec_toks = spec_sample_rows(
                            sub,
                            [a.req.sampling for _, a in spec_rows],
                            [len(a.result.tokens)
                             for _, a in spec_rows],
                            [blocks[i][1:] for i, _ in spec_rows])
                        for r, (i, a) in enumerate(spec_rows):
                            blk = blocks[i]
                            start = a.pos
                            n_acc = int(acc[r])
                            accepted = 0
                            done = False
                            for t in range(n_acc + 1):
                                row = np.asarray(logits3[i, t])
                                fed = t < n_acc
                                if fed:
                                    accepted += 1
                                done = emit(a, row, t0, now,
                                            tok=int(spec_toks[r, t]))
                                if done or not fed:
                                    break
                            drafted = len(blk) - 1
                            a.drafted += drafted
                            a.accepted += accepted
                            self.accepted_tokens += accepted
                            new_len = start + 1 + accepted
                            rolled = start + len(blk) - new_len
                            if rolled:
                                self.pool.truncate_seq(a.seq_id,
                                                       new_len)
                                self.rolled_back_tokens += rolled
                            a.pos = new_len
                            if obs_on and drafted:
                                _smetrics.record_spec(
                                    drafted, accepted,
                                    source=a.spec_source)
                                _flight.default_flight().record(
                                    "verify", seq_id=a.seq_id,
                                    step=step_idx, accepted=accepted,
                                    rejected=drafted - accepted,
                                    source=a.spec_source,
                                    trace_id=a.result.trace_id)
                                if rolled:
                                    _flight.default_flight().record(
                                        "rollback", seq_id=a.seq_id,
                                        step=step_idx, tokens=rolled,
                                        length=new_len,
                                        trace_id=a.result.trace_id)
                            if done:
                                retired.append(a)
                    retired.extend(emit_batch(pairs, t0, now))
                    retire(retired, now)
                    if obs_on:
                        self._note_attention_bytes()
                    self._watchdog()
                    self._prefer_prefill = True
                    continue

                tokens = [b[0] for b in blocks]
                positions = [a.pos for a in batch]
                if self.program is not None:
                    logits = self.program.decode_step(
                        self.pool, seq_ids, tokens, positions)
                else:
                    ad, asl = adapter_args(batch)
                    win, snk = window_args(batch)
                    logits = decode_step(
                        self.params, self.cfg, self.pool, seq_ids, tokens,
                        positions, force=self.force, impl=self.paged_impl,
                        adapters=ad, adapter_slots=asl,
                        windows=win, sinks=snk,
                        table_block=self._table_block)
                    self.max_decode_table_pages = max(
                        self.max_decode_table_pages,
                        max(len(self.pool._tables[a.seq_id].pages)
                            for a in batch))
                self.steps += 1
                self.decode_steps += 1
                ntok = sum(1 for a in batch
                           if a.pos < len(a.result.prompt))
                if ntok:
                    self.prefill_tokens += ntok
                    self.max_prefill_tokens_step = max(
                        self.max_prefill_tokens_step, ntok)
                self._occupancy_sum += len(batch) / float(self.max_batch)
                logits, ok, now = quarantine(batch, logits, step_idx)
                if chunkers:
                    self._decode_durs_during_prefill.append(now - t0)

                pairs = []
                for i, a in enumerate(batch):
                    a.pos += 1
                    if i not in ok:
                        continue  # quarantined this step
                    if a.pos < len(a.result.prompt):
                        continue  # still prefilling; logits unused
                    if a.pos == len(a.result.prompt):
                        # the fed token completed the prompt's K/V:
                        # offer its pages to the prefix cache
                        self._cache_insert(a)
                    pairs.append((a, np.asarray(logits[i])))
                retire(emit_batch(pairs, t0, now), now)
                if obs_on:
                    self._note_attention_bytes()
                self._watchdog()
                self._prefer_prefill = True
        except BaseException:
            # ANY raise out of a prefill/decode step (or admission): the
            # stepping sequences' pages go back to the pool BEFORE the
            # error propagates — a failed run must never strand pages
            # (the acknowledged hazard this loop previously carried)
            for a in active:
                self.pool.free_seq(a.seq_id)
                if self.prefix_cache is not None:
                    self.prefix_cache.forget_seq(a.seq_id)
                if getattr(self.drafter, "stateful", False):
                    self.drafter.release(a.seq_id)
                if self.session_manager is not None \
                        and a.req.session is not None:
                    # the pool side is freed above: the session must
                    # not believe it still owns a resident sequence
                    self.session_manager.on_quarantine(a.req.session)
                if a.aslot and self.adapter_pool is not None:
                    self.adapter_pool.release(a.req.adapter_id)
            active.clear()
            raise
        return results

    def _cache_insert(self, a: _Active) -> None:
        """Offer a fully-prefilled prompt's pages to the prefix cache
        (once per sequence): future prompts sharing the prefix attach
        them instead of re-prefilling."""
        if self.prefix_cache is None or a.inserted:
            return
        a.inserted = True
        self.prefix_cache.insert(a.seq_id, a.result.prompt,
                                 adapter_id=a.req.adapter_id)

    def _watchdog(self) -> None:
        """Every check_every steps: audit pool integrity and repair
        detected leaks (orphaned pages return to the free list)."""
        if not self.check_every or self.steps % self.check_every:
            return
        report = self.pool.check_invariants()
        if report["ok"]:
            return
        self.invariant_violations += 1
        reclaimed = self.pool.reclaim_orphans()
        self.reclaimed_pages += reclaimed
        _log.warning(
            "KV pool '%s' failed its invariant audit at step %d "
            "(orphaned=%s double_owned=%s free_errors=%s); reclaimed %d "
            "orphaned pages", self.pool.name, self.steps,
            report["orphaned_pages"], report["double_owned_pages"],
            report["free_list_errors"], reclaimed)
        if _flags._VALUES["FLAGS_observability"] and reclaimed:
            _smetrics.record_pool_reclaim(reclaimed, pool=self.pool.name)
            _flight.default_flight().record(
                "page_reclaim", pool=self.pool.name, pages=reclaimed,
                step=self.steps)

    def _note_attention_bytes(self) -> None:
        """Attention-bytes-per-step gauge for the CURRENT pool contents,
        labeled with the impl that runs AND the pool's kv_dtype —
        callers gate on the observability flag (zero-work disabled
        path).  The byte model takes the pool's explicit dtype and KV
        head count: GQA and int8 pools price H_q/H_kv x and itemsize/4 x
        below the fp32 full-head default, which is the win the gauge
        exists to make visible."""
        st = self.pool.stats()
        if not st["live_sequences"]:
            return
        maxp = self.pool.max_live_pages()
        kv_dtype = np.dtype(self.pool.k_pages.dtype).name
        _smetrics.record_attention_bytes(
            attention_bytes_per_step(
                self.paged_impl, st["live_sequences"], maxp,
                self.pool.page_size, self.pool.num_heads,
                self.pool.head_dim,
                num_layers=self.pool.num_layers,
                num_kv_heads=self.pool.num_kv_heads,
                dtype=self.pool.k_pages.dtype),
            impl=self.paged_impl, kv_dtype=kv_dtype)

    def mean_occupancy(self) -> float:
        return self._occupancy_sum / self.steps if self.steps else 0.0
