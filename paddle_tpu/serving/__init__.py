"""Serving tier: a batching inference server with a paged KV-cache
decode path — the fourth pillar next to train-perf (kernels/fusion),
resilience, and observability.

Pieces (each usable alone, wired together by the Engine and the decode
loop):

- **Engine** (engine.py) — wraps a loaded AOT artifact
  (inference/aot.py) or an Executor-compiled Program behind a
  thread-safe ``submit(feed) -> Future`` API: a single dispatcher
  coalesces queued requests into micro-batches padded to a fixed bucket
  ladder (``FLAGS_serving_buckets``), so the backend compiles at most
  once per bucket regardless of the request mix.  Bounded-queue
  backpressure (QueueFullError), per-request deadlines
  (RequestTimeoutError), and graceful drain wired to
  resilience.PreemptionDrain.
- **Dynamic batcher** (batching.py) — the bucket ladder, row coalescing
  (replicated-last-row padding, sliced off before completion: per-row
  outputs stay bit-identical to unbatched calls), and request records.
- **Paged KV cache** (kvcache.py) — fixed-size page blocks in one
  preallocated device array per model (kernel-native [H, pages,
  page_size, head_dim] layout per layer), per-sequence page tables,
  alloc/free/defrag accounting; attention reads it through
  kernels/paged_attention.py — FLAGS_serving_paged_impl selects the
  pallas ragged page-streaming kernel (no gather ever materializes) vs
  the reference gather + flash ragged ``k_lengths`` tier, with a
  measured-envelope fallback.
- **Continuous batching** (generate.py) — greedy decode that admits
  waiting sequences the moment finished ones retire, holding batch
  occupancy (the serving throughput lever) across mixed-length
  workloads; admitted prompts prefill in ONE whole-prompt causal pass
  (``prefill_step``; ``prefill="token"`` keeps the step-per-token arm);
  ``full_decode`` is the full-recompute parity oracle.
- **Speculative decoding** (speculative.py + generate.verify_step,
  ISSUE 13) — draft-model-free speculation:
  ``ContinuousBatchingLoop(speculate=d)`` has a prompt-lookup drafter
  (n-gram match over prompt + generation history; no second model, no
  extra HBM) propose up to d continuation tokens per greedy sequence,
  verified in ONE Sq=1+d model step through the paged kernel's ragged
  ``q_lengths`` arm (each live KV page still streams once — bytes/step
  is flat in d); acceptance is longest-prefix-match against the
  model's own argmax (greedy output stays token-identical to
  ``full_decode``), rejected tokens roll back via the atomic
  ``KVCachePool.truncate_seq`` (refcount/CoW/int8-scale aware).
- **Sampling contract** (sampling.py, ISSUE 13) —
  ``DecodeRequest.sampling = SamplingParams(...)`` (threaded from
  ``Engine.submit(sampling=)`` in pass-through mode):
  temperature/top-k/top-p through one jitted epilogue per step, logit
  bias (greedy included), stop sequences, per-request max_new;
  non-greedy sequences auto-degrade speculation to d=0 while greedy
  batch-mates keep drafting.
- **Prefix cache** (prefixcache.py, ISSUE 11) — refcounted
  copy-on-write page sharing over the pool: prompts are trie-keyed by
  a rolling prefix hash at page granularity, a hit attaches cached
  pages read-only (refcount++; ``free_seq`` frees only refcount-zero
  pages) and prefills ONLY the unshared tail via
  ``chunk_prefill_step``; the first divergent append copy-on-writes a
  shared partial tail page; LRU eviction under pool pressure;
  ``FLAGS_serving_prefill_chunk`` caps prefill tokens per engine step
  with chunk/decode interleaving (chunked prefill).

Fault isolation (ISSUE 6 — the resilience pillar's serving half): a
backend raise fails only its batch's futures (typed EngineInternalError)
while the dispatcher survives; a dispatcher thread that dies anyway is
restarted by a supervisor with the queue preserved; repeated failures
trip a circuit breaker (EngineUnhealthyError fast-fail + half-open
probe); decode sequences whose logits go non-finite are QUARANTINED
individually (NonFiniteSequenceError; pages freed; batch-mates decode
on); KVCachePool.check_invariants()/reclaim_orphans() detect and repair
page leaks; deadline-aware admission sheds requests that cannot dispatch
in time; engine.health() snapshots
SERVING/DEGRADED/DRAINING/BROKEN.  FAULT_SERVE_* chaos knobs
(resilience/faultinject.py) drive tests/test_serving_resilience.py.

Observability (serving/metrics.py): queue-depth/batch-occupancy gauges,
TTFT and per-token latency histograms, page-pool utilization, and
admission/reject counters — all behind FLAGS_observability with the
established one-dict-lookup disabled path.  ISSUE 8 adds request-scoped
tracing end to end: Engine.submit() mints a `trace_id` carried on the
returned Future, on typed errors, and on GeneratedSequence; the request/
sequence lifecycle is recorded as cross-thread span trees, tail-sampled
(slow/errored/shed/quarantined keep full detail under
FLAGS_request_trace_budget) into the merged Perfetto trace; latency/TTFT
histograms carry OpenMetrics exemplars; and a flight recorder
(observability/flight.py) auto-dumps the last N lifecycle events as
JSONL whenever the breaker trips or health() enters BROKEN.
tools/serve_bench.py is the closed-loop load generator + regression
gate.

Tiered KV cache (kvtier.py, ISSUE 18): multi-turn sessions stop dying
with HBM — a ``TieredSessionManager`` keeps retired sequences' pages
RESIDENT between turns (``DecodeRequest.session`` resumes them with
zero prefill), spills LRU/idle sessions' KV to a checksummed
``HostKVTier`` in host RAM (``export_seq`` payloads parked by a
spill-writer thread overlapped with decode, or inline under the pool's
pressure-reclaimer hook), and resumes parked sessions by re-attaching
their pinned prefix-cache pages and importing only the unshared tail.
Admission reserves against the COMBINED tier (``make_room`` spills on
demand, so session capacity is HBM + host while active decode stays
HBM-bounded); a spilled-and-resumed session is token-identical to a
never-spilled one; FAULT_SERVE_SPILL_CORRUPT/_DROP chaos verifies a
damaged payload re-prefills typed instead of importing garbage.

Multi-tenant serving (adapters.py, ISSUE 19): thousands of LoRA
fine-tunes of one base checkpoint served side by side — an
``AdapterPool`` of paged, refcounted, LRU-evicted low-rank A/B deltas
(per-layer attention QKV/wo + MLP projections; geometry/rank/dtype
validated typed at ``register_adapter``; cold adapters live in a
bounded CRC-verified host tier and fault in on first request,
kvtier-style) with a BATCHED per-row apply: each live row carries an
adapter slot index, every decode/prefill/verify step gathers that
row's A/B from device packs and applies ``y += (x @ A) @ B`` per
projection (slot 0 is an all-zero identity, so base rows ride the
same einsum at zero extra cost), token-identical to a per-tenant
dense weight merge.  The contract threads
``Engine.submit(adapter_id=)`` → ``DecodeRequest.adapter_id`` →
typed admission (an unloadable adapter rejects before any KV page is
claimed) → adapter-namespaced prefix cache and corpus drafter →
``SeqExport.adapter_id`` mismatch resets on the kvtier and fleet
planes → hot ``publish``/``retire`` under live traffic.

Scaling past one chip (ISSUE 10) lives in ``serving/distributed/``:
tensor-parallel decode under shard_map (ShardedDecodeProgram +
head-sharded ShardedKVCachePool — the ContinuousBatchingLoop takes it
via ``program=``) and data-parallel Engine replicas behind one
admission Router with health/lease-aware dispatch and drain-based
replica handoff.  ``serve_bench --replicas N`` / ``--mesh N`` bench
both axes chip-less.
"""

from .adapters import (
    AdapterCorruptError,
    AdapterError,
    AdapterGeometryError,
    AdapterHostFullError,
    AdapterInUseError,
    AdapterMismatchError,
    AdapterNotRegisteredError,
    AdapterPool,
    AdapterPoolFullError,
    make_adapter,
    merge_adapter_params,
)
from .batching import BucketLadder, parse_buckets
from .engine import (
    AotBackend,
    Engine,
    EngineClosedError,
    EngineConfig,
    EngineInternalError,
    EngineUnhealthyError,
    ExecutorBackend,
    QueueFullError,
    RequestTimeoutError,
)
from .generate import (
    ContinuousBatchingLoop,
    DecodeConfig,
    DecodeRequest,
    GeneratedSequence,
    NonFiniteSequenceError,
    full_decode,
    full_forward,
    init_decode_params,
    prefill_step,
    verify_step,
)
from .kvcache import (
    KVCachePool,
    PagePoolExhausted,
    SeqExport,
    SequenceHandle,
)
from .kvtier import (
    HostKVTier,
    HostTierFullError,
    SpillCorruptError,
    SpillMissingError,
    TierSession,
    TieredSessionManager,
)
from .prefixcache import PrefixCache, PrefixMatch
from .sampling import SamplingParams
from .speculative import PromptLookupDrafter
from . import distributed  # noqa: F401 — serving.distributed is API
from . import fleet  # noqa: F401 — serving.fleet is API (ISSUE 15)

__all__ = [
    "AdapterCorruptError",
    "AdapterError",
    "AdapterGeometryError",
    "AdapterHostFullError",
    "AdapterInUseError",
    "AdapterMismatchError",
    "AdapterNotRegisteredError",
    "AdapterPool",
    "AdapterPoolFullError",
    "AotBackend",
    "BucketLadder",
    "ContinuousBatchingLoop",
    "DecodeConfig",
    "DecodeRequest",
    "Engine",
    "EngineClosedError",
    "EngineConfig",
    "EngineInternalError",
    "EngineUnhealthyError",
    "ExecutorBackend",
    "GeneratedSequence",
    "HostKVTier",
    "HostTierFullError",
    "KVCachePool",
    "NonFiniteSequenceError",
    "PagePoolExhausted",
    "PrefixCache",
    "PrefixMatch",
    "PromptLookupDrafter",
    "QueueFullError",
    "RequestTimeoutError",
    "SamplingParams",
    "SeqExport",
    "SequenceHandle",
    "SpillCorruptError",
    "SpillMissingError",
    "TierSession",
    "TieredSessionManager",
    "full_decode",
    "full_forward",
    "init_decode_params",
    "make_adapter",
    "merge_adapter_params",
    "parse_buckets",
    "prefill_step",
    "verify_step",
]
