"""Shared prefill scheduler: one arithmetic for both prefill consumers.

`ContinuousBatchingLoop.run` (the monolithic loop) and
`PrefillReplica._prefill_jobs` (the disaggregated fleet's prefill side)
each re-implemented the same three decisions — when a prompt takes the
one-pass whole-prompt fast path, how a chunk step's token budget packs
over still-prefilling sequences, and the per-sequence blast radius when
a step's logits come back non-finite.  ~90 lines of drift that the
parity matrix could only detect after the fact; extracting them here
makes the split impossible to diverge.  The callers keep what is
genuinely theirs: step invocation (program/force arms), counters, and
what "evict" means for their bookkeeping (an `_Active` leaving the
batch vs a `_Job`'s future failing typed).
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .. import flags as _flags
from ..resilience import faultinject as _finject
from ..resilience.sentinel import rows_finite
from . import metrics as _smetrics

__all__ = ["whole_eligible", "plan_chunks", "evict_nonfinite"]


def whole_eligible(matched: int, chunk_cap: int) -> bool:
    """True when a prompt takes the one-pass whole-prompt prefill fast
    path: nothing is cached (a cached-prefix tail must chunk from its
    match offset) and no chunk cap binds."""
    return matched == 0 and not chunk_cap


def plan_chunks(prompts: Sequence[Sequence[int]],
                positions: Sequence[int], chunk_cap: int,
                flop_budget: Optional[float] = None,
                ) -> Tuple[List[int], List[List[int]], List[int]]:
    """Pack one chunk step's budget over still-prefilling sequences,
    FIFO, clamped per sequence.  A zero/None cap means one uncapped
    step that finishes every prompt.  Returns ``(idx, chunks, starts)``
    where ``idx`` indexes into the caller's selection so it can map
    rows back to its own records.

    ``flop_budget`` (ISSUE 20) switches the budget unit from tokens to
    ESTIMATED ATTENTION WORK: a chunk of ``n`` tokens starting at
    resident position ``pos`` attends over roughly ``n * (pos + n/2)``
    query·key pairs (per head·dim — the d_model factor is constant
    across candidates, so it cancels).  A token cap charges a 100-token
    chunk the same whether the sequence holds 100 or 100k resident
    tokens; at 32k+ contexts that quadratic term is the whole cost, and
    budgeting by it is what bounds the per-step decode-latency hit of a
    long prefill.  Per sequence the largest ``n`` with
    ``n * (pos + n/2) <= remaining budget`` is
    ``-pos + sqrt(pos^2 + 2*budget)`` (the positive root); the HEAD
    sequence always gets >= 1 token so deep-context prefill can never
    starve (the same no-starvation rule as a 1-token token cap).  A
    nonzero ``chunk_cap`` still clamps tokens on top — the two budgets
    compose, each binding where it is the tighter one."""
    idx: List[int] = []
    chunks: List[List[int]] = []
    starts: List[int] = []
    if flop_budget is not None and flop_budget <= 0:
        raise ValueError(
            f"flop_budget must be > 0 (or None), got {flop_budget}")
    budget = chunk_cap or sum(
        len(p) - pos for p, pos in zip(prompts, positions))
    flops = float(flop_budget) if flop_budget is not None else None
    for i, (prompt, pos) in enumerate(zip(prompts, positions)):
        if budget <= 0 or (flops is not None and flops <= 0 and idx):
            break
        n = min(len(prompt) - pos, budget)
        if flops is not None:
            n_flop = int(-pos + (pos * pos + 2.0 * flops) ** 0.5)
            if not idx:
                n_flop = max(n_flop, 1)  # head never starves
            n = min(n, n_flop)
        if n <= 0:
            break
        idx.append(i)
        chunks.append(list(prompt[pos:pos + n]))
        starts.append(pos)
        budget -= n
        if flops is not None:
            flops -= n * (pos + n / 2.0)
    return idx, chunks, starts


def evict_nonfinite(pool, cache, seq_ids: Sequence[int],
                    matched: Sequence[int], logits, step_idx: int,
                    on_evict: Callable[[int, BaseException, float], None],
                    ) -> Tuple[np.ndarray, np.ndarray, float]:
    """Evict every non-finite row of one step's logits — the shared
    per-sequence quarantine blast radius.  `logits` arrives as the
    step's DEVICE output: the chaos knob (FAULT_SERVE_NAN_SEQ) poisons
    it first, then the ONE fused jitted [B]-bool scan runs before the
    single host materialization, so the scan never re-uploads a host
    array and the whole batch syncs as one vector, never per row.

    For each poisoned row i: the sequence's private pages are scrubbed
    (zeroed — the free list must never recycle NaN content) and freed,
    its prefix-cache chain is quarantined when it READ cached pages
    (``matched[i]``, presume the chain poisoned) or merely forgotten
    otherwise, the quarantined-sequence metric lands, and the caller's
    ``on_evict(i, err, now)`` does its own bookkeeping (remove from
    batch / fail the future).

    Returns ``(host logits, finite [B] bool mask, post-sync step-end
    timestamp)``.
    """
    logits = _finject.serve_nan_rows(list(seq_ids), step_idx, logits)
    finite = np.asarray(rows_finite(logits))
    logits = np.asarray(logits)
    now = time.perf_counter()  # after the sync: true step end
    if finite.all():
        return logits, finite, now
    from .generate import NonFiniteSequenceError  # circular at import time

    obs_on = _flags._VALUES["FLAGS_observability"]
    for i, sid in enumerate(seq_ids):
        if finite[i]:
            continue
        err = NonFiniteSequenceError(int(sid), step_idx)
        pool.scrub_seq_pages(sid)
        pool.free_seq(sid)
        if cache is not None:
            if matched[i]:
                cache.quarantine_seq(sid)
            else:
                cache.forget_seq(sid)
        if obs_on:
            _smetrics.record_sequence("quarantined")
        on_evict(i, err, now)
    return logits, finite, now
