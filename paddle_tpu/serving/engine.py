"""Batching inference engine: thread-safe submit(feed) -> Future.

One Engine wraps one loaded model — an AOT StableHLO artifact
(inference/aot.py) or an Executor-compiled Program — behind a bounded
request queue and a single dispatcher thread:

- **submit() is thread-safe and non-blocking**: callers get a
  concurrent.futures.Future; the dispatcher coalesces queued requests
  into micro-batches padded to the bucket ladder (batching.py), runs the
  backend once per batch, and slices per-request rows back out.
- **Backpressure** is a bounded queue: submit raises QueueFullError once
  `queue_depth` requests are pending — callers shed load explicitly
  instead of the engine buffering unboundedly.
- **Deadlines**: submit(feed, timeout=...) arms an absolute deadline; a
  request still queued when it expires fails with RequestTimeoutError
  (requests already inside a dispatched batch always complete — an XLA
  dispatch cannot be recalled).
- **Drain** mirrors resilience.PreemptionDrain semantics: begin_drain()
  stops admissions (submit raises EngineClosedError), the dispatcher
  finishes the in-flight batch and every queued request that still has
  deadline headroom, then parks.  attach_drain(PreemptionDrain) wires
  SIGTERM straight to begin_drain via the drain's listener hook.
- **Compile discipline**: every dispatch is padded to a ladder bucket, so
  the backend sees at most len(buckets) distinct batch shapes for the
  life of the engine.  The engine counts first-seen shapes
  (`compile_counters()`) — the serving analogue of the executor's
  compile-cache hit/miss counters — and tests assert the ladder bound.

Observability (queue depth, batch occupancy, latency histograms,
admission/reject/timeout counters) gates on FLAGS_observability with the
established zero-work disabled path: one dict lookup, no allocation.
"""

from __future__ import annotations

import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import flags as _flags
from . import metrics as _smetrics
from .batching import (
    BucketLadder,
    Request,
    coalesce,
    parse_buckets,
    request_rows,
    scatter,
)

__all__ = [
    "Engine",
    "EngineConfig",
    "EngineClosedError",
    "QueueFullError",
    "RequestTimeoutError",
    "AotBackend",
    "ExecutorBackend",
]


class RequestTimeoutError(TimeoutError):
    """A request's deadline expired before its batch was dispatched."""


class QueueFullError(RuntimeError):
    """The engine's bounded request queue is at queue_depth (backpressure:
    the caller must shed or retry, the engine will not buffer more)."""


class EngineClosedError(RuntimeError):
    """submit() after begin_drain()/close(): the engine no longer admits
    new requests (in-flight and queued work still completes)."""


class EngineConfig:
    """Knobs for the dynamic batcher.

    buckets: batch-size ladder (default: FLAGS_serving_buckets).  An
        EMPTY ladder selects pass-through mode: no concat/pad/split —
        each request dispatches alone with its feed forwarded verbatim
        (the Inferencer path; also the only mode that can carry ragged
        LoD feeds).
    max_batch: admission cap on rows per request (default: the largest
        bucket).
    max_wait_s: how long the oldest queued request may wait for the
        batch to fill before dispatching anyway.
    queue_depth: bounded-queue capacity in requests (backpressure).
    default_timeout_s: deadline applied when submit() passes none.
    """

    def __init__(self, buckets: Optional[Sequence[int]] = None,
                 max_batch: Optional[int] = None,
                 max_wait_s: float = 0.002,
                 queue_depth: int = 256,
                 default_timeout_s: Optional[float] = None):
        self.buckets = (parse_buckets() if buckets is None
                        else parse_buckets(buckets))
        self.max_batch = (int(max_batch) if max_batch is not None
                          else (self.buckets[-1] if self.buckets else 0))
        self.max_wait_s = float(max_wait_s)
        self.queue_depth = int(queue_depth)
        self.default_timeout_s = default_timeout_s


class AotBackend:
    """Adapter over the predict callable load_compiled_inference_model
    returns (or an artifact directory)."""

    def __init__(self, predict_or_dir):
        if isinstance(predict_or_dir, str):
            from ..inference import load_compiled_inference_model

            predict_or_dir = load_compiled_inference_model(predict_or_dir)
        self.predict = predict_or_dir
        self.feed_names = list(self.predict.feed_names)
        self.fetch_names = list(getattr(self.predict, "fetch_names", []))
        self.meta = dict(getattr(self.predict, "meta", {}) or {})

    def __call__(self, feed: Dict[str, Any]) -> List[np.ndarray]:
        return self.predict(feed)


class ExecutorBackend:
    """Adapter over a live Executor + Program (+ Scope): every dispatch
    goes through the executor's compiled-program cache, so the engine and
    any direct exe.run callers share one compile per program signature."""

    def __init__(self, executor, program, fetch_list,
                 scope=None, feed_names: Optional[Sequence[str]] = None):
        self.executor = executor
        self.program = program
        self.fetch_list = list(fetch_list)
        self.scope = scope
        # feed_names=None skips engine-side feed validation (the executor
        # keys its cache on whatever names arrive)
        self.feed_names = list(feed_names) if feed_names is not None else None
        from ..core.framework import Variable

        self.fetch_names = [
            v.name if isinstance(v, Variable) else str(v)
            for v in self.fetch_list
        ]
        self.meta: Dict[str, Any] = {}

    def __call__(self, feed: Dict[str, Any], return_numpy: bool = True):
        from ..core.scope import scope_guard

        if self.scope is not None:
            with scope_guard(self.scope):
                return self.executor.run(
                    self.program, feed=feed, fetch_list=self.fetch_list,
                    return_numpy=return_numpy)
        return self.executor.run(
            self.program, feed=feed, fetch_list=self.fetch_list,
            return_numpy=return_numpy)


def _plan_buckets(backend, requested: Tuple[int, ...]) -> Tuple[Tuple[int, ...], Optional[str]]:
    """The bucket planner: a static-batch artifact (shape polymorphism
    failed at export — meta['symbolic_error'] records why) can only run
    its one exported batch size, so the ladder collapses to it and the
    reason rides on the engine for debuggability."""
    meta = getattr(backend, "meta", None) or {}
    if meta.get("batch") == "static" and requested:
        shapes = meta.get("exported_shapes") or []
        static_b = int(shapes[0][0]) if shapes and shapes[0] else 1
        reason = (
            f"artifact exported with a STATIC batch of {static_b} "
            f"(symbolic batch unavailable: {meta.get('symbolic_error')}); "
            f"ladder {requested} collapsed to ({static_b},)")
        return (static_b,), reason
    return requested, None


class Engine:
    """Thread-safe batching front end over one loaded model."""

    def __init__(self, backend, config: Optional[EngineConfig] = None,
                 name: str = "engine"):
        self.backend = backend
        self.config = config or EngineConfig()
        self.name = name
        buckets, self.bucket_reason = _plan_buckets(
            backend, self.config.buckets)
        self.ladder = BucketLadder(buckets)
        if self.ladder.buckets:
            self.max_batch = min(self.config.max_batch or
                                 self.ladder.max_bucket,
                                 self.ladder.max_bucket)
        else:
            self.max_batch = 0  # pass-through mode

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[Request] = []
        self._closed = False      # no new admissions
        self._stopped = False     # dispatcher exited
        self._inflight = 0        # requests inside the current dispatch
        # first-seen dispatch shapes — the serving compile counters: a
        # "miss" is a batch shape the backend has never seen (a fresh
        # XLA specialization for a symbolic artifact / a fresh jit trace
        # for an executor program), a "hit" reuses one
        self._shapes_seen: set = set()
        self._shape_hits = 0
        self._shape_misses = 0
        self._dispatched_batches = 0
        self._dispatched_rows = 0
        self._occupancy_sum = 0.0

        # trailing feed shapes (everything past the batch dim) each
        # request must match — seeded from the AOT meta when available,
        # learned from the first request otherwise.  Validating at
        # submit() keeps one client's mis-shaped request from failing
        # the innocent requests coalesced into the same micro-batch.
        self._trailing: Dict[str, Tuple[int, ...]] = {}
        for fm in (getattr(backend, "meta", None) or {}).get("feeds", []):
            self._trailing[fm["name"]] = tuple(int(d) for d in fm["shape"][1:])

        # The dispatcher holds only a WEAKREF to the engine between
        # cycles (and parks in bounded waits), so an Engine that is
        # dropped without close() is garbage-collected and its thread
        # exits within ~_IDLE_PARK_S instead of leaking both forever.
        self._thread = threading.Thread(
            target=_dispatch_entry, args=(weakref.ref(self),),
            name=f"serving-{name}", daemon=True)
        self._thread.start()

    # -- submission ----------------------------------------------------

    @classmethod
    def from_artifact(cls, dirname_or_predict,
                      config: Optional[EngineConfig] = None,
                      name: str = "engine") -> "Engine":
        return cls(AotBackend(dirname_or_predict), config=config, name=name)

    @classmethod
    def from_program(cls, executor, program, fetch_list, scope=None,
                     feed_names: Optional[Sequence[str]] = None,
                     config: Optional[EngineConfig] = None,
                     name: str = "engine") -> "Engine":
        return cls(
            ExecutorBackend(executor, program, fetch_list, scope=scope,
                            feed_names=feed_names),
            config=config, name=name)

    def submit(self, feed: Dict[str, Any],
               timeout: Optional[float] = None,
               call_kwargs: Optional[Dict[str, Any]] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the list of
        per-fetch numpy arrays (this request's rows only).

        timeout: seconds until the request's deadline; None uses
        config.default_timeout_s.  call_kwargs forwards extra backend
        keyword args and is only legal in pass-through mode (a padded
        batch serves many requests — per-request backend options cannot
        apply)."""
        obs_on = _flags._VALUES["FLAGS_observability"]
        fut: Future = Future()
        feed_names = self.backend.feed_names
        if feed_names is not None:
            missing = [n for n in feed_names if n not in feed]
            if missing:
                raise KeyError(f"feed is missing {missing}")
            unknown = [n for n in sorted(feed) if n not in set(feed_names)]
            if unknown:
                raise KeyError(
                    f"feed has unknown keys {unknown}; this engine serves "
                    f"feeds {feed_names}")
        if self.ladder.buckets:
            if call_kwargs:
                raise ValueError(
                    "call_kwargs requires pass-through mode (empty bucket "
                    "ladder): a padded batch cannot carry per-request "
                    "backend options")
            rows = request_rows(feed, feed_names or sorted(feed))
            if rows < 1:
                raise ValueError("request must carry at least one row")
            if rows > self.max_batch:
                raise ValueError(
                    f"request has {rows} rows but max_batch={self.max_batch} "
                    f"(ladder {self.ladder.buckets}); split it client-side")
            self._check_trailing(feed, feed_names or sorted(feed))
        else:
            rows = 0  # pass-through: never split
        if timeout is None:
            timeout = self.config.default_timeout_s
        now = time.perf_counter()
        req = Request(
            feed=feed, future=fut, rows=rows, enqueued_at=now,
            deadline=(now + timeout) if timeout is not None else None,
            call_kwargs=dict(call_kwargs) if call_kwargs else None,
        )
        with self._cond:
            if self._closed:
                if obs_on:
                    _smetrics.record_reject("closed")
                raise EngineClosedError(
                    f"engine '{self.name}' is draining/closed")
            if len(self._queue) >= self.config.queue_depth:
                if obs_on:
                    _smetrics.record_reject("queue_full")
                raise QueueFullError(
                    f"engine '{self.name}' queue is at "
                    f"{self.config.queue_depth} requests")
            self._queue.append(req)
            depth = len(self._queue)
            self._cond.notify_all()
        if obs_on:
            _smetrics.record_submit(depth)
        return fut

    def _check_trailing(self, feed: Dict[str, Any],
                        feed_names: Sequence[str]) -> None:
        """Reject a request whose trailing dims disagree with the model
        (AOT meta) or with previously admitted traffic — BEFORE it can
        be coalesced with (and fail) innocent batch-mates."""
        for n in feed_names:
            shape = tuple(int(d) for d in getattr(feed[n], "shape", ())[1:])
            with self._lock:
                want = self._trailing.get(n)
                if want is None:
                    self._trailing[n] = shape
                    continue
            if shape != want:
                raise ValueError(
                    f"feed '{n}' has trailing shape {list(shape)} but this "
                    f"engine serves {list(want)} (batches coalesce "
                    "row-wise; trailing dims must match)")

    def infer(self, feed: Dict[str, Any], timeout: Optional[float] = None,
              call_kwargs: Optional[Dict[str, Any]] = None):
        """Blocking submit: returns the fetch list directly."""
        return self.submit(feed, timeout=timeout,
                           call_kwargs=call_kwargs).result()

    # -- drain / close -------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admissions; the dispatcher finishes in-flight + queued
        work then parks.  SIGNAL-SAFE — it is the PreemptionDrain
        listener, and the handler runs on the main thread, possibly
        while that very thread holds the engine lock inside submit():
        the flag write is a plain GIL-atomic store and the wake-up is a
        best-effort NON-BLOCKING acquire (skipping it only costs the
        dispatcher's bounded park, <= _IDLE_PARK_S, before it sees the
        flag)."""
        self._closed = True
        if self._cond.acquire(blocking=False):
            try:
                self._cond.notify_all()
            finally:
                self._cond.release()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """begin_drain() then wait for the queue and in-flight batch to
        finish.  Returns True when fully drained (timeout=0 polls)."""
        self.begin_drain()
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        with self._cond:
            while self._queue or self._inflight:
                wait = None
                if deadline is not None:
                    wait = deadline - time.perf_counter()
                    if wait <= 0:
                        return False
                self._cond.wait(wait)
        return True

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain, stop the dispatcher thread, and join it.  If the
        drain timed out, whatever is still queued fails with
        EngineClosedError — a stopped dispatcher must never leave a
        future unresolved (callers block in .result())."""
        self.drain(timeout)
        with self._cond:
            self._stopped = True
            leftovers, self._queue = self._queue, []
            self._cond.notify_all()
        for r in leftovers:  # outside the lock: done-callbacks may reenter
            self._fail(r, EngineClosedError(
                f"engine '{self.name}' closed before this request was "
                "dispatched (drain timed out)"))
        self._thread.join(timeout=5.0)

    def attach_drain(self, drain) -> "Engine":
        """Wire a resilience.PreemptionDrain: its SIGTERM/SIGINT notice
        triggers begin_drain(), so a preemption stops admissions while
        queued and in-flight batches complete."""
        drain.on_request(self.begin_drain)
        return self

    def __enter__(self) -> "Engine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def draining(self) -> bool:
        return self._closed

    # -- introspection -------------------------------------------------

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def _counters_locked(self) -> Dict[str, int]:
        return {
            "distinct_shapes": len(self._shapes_seen),
            "miss": self._shape_misses,
            "hit": self._shape_hits,
        }

    def compile_counters(self) -> Dict[str, int]:
        """Serving-side compile accounting: distinct batch shapes ever
        dispatched ('miss' = first sight), bounded by len(buckets) for a
        bucketed engine no matter the request mix."""
        with self._lock:
            return self._counters_locked()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            batches = self._dispatched_batches
            return {
                "batches": batches,
                "rows": self._dispatched_rows,
                "mean_occupancy": (self._occupancy_sum / batches
                                   if batches else 0.0),
                "queue_depth": len(self._queue),
                "buckets": self.ladder.buckets,
                "bucket_reason": self.bucket_reason,
                **self._counters_locked(),
            }

    # -- dispatcher ----------------------------------------------------

    # longest a truly idle dispatcher parks before re-checking the
    # engine weakref in _dispatch_entry — bounds both abandoned-engine
    # thread lifetime and how long close() can lag an empty engine
    _IDLE_PARK_S = 0.5

    def _take_batch(self) -> Tuple[Optional[List[Request]], List[Request]]:
        """Called under the lock.  Pop the next dispatchable batch (or
        None to keep waiting) and the expired requests removed from the
        queue.  Expired futures are completed by the CALLER outside the
        lock: Future.set_exception runs done-callbacks synchronously,
        and a callback touching the engine from under its own lock
        would deadlock the dispatcher."""
        now = time.perf_counter()
        expired = [r for r in self._queue if r.expired(now)]
        if expired:
            self._queue = [r for r in self._queue if not r.expired(now)]
        if not self._queue:
            return None, expired
        if not self.ladder.buckets:
            return [self._queue.pop(0)], expired  # pass-through: 1 at a time
        # greedy FIFO pack up to the largest bucket
        batch: List[Request] = []
        rows = 0
        for r in self._queue:
            if rows + r.rows > self.ladder.max_bucket:
                break
            batch.append(r)
            rows += r.rows
        full = rows >= self.ladder.max_bucket or len(batch) < len(self._queue)
        oldest_wait = now - batch[0].enqueued_at
        if full or oldest_wait >= self.config.max_wait_s or self._closed:
            del self._queue[:len(batch)]
            return batch, expired
        return None, expired

    def _wait_time(self) -> Optional[float]:
        """Called under the lock: how long the dispatcher may sleep —
        until the oldest request's batch-fill window or the earliest
        deadline, whichever is sooner."""
        if not self._queue:
            return None  # idle: park (bounded by _IDLE_PARK_S)
        now = time.perf_counter()
        oldest = self._queue[0].enqueued_at
        wait = max(0.0, self.config.max_wait_s - (now - oldest))
        for r in self._queue:
            if r.deadline is not None:
                wait = min(wait, max(0.0, r.deadline - now))
        return wait

    def _fail(self, req: Request, exc: Exception) -> None:
        """Complete a future exceptionally; never call under the lock."""
        if req.future.set_running_or_notify_cancel():
            req.future.set_exception(exc)
        if _flags._VALUES["FLAGS_observability"] and isinstance(
                exc, RequestTimeoutError):
            _smetrics.record_timeout()

    def _dispatch_cycle(self) -> bool:
        """One dispatcher iteration: take (or wait for) a batch, fail
        whatever expired, run the batch.  Returns False once stopped."""
        with self._cond:
            if self._stopped:
                self._cond.notify_all()
                return False
            batch, expired = self._take_batch()
            if batch is None:
                if self._closed and not self._queue:
                    self._cond.notify_all()  # wake drain() waiters
                if not expired:
                    wait = self._wait_time()
                    self._cond.wait(self._IDLE_PARK_S if wait is None
                                    else min(wait, self._IDLE_PARK_S))
            else:
                self._inflight = len(batch)
        now = time.perf_counter()
        for r in expired:
            self._fail(r, RequestTimeoutError(
                f"request expired after {now - r.enqueued_at:.3f}s in "
                f"queue (deadline {r.deadline - r.enqueued_at:.3f}s)"))
        if batch is None:
            return True
        try:
            self._dispatch(batch)
        finally:
            with self._cond:
                self._inflight = 0
                self._cond.notify_all()
        return True

    def _dispatch(self, batch: List[Request]) -> None:
        obs_on = _flags._VALUES["FLAGS_observability"]
        t0 = time.perf_counter() if obs_on else 0.0
        try:
            if not self.ladder.buckets:
                req = batch[0]
                outs = self.backend(req.feed, **(req.call_kwargs or {}))
                # real feed shapes, not a constant: an executor backend
                # re-traces per shape, and compile_counters must say so
                self._note_shape(tuple(sorted(
                    (n, tuple(getattr(v, "shape", ()) or ()))
                    for n, v in req.feed.items())))
                if req.future.set_running_or_notify_cancel():
                    req.future.set_result(outs)
                rows = bucket = 1
            else:
                rows = sum(r.rows for r in batch)
                bucket = self.ladder.bucket_for(rows)
                feed_names = self.backend.feed_names or sorted(batch[0].feed)
                feed = coalesce(batch, feed_names, bucket)
                self._note_shape(
                    tuple((n,) + tuple(feed[n].shape) for n in feed_names))
                outs = self.backend(feed)
                scatter(batch, outs)
        except Exception as e:  # noqa: BLE001 — backend failure fails the batch
            for r in batch:
                if r.future.done():
                    continue  # scatter resolved it before the raise
                try:
                    r.future.set_exception(e)
                except Exception:  # cancelled between check and set
                    pass
            if obs_on:
                _smetrics.record_batch_error()
            return
        now = time.perf_counter()
        with self._lock:
            self._dispatched_batches += 1
            self._dispatched_rows += rows
            self._occupancy_sum += rows / float(bucket)
        if obs_on:
            _smetrics.record_batch(
                bucket=bucket, rows=rows, latency_s=now - t0)
            for r in batch:
                _smetrics.record_request_latency(now - r.enqueued_at)

    def _note_shape(self, key: Tuple) -> None:
        with self._lock:
            if key in self._shapes_seen:
                self._shape_hits += 1
            else:
                self._shapes_seen.add(key)
                self._shape_misses += 1


def _dispatch_entry(ref: "weakref.ref") -> None:
    """Dispatcher thread body.  Holds the engine STRONGLY only while
    running one cycle; between cycles only the weakref survives, so an
    engine dropped without close() becomes collectable and this thread
    exits on the next _IDLE_PARK_S heartbeat instead of pinning the
    engine (and its backend/executor/scope) forever."""
    while True:
        eng = ref()
        if eng is None or not eng._dispatch_cycle():
            return
        del eng
