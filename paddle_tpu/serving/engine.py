"""Batching inference engine: thread-safe submit(feed) -> Future.

One Engine wraps one loaded model — an AOT StableHLO artifact
(inference/aot.py) or an Executor-compiled Program — behind a bounded
request queue and a single dispatcher thread:

- **submit() is thread-safe and non-blocking**: callers get a
  concurrent.futures.Future; the dispatcher coalesces queued requests
  into micro-batches padded to the bucket ladder (batching.py), runs the
  backend once per batch, and slices per-request rows back out.
- **Backpressure** is a bounded queue: submit raises QueueFullError once
  `queue_depth` requests are pending — callers shed load explicitly
  instead of the engine buffering unboundedly.
- **Deadlines**: submit(feed, timeout=...) arms an absolute deadline; a
  request still queued when it expires fails with RequestTimeoutError
  (requests already inside a dispatched batch always complete — an XLA
  dispatch cannot be recalled).
- **Drain** mirrors resilience.PreemptionDrain semantics: begin_drain()
  stops admissions (submit raises EngineClosedError), the dispatcher
  finishes the in-flight batch and every queued request that still has
  deadline headroom, then parks.  attach_drain(PreemptionDrain) wires
  SIGTERM straight to begin_drain via the drain's listener hook.
- **Compile discipline**: every dispatch is padded to a ladder bucket, so
  the backend sees at most len(buckets) distinct batch shapes for the
  life of the engine.  The engine counts first-seen shapes
  (`compile_counters()`) — the serving analogue of the executor's
  compile-cache hit/miss counters — and tests assert the ladder bound.

Fault isolation (the serving half of the resilience pillar):

- **Batch-level blast radius**: a backend raise inside one dispatch
  fails ONLY that batch's futures — each gets a typed
  EngineInternalError naming the cause — and the dispatcher moves on to
  the next batch.
- **Dispatcher supervision**: an exception that escapes the dispatch
  cycle anyway (a bug outside the protected region) kills the thread;
  the supervisor hook restarts it with the queue preserved, so queued
  futures never strand behind a dead thread.
- **Circuit breaker**: `breaker_threshold` CONSECUTIVE internal errors
  open the breaker — submit() fails fast with EngineUnhealthyError for
  `breaker_cooldown_s`, then half-opens (requests probe the backend);
  one successful dispatch closes it.  Callers shed to a replica instead
  of queueing onto a backend that fails every batch.
- **Overload shedding**: a request whose deadline is already unmeetable
  at submit time — queue depth x the observed per-batch latency p50
  (an engine-local StepStats ring) says it cannot dispatch before it
  expires — is rejected immediately with RequestTimeoutError instead of
  rotting in the queue and timing out after burning its wait.
- **health()**: one snapshot — SERVING/DEGRADED/DRAINING/BROKEN, queue
  depth, breaker state, last-dispatch age, dispatcher liveness, shed
  and restart counts, optional attached KV-pool utilization — exported
  through observability gauges when the flag is on.

Observability (queue depth, batch occupancy, latency histograms,
admission/reject/timeout counters) gates on FLAGS_observability with the
established zero-work disabled path: one dict lookup, no allocation —
tier-1 extends the tracemalloc assertion to submit().  With the flag ON
every request is traced end to end (ISSUE 8): submit() mints a
`trace_id` (on the returned Future and on every typed error), the
request's life is recorded as a cross-thread span tree
(submit -> queued -> dispatch, each span on the thread that ran it) and
tail-sampled into the merged Perfetto trace
(observability/requesttrace.py), latency histograms carry OpenMetrics
exemplars linking their p99 bucket to the trace behind it, and every
lifecycle event lands in the flight recorder (observability/flight.py)
— which auto-dumps a JSONL black box when the breaker trips or health()
enters BROKEN.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import flags as _flags
from ..observability import flight as _flight
from ..observability import requesttrace as _rtrace
from ..observability.stepstats import StepStats
from ..resilience import faultinject as _finject
from . import metrics as _smetrics
from .batching import (
    BucketLadder,
    Request,
    coalesce,
    parse_buckets,
    request_rows,
    scatter,
)

__all__ = [
    "Engine",
    "EngineConfig",
    "EngineClosedError",
    "EngineInternalError",
    "EngineUnhealthyError",
    "QueueFullError",
    "RequestTimeoutError",
    "AotBackend",
    "ExecutorBackend",
]

_log = logging.getLogger("paddle_tpu.serving")


class RequestTimeoutError(TimeoutError):
    """A request's deadline expired before its batch was dispatched —
    either in the queue, or at submit() when deadline-aware admission
    predicts the queue cannot dispatch it in time (shed)."""


class QueueFullError(RuntimeError):
    """The engine's bounded request queue is at queue_depth (backpressure:
    the caller must shed or retry, the engine will not buffer more)."""


class EngineClosedError(RuntimeError):
    """submit() after begin_drain()/close(): the engine no longer admits
    new requests (in-flight and queued work still completes)."""


class EngineInternalError(RuntimeError):
    """A micro-batch's dispatch failed inside the engine (backend raise,
    scatter bug): every future in THAT batch gets this error — naming
    the underlying cause — and the dispatcher survives to serve the next
    batch.  The original exception rides on `cause` / `__cause__`."""

    def __init__(self, cause: BaseException):
        self.cause = cause
        super().__init__(
            f"batch dispatch failed: {type(cause).__name__}: {cause}")
        self.__cause__ = cause


class EngineUnhealthyError(RuntimeError):
    """The circuit breaker is open: `breaker_threshold` consecutive
    batches failed, so submit() fails fast for `breaker_cooldown_s`
    instead of queueing onto a backend that fails everything.  After the
    cool-down the breaker half-opens and requests probe the backend;
    one successful dispatch closes it."""


class EngineConfig:
    """Knobs for the dynamic batcher.

    buckets: batch-size ladder (default: FLAGS_serving_buckets).  An
        EMPTY ladder selects pass-through mode: no concat/pad/split —
        each request dispatches alone with its feed forwarded verbatim
        (the Inferencer path; also the only mode that can carry ragged
        LoD feeds).
    max_batch: admission cap on rows per request (default: the largest
        bucket).
    max_wait_s: how long the oldest queued request may wait for the
        batch to fill before dispatching anyway.
    queue_depth: bounded-queue capacity in requests (backpressure).
    default_timeout_s: deadline applied when submit() passes none.
    breaker_threshold: consecutive internal (batch-dispatch) errors that
        open the circuit breaker (default FLAGS_serving_breaker_threshold).
    breaker_cooldown_s: how long an open breaker fails submit() fast
        before half-opening a probe (default
        FLAGS_serving_breaker_cooldown_s).
    shed_deadlines: deadline-aware admission — reject a request at
        submit() when queue depth x observed per-batch latency p50 says
        it cannot dispatch before its deadline (default True; requests
        without a deadline are never shed).
    """

    def __init__(self, buckets: Optional[Sequence[int]] = None,
                 max_batch: Optional[int] = None,
                 max_wait_s: float = 0.002,
                 queue_depth: int = 256,
                 default_timeout_s: Optional[float] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_cooldown_s: Optional[float] = None,
                 shed_deadlines: bool = True):
        self.buckets = (parse_buckets() if buckets is None
                        else parse_buckets(buckets))
        self.max_batch = (int(max_batch) if max_batch is not None
                          else (self.buckets[-1] if self.buckets else 0))
        self.max_wait_s = float(max_wait_s)
        self.queue_depth = int(queue_depth)
        self.default_timeout_s = default_timeout_s
        self.breaker_threshold = int(
            breaker_threshold if breaker_threshold is not None
            else _flags.flag("serving_breaker_threshold"))
        self.breaker_cooldown_s = float(
            breaker_cooldown_s if breaker_cooldown_s is not None
            else _flags.flag("serving_breaker_cooldown_s"))
        self.shed_deadlines = bool(shed_deadlines)


class AotBackend:
    """Adapter over the predict callable load_compiled_inference_model
    returns (or an artifact directory)."""

    def __init__(self, predict_or_dir):
        if isinstance(predict_or_dir, str):
            from ..inference import load_compiled_inference_model

            predict_or_dir = load_compiled_inference_model(predict_or_dir)
        self.predict = predict_or_dir
        self.feed_names = list(self.predict.feed_names)
        self.fetch_names = list(getattr(self.predict, "fetch_names", []))
        self.meta = dict(getattr(self.predict, "meta", {}) or {})

    def __call__(self, feed: Dict[str, Any]) -> List[np.ndarray]:
        return self.predict(feed)


class ExecutorBackend:
    """Adapter over a live Executor + Program (+ Scope): every dispatch
    goes through the executor's compiled-program cache, so the engine and
    any direct exe.run callers share one compile per program signature."""

    def __init__(self, executor, program, fetch_list,
                 scope=None, feed_names: Optional[Sequence[str]] = None):
        self.executor = executor
        self.program = program
        self.fetch_list = list(fetch_list)
        self.scope = scope
        # feed_names=None skips engine-side feed validation (the executor
        # keys its cache on whatever names arrive)
        self.feed_names = list(feed_names) if feed_names is not None else None
        from ..core.framework import Variable

        self.fetch_names = [
            v.name if isinstance(v, Variable) else str(v)
            for v in self.fetch_list
        ]
        self.meta: Dict[str, Any] = {}

    def __call__(self, feed: Dict[str, Any], return_numpy: bool = True):
        from ..core.scope import scope_guard

        if self.scope is not None:
            with scope_guard(self.scope):
                return self.executor.run(
                    self.program, feed=feed, fetch_list=self.fetch_list,
                    return_numpy=return_numpy)
        return self.executor.run(
            self.program, feed=feed, fetch_list=self.fetch_list,
            return_numpy=return_numpy)


def _plan_buckets(backend, requested: Tuple[int, ...]) -> Tuple[Tuple[int, ...], Optional[str]]:
    """The bucket planner: a static-batch artifact (shape polymorphism
    failed at export — meta['symbolic_error'] records why) can only run
    its one exported batch size, so the ladder collapses to it and the
    reason rides on the engine for debuggability."""
    meta = getattr(backend, "meta", None) or {}
    if meta.get("batch") == "static" and requested:
        shapes = meta.get("exported_shapes") or []
        static_b = int(shapes[0][0]) if shapes and shapes[0] else 1
        reason = (
            f"artifact exported with a STATIC batch of {static_b} "
            f"(symbolic batch unavailable: {meta.get('symbolic_error')}); "
            f"ladder {requested} collapsed to ({static_b},)")
        return (static_b,), reason
    return requested, None


class Engine:
    """Thread-safe batching front end over one loaded model."""

    def __init__(self, backend, config: Optional[EngineConfig] = None,
                 name: str = "engine"):
        self.backend = backend
        self.config = config or EngineConfig()
        self.name = name
        # replica label (set by distributed.Router.add_replica): rides
        # on every flight-recorder event, request trace, and health
        # gauge this engine emits, so per-replica telemetry stays
        # attributable after aggregate_dir() merges process dumps
        self.replica: Optional[str] = None
        buckets, self.bucket_reason = _plan_buckets(
            backend, self.config.buckets)
        self.ladder = BucketLadder(buckets)
        if self.ladder.buckets:
            self.max_batch = min(self.config.max_batch or
                                 self.ladder.max_bucket,
                                 self.ladder.max_bucket)
        else:
            self.max_batch = 0  # pass-through mode

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[Request] = []
        self._closed = False      # no new admissions
        self._stopped = False     # dispatcher exited
        self._inflight = 0        # requests inside the current dispatch
        # first-seen dispatch shapes — the serving compile counters: a
        # "miss" is a batch shape the backend has never seen (a fresh
        # XLA specialization for a symbolic artifact / a fresh jit trace
        # for an executor program), a "hit" reuses one
        self._shapes_seen: set = set()
        self._shape_hits = 0
        self._shape_misses = 0
        self._dispatched_batches = 0
        self._dispatched_rows = 0
        self._occupancy_sum = 0.0

        # fault isolation / supervision state (all under self._lock)
        self._internal_errors = 0         # total failed dispatches
        self._consecutive_errors = 0      # streak feeding the breaker
        self._last_error: Optional[str] = None
        self._breaker_open_until = 0.0    # 0.0: closed; <=now: half-open
        self._breaker_trips = 0
        self._dispatcher_restarts = 0
        self._last_dispatch_ok: Optional[float] = None
        self._shed = 0                    # deadline-aware rejections
        self._close_timed_out = False
        # chaos (FAULT_SERVE_REPLICA_KILL): a killed replica's
        # dispatcher dies WITHOUT restart — models a dead process
        self._replica_killed = False
        # observed per-batch dispatch latency — the shedding estimator's
        # input (engine-local ring: admission control is functional, not
        # telemetry, so it runs regardless of FLAGS_observability)
        self._batch_lat = StepStats(capacity=128)
        # percentile caches keyed by the ring's monotonic count: the
        # submit fast path (p50, deadline shedding) and continuously
        # polled health() (p99) must not re-sort the 128-sample window
        # when nothing new landed
        self._batch_lat_p50: Tuple[int, Optional[float]] = (0, None)
        self._batch_lat_p99: Tuple[int, Optional[float]] = (0, None)
        self._pool = None                 # optional attach_pool target
        # last health() verdict — the flight recorder logs state EDGES
        # (SERVING->BROKEN), not every poll
        self._last_health_state: Optional[str] = None

        # trailing feed shapes (everything past the batch dim) each
        # request must match — seeded from the AOT meta when available,
        # learned from the first request otherwise.  Validating at
        # submit() keeps one client's mis-shaped request from failing
        # the innocent requests coalesced into the same micro-batch.
        self._trailing: Dict[str, Tuple[int, ...]] = {}
        for fm in (getattr(backend, "meta", None) or {}).get("feeds", []):
            self._trailing[fm["name"]] = tuple(int(d) for d in fm["shape"][1:])

        # The dispatcher holds only a WEAKREF to the engine between
        # cycles (and parks in bounded waits), so an Engine that is
        # dropped without close() is garbage-collected and its thread
        # exits within ~_IDLE_PARK_S instead of leaking both forever.
        self._spawn_dispatcher()

    def _flight_record(self, kind: str, **fields) -> None:
        """One engine lifecycle event into the flight recorder, labeled
        with the replica name when this engine serves behind a Router."""
        if self.replica is not None:
            fields.setdefault("replica", self.replica)
        _flight.default_flight().record(kind, engine=self.name, **fields)

    def _spawn_dispatcher(self) -> None:
        self._thread = threading.Thread(
            target=_dispatch_entry, args=(weakref.ref(self),),
            name=f"serving-{self.name}", daemon=True)
        self._thread.start()

    # -- submission ----------------------------------------------------

    @classmethod
    def from_artifact(cls, dirname_or_predict,
                      config: Optional[EngineConfig] = None,
                      name: str = "engine") -> "Engine":
        return cls(AotBackend(dirname_or_predict), config=config, name=name)

    @classmethod
    def from_program(cls, executor, program, fetch_list, scope=None,
                     feed_names: Optional[Sequence[str]] = None,
                     config: Optional[EngineConfig] = None,
                     name: str = "engine") -> "Engine":
        return cls(
            ExecutorBackend(executor, program, fetch_list, scope=scope,
                            feed_names=feed_names),
            config=config, name=name)

    def submit(self, feed: Dict[str, Any],
               timeout: Optional[float] = None,
               call_kwargs: Optional[Dict[str, Any]] = None,
               sampling=None, adapter_id: Optional[str] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the list of
        per-fetch numpy arrays (this request's rows only).

        timeout: seconds until the request's deadline; None uses
        config.default_timeout_s.  call_kwargs forwards extra backend
        keyword args and is only legal in pass-through mode (a padded
        batch serves many requests — per-request backend options cannot
        apply).  sampling: a serving.SamplingParams threaded to the
        backend the same way (pass-through only — it is a PER-REQUEST
        contract; a decode-style backend receives it as the `sampling`
        call kwarg and hands it to DecodeRequest.sampling).
        adapter_id: the model variant to serve this request under
        (ISSUE 19) — same pass-through-only threading; a decode-style
        backend hands it to ``DecodeRequest.adapter_id`` and the
        loop's AdapterPool resolves or typed-rejects it.

        With FLAGS_observability on, the returned Future carries a
        fresh `trace_id` (also attached to every typed error this
        request can fail with) and the request's life is traced
        submit -> dispatch -> completion as a cross-thread span tree —
        kept in the merged Perfetto trace when tail sampling elects it
        (slow / errored / shed / timed out, under
        FLAGS_request_trace_budget).  Off, `fut.trace_id` is None and
        nothing from the observability package runs or allocates."""
        obs_on = _flags._VALUES["FLAGS_observability"]
        if sampling is not None:
            from .sampling import SamplingParams

            if not isinstance(sampling, SamplingParams):
                raise TypeError(
                    f"sampling must be a serving.SamplingParams, got "
                    f"{type(sampling).__name__}")
            call_kwargs = dict(call_kwargs or {}, sampling=sampling)
        if adapter_id is not None:
            if not isinstance(adapter_id, str):
                raise TypeError(
                    f"adapter_id must be a str, got "
                    f"{type(adapter_id).__name__}")
            call_kwargs = dict(call_kwargs or {}, adapter_id=adapter_id)
        fut: Future = Future()
        fut.trace_id = None
        feed_names = self.backend.feed_names
        if feed_names is not None:
            missing = [n for n in feed_names if n not in feed]
            if missing:
                raise KeyError(f"feed is missing {missing}")
            unknown = [n for n in sorted(feed) if n not in set(feed_names)]
            if unknown:
                raise KeyError(
                    f"feed has unknown keys {unknown}; this engine serves "
                    f"feeds {feed_names}")
        if self.ladder.buckets:
            if call_kwargs:
                raise ValueError(
                    "call_kwargs requires pass-through mode (empty bucket "
                    "ladder): a padded batch cannot carry per-request "
                    "backend options")
            rows = request_rows(feed, feed_names or sorted(feed))
            if rows < 1:
                raise ValueError("request must carry at least one row")
            if rows > self.max_batch:
                raise ValueError(
                    f"request has {rows} rows but max_batch={self.max_batch} "
                    f"(ladder {self.ladder.buckets}); split it client-side")
            self._check_trailing(feed, feed_names or sorted(feed))
        else:
            rows = 0  # pass-through: never split
        if timeout is None:
            timeout = self.config.default_timeout_s
        rt = None
        if obs_on:
            rt = _rtrace.default_request_tracer().start()
            fut.trace_id = rt.trace_id
            if self.replica is not None:
                # the replica attribute is the join key a merged
                # (aggregate_dir) view filters kept traces by
                rt.annotate(replica=self.replica)
        now = time.perf_counter()
        req = Request(
            feed=feed, future=fut, rows=rows, enqueued_at=now,
            deadline=(now + timeout) if timeout is not None else None,
            call_kwargs=dict(call_kwargs) if call_kwargs else None,
            trace_id=fut.trace_id, trace=rt,
        )
        with self._cond:
            if self._closed:
                self._reject(rt, EngineClosedError(
                    f"engine '{self.name}' is draining/closed"),
                    "closed", obs_on)
            if self._replica_killed:
                # a chaos-killed replica has no dispatcher and never
                # will — admitting would strand the request in a queue
                # nothing drains; reject typed so the router's raced
                # health cache falls over to a survivor instead
                self._reject(rt, EngineClosedError(
                    f"engine '{self.name}': replica was killed"),
                    "closed", obs_on)
            if self._breaker_open_until > now:
                self._reject(rt, EngineUnhealthyError(
                    f"engine '{self.name}' circuit breaker is open "
                    f"({self._consecutive_errors} consecutive dispatch "
                    f"failures, last: {self._last_error}); retry in "
                    f"{self._breaker_open_until - now:.2f}s"),
                    "breaker_open", obs_on)
            if len(self._queue) >= self.config.queue_depth:
                self._reject(rt, QueueFullError(
                    f"engine '{self.name}' queue is at "
                    f"{self.config.queue_depth} requests"),
                    "queue_full", obs_on)
            if req.deadline is not None and self.config.shed_deadlines:
                est = self._estimate_dispatch_wait_locked()
                if est is not None and now + est >= req.deadline:
                    self._shed += 1
                    self._reject(rt, RequestTimeoutError(
                        f"shed: ~{est:.3f}s of queued work ahead "
                        f"(observed batch p50 x queue depth) already "
                        f"violates this request's {timeout:.3f}s "
                        f"deadline — rejecting at submit instead of "
                        f"expiring in queue"),
                        "deadline_shed", obs_on)
            # a dispatcher that died without its supervisor running
            # (never under normal faults) must not strand the queue
            if not self._stopped and not self._thread.is_alive():
                self._dispatcher_restarts += 1
                self._spawn_dispatcher()
            self._queue.append(req)
            depth = len(self._queue)
            if obs_on:
                # still under the cond: the dispatcher cannot take the
                # batch (it needs this lock) until the submit span and
                # flight event are recorded — otherwise a fast dispatch
                # could finish() the trace before its submit span lands
                rt.event("request.submit", rt.t0, time.perf_counter())
                self._flight_record(
                    "submit", trace_id=fut.trace_id,
                    depth=depth)
            self._cond.notify_all()
        if obs_on:
            _smetrics.record_submit(depth)
        return fut

    def _reject(self, rt, exc: Exception, reason: str,
                obs_on: bool) -> None:
        """Account one rejected submission and raise `exc` (with the
        request's trace_id attached).  Rejections are forced-keep in
        tail sampling — a shed or fast-failed request is exactly the
        kind an operator wants the span tree for."""
        if obs_on:
            _smetrics.record_reject(reason)
            self._flight_record(
                "reject", reason=reason,
                trace_id=rt.trace_id)
            exc.trace_id = rt.trace_id
            _rtrace.default_request_tracer().finish(
                rt, outcome=("shed" if reason == "deadline_shed"
                             else f"rejected_{reason}"))
        raise exc

    def _estimate_dispatch_wait_locked(self) -> Optional[float]:
        """Earliest-possible-dispatch estimate for a NEW request, from
        the work already ahead of it: whole batches the queue holds
        (plus the in-flight one) x the observed per-batch latency p50.
        None when there is nothing ahead or no latency observed yet —
        shedding needs evidence, never a guess."""
        if not self._queue and not self._inflight:
            return None
        p50 = self._batch_lat_p50_cached()
        if p50 is None:
            return None
        if self.ladder.buckets:
            rows_ahead = sum(r.rows for r in self._queue)
            batches_ahead = -(-rows_ahead // self.ladder.max_bucket)
        else:
            batches_ahead = len(self._queue)
        if self._inflight:
            batches_ahead += 1
        return batches_ahead * p50

    def _batch_lat_p50_cached(self) -> Optional[float]:
        """Observed batch-latency p50, re-sorted only when the ring has
        new samples — the steady-state submit path pays one int compare,
        not an O(K log K) window sort under self._cond."""
        count = self._batch_lat.count
        cached_at, p50 = self._batch_lat_p50
        if count != cached_at:
            p50 = self._batch_lat.percentile(50)
            self._batch_lat_p50 = (count, p50)
        return p50

    def _batch_lat_p99_cached(self) -> Optional[float]:
        """Same one-sort-per-change scheme for the p99 health() polls."""
        count = self._batch_lat.count
        cached_at, p99 = self._batch_lat_p99
        if count != cached_at:
            p99 = self._batch_lat.percentile(99)
            self._batch_lat_p99 = (count, p99)
        return p99

    def _check_trailing(self, feed: Dict[str, Any],
                        feed_names: Sequence[str]) -> None:
        """Reject a request whose trailing dims disagree with the model
        (AOT meta) or with previously admitted traffic — BEFORE it can
        be coalesced with (and fail) innocent batch-mates."""
        for n in feed_names:
            shape = tuple(int(d) for d in getattr(feed[n], "shape", ())[1:])
            with self._lock:
                want = self._trailing.get(n)
                if want is None:
                    self._trailing[n] = shape
                    continue
            if shape != want:
                raise ValueError(
                    f"feed '{n}' has trailing shape {list(shape)} but this "
                    f"engine serves {list(want)} (batches coalesce "
                    "row-wise; trailing dims must match)")

    def infer(self, feed: Dict[str, Any], timeout: Optional[float] = None,
              call_kwargs: Optional[Dict[str, Any]] = None):
        """Blocking submit: returns the fetch list directly."""
        return self.submit(feed, timeout=timeout,
                           call_kwargs=call_kwargs).result()

    # -- drain / close -------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admissions; the dispatcher finishes in-flight + queued
        work then parks.  SIGNAL-SAFE — it is the PreemptionDrain
        listener, and the handler runs on the main thread, possibly
        while that very thread holds the engine lock inside submit():
        the flag write is a plain GIL-atomic store and the wake-up is a
        best-effort NON-BLOCKING acquire (skipping it only costs the
        dispatcher's bounded park, <= _IDLE_PARK_S, before it sees the
        flag)."""
        self._closed = True
        if self._cond.acquire(blocking=False):
            try:
                self._cond.notify_all()
            finally:
                self._cond.release()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """begin_drain() then wait for the queue and in-flight batch to
        finish.  Returns True when fully drained (timeout=0 polls)."""
        self.begin_drain()
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        with self._cond:
            while self._queue or self._inflight:
                wait = None
                if deadline is not None:
                    wait = deadline - time.perf_counter()
                    if wait <= 0:
                        return False
                self._cond.wait(wait)
        return True

    # how long close() waits for the dispatcher thread to exit; a join
    # that outlasts this surfaces as stats()["close_timed_out"]
    _JOIN_TIMEOUT_S = 5.0

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain, stop the dispatcher thread, and join it.  If the
        drain timed out, whatever is still queued fails with
        EngineClosedError — a stopped dispatcher must never leave a
        future unresolved (callers block in .result()).  A dispatcher
        that outlives the join (a backend call that never returns) is
        logged and surfaced as stats()['close_timed_out'] instead of
        close() returning as if the shutdown completed cleanly."""
        self.drain(timeout)
        with self._cond:
            self._stopped = True
            leftovers, self._queue = self._queue, []
            self._cond.notify_all()
        for r in leftovers:  # outside the lock: done-callbacks may reenter
            self._fail(r, EngineClosedError(
                f"engine '{self.name}' closed before this request was "
                "dispatched (drain timed out)"))
        self._thread.join(timeout=self._JOIN_TIMEOUT_S)
        if self._thread.is_alive():
            with self._lock:
                self._close_timed_out = True
            _log.warning(
                "engine '%s': dispatcher thread still alive %.1fs after "
                "close() — a backend dispatch is stuck; its batch's "
                "futures remain pending", self.name, self._JOIN_TIMEOUT_S)

    def attach_drain(self, drain) -> "Engine":
        """Wire a resilience.PreemptionDrain: its SIGTERM/SIGINT notice
        triggers begin_drain(), so a preemption stops admissions while
        queued and in-flight batches complete."""
        drain.on_request(self.begin_drain)
        return self

    def __enter__(self) -> "Engine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def draining(self) -> bool:
        return self._closed

    # -- introspection -------------------------------------------------

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def _counters_locked(self) -> Dict[str, int]:
        return {
            "distinct_shapes": len(self._shapes_seen),
            "miss": self._shape_misses,
            "hit": self._shape_hits,
        }

    def compile_counters(self) -> Dict[str, int]:
        """Serving-side compile accounting: distinct batch shapes ever
        dispatched ('miss' = first sight), bounded by len(buckets) for a
        bucketed engine no matter the request mix."""
        with self._lock:
            return self._counters_locked()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            batches = self._dispatched_batches
            return {
                "batches": batches,
                "rows": self._dispatched_rows,
                "mean_occupancy": (self._occupancy_sum / batches
                                   if batches else 0.0),
                "queue_depth": len(self._queue),
                "buckets": self.ladder.buckets,
                "bucket_reason": self.bucket_reason,
                "internal_errors": self._internal_errors,
                "breaker_trips": self._breaker_trips,
                "dispatcher_restarts": self._dispatcher_restarts,
                "shed": self._shed,
                "close_timed_out": self._close_timed_out,
                "replica_killed": self._replica_killed,
                **self._counters_locked(),
            }

    # -- dispatcher ----------------------------------------------------

    # longest a truly idle dispatcher parks before re-checking the
    # engine weakref in _dispatch_entry — bounds both abandoned-engine
    # thread lifetime and how long close() can lag an empty engine
    _IDLE_PARK_S = 0.5

    def _take_batch(self) -> Tuple[Optional[List[Request]], List[Request]]:
        """Called under the lock.  Pop the next dispatchable batch (or
        None to keep waiting) and the expired requests removed from the
        queue.  Expired futures are completed by the CALLER outside the
        lock: Future.set_exception runs done-callbacks synchronously,
        and a callback touching the engine from under its own lock
        would deadlock the dispatcher."""
        now = time.perf_counter()
        expired = [r for r in self._queue if r.expired(now)]
        if expired:
            self._queue = [r for r in self._queue if not r.expired(now)]
        if not self._queue:
            return None, expired
        if not self.ladder.buckets:
            return [self._queue.pop(0)], expired  # pass-through: 1 at a time
        # greedy FIFO pack up to the largest bucket
        batch: List[Request] = []
        rows = 0
        for r in self._queue:
            if rows + r.rows > self.ladder.max_bucket:
                break
            batch.append(r)
            rows += r.rows
        full = rows >= self.ladder.max_bucket or len(batch) < len(self._queue)
        oldest_wait = now - batch[0].enqueued_at
        if full or oldest_wait >= self.config.max_wait_s or self._closed:
            del self._queue[:len(batch)]
            return batch, expired
        return None, expired

    def _wait_time(self) -> Optional[float]:
        """Called under the lock: how long the dispatcher may sleep —
        until the oldest request's batch-fill window or the earliest
        deadline, whichever is sooner."""
        if not self._queue:
            return None  # idle: park (bounded by _IDLE_PARK_S)
        now = time.perf_counter()
        oldest = self._queue[0].enqueued_at
        wait = max(0.0, self.config.max_wait_s - (now - oldest))
        for r in self._queue:
            if r.deadline is not None:
                wait = min(wait, max(0.0, r.deadline - now))
        return wait

    def _finish_trace(self, req: Request, outcome: str, t_end: float,
                      dispatch: Optional[Tuple[float, float, dict]] = None,
                      ) -> bool:
        """Close one request's span tree: the queue-wait span on the
        SUBMITTING thread, an optional (t0, t1, args) dispatch span on
        the calling thread, then the tail-sampling decision.  Returns
        whether the trace was kept — the one shape every completion
        path (success, batch failure, timeout, close) shares."""
        rt = req.trace
        if rt is None:
            return False
        q_end = dispatch[0] if dispatch is not None else t_end
        rt.event("request.queued", req.enqueued_at, q_end,
                 tid=rt.tid, thread_name=rt.thread_name)
        if dispatch is not None:
            rt.event("request.dispatch", dispatch[0], dispatch[1],
                     **dispatch[2])
        return _rtrace.default_request_tracer().finish(
            rt, outcome=outcome, t_end=t_end)

    def _fail(self, req: Request, exc: Exception) -> None:
        """Complete a future exceptionally; never call under the lock."""
        if req.trace is not None and _flags._VALUES["FLAGS_observability"]:
            exc.trace_id = req.trace_id
            outcome = ("timeout" if isinstance(exc, RequestTimeoutError)
                       else "closed")
            self._flight_record(
                "request_fail", outcome=outcome,
                trace_id=req.trace_id, error=type(exc).__name__)
            self._finish_trace(req, outcome, time.perf_counter())
        if req.future.set_running_or_notify_cancel():
            req.future.set_exception(exc)
        if _flags._VALUES["FLAGS_observability"] and isinstance(
                exc, RequestTimeoutError):
            _smetrics.record_timeout()

    def _dispatch_cycle(self) -> bool:
        """One dispatcher iteration: take (or wait for) a batch, fail
        whatever expired, run the batch.  Returns False once stopped."""
        # chaos: a raise HERE is outside every protected region — the
        # dispatcher thread dies and the supervisor must restart it
        _finject.serve_dispatch_raise("thread")
        # chaos: replica kill — the dispatcher dies and the supervisor
        # must NOT restart it (a dead process has no supervisor); fires
        # between batches so no in-flight work is lost, only queued
        # requests fail over
        if _finject.serve_replica_kill(self.replica or self.name):
            with self._lock:
                self._replica_killed = True
            raise RuntimeError(
                f"faultinject: replica {self.replica or self.name} "
                "killed")
        with self._cond:
            if self._stopped:
                self._cond.notify_all()
                return False
            batch, expired = self._take_batch()
            if batch is None:
                if self._closed and not self._queue:
                    self._cond.notify_all()  # wake drain() waiters
                if not expired:
                    wait = self._wait_time()
                    self._cond.wait(self._IDLE_PARK_S if wait is None
                                    else min(wait, self._IDLE_PARK_S))
            else:
                self._inflight = len(batch)
        now = time.perf_counter()
        for r in expired:
            self._fail(r, RequestTimeoutError(
                f"request expired after {now - r.enqueued_at:.3f}s in "
                f"queue (deadline {r.deadline - r.enqueued_at:.3f}s)"))
        if batch is None:
            return True
        try:
            self._dispatch(batch)
        finally:
            with self._cond:
                self._inflight = 0
                self._cond.notify_all()
        return True

    def _dispatch(self, batch: List[Request]) -> None:
        obs_on = _flags._VALUES["FLAGS_observability"]
        # t0 always: the batch-latency ring feeds deadline shedding
        t0 = time.perf_counter()
        if obs_on:
            self._flight_record(
                "dispatch", n_requests=len(batch),
                trace_ids=[r.trace_id for r in batch])
        try:
            _finject.serve_slow_step()
            _finject.serve_dispatch_raise("batch")
            if not self.ladder.buckets:
                req = batch[0]
                outs = self.backend(req.feed, **(req.call_kwargs or {}))
                # real feed shapes, not a constant: an executor backend
                # re-traces per shape, and compile_counters must say so
                self._note_shape(tuple(sorted(
                    (n, tuple(getattr(v, "shape", ()) or ()))
                    for n, v in req.feed.items())))
                if req.future.set_running_or_notify_cancel():
                    req.future.set_result(outs)
                rows = bucket = 1
            else:
                rows = sum(r.rows for r in batch)
                bucket = self.ladder.bucket_for(rows)
                feed_names = self.backend.feed_names or sorted(batch[0].feed)
                feed = coalesce(batch, feed_names, bucket)
                self._note_shape(
                    tuple((n,) + tuple(feed[n].shape) for n in feed_names))
                outs = self.backend(feed)
                scatter(batch, outs)
        except Exception as e:  # noqa: BLE001 — backend failure fails the batch
            # pass-through mode forwards ONE request's own feed/kwargs
            # verbatim, so a raise there is that request's error: the
            # future gets the ORIGINAL exception and the breaker is not
            # advanced — one bad client must not open the breaker on
            # everyone (the request-level blast radius).  A bucketed
            # dispatch serves many requests: the failure is the
            # engine's, wrapped as EngineInternalError and counted
            # toward the breaker.
            batched = bool(self.ladder.buckets)
            err = EngineInternalError(e) if batched else e
            if obs_on:
                # typed errors carry the trace ids they failed:
                # EngineInternalError serves a whole batch, so it gets
                # the list (and the first id on .trace_id for the
                # common single-request case); a pass-through error is
                # one request's own and gets its id directly
                try:
                    err.trace_ids = [r.trace_id for r in batch]
                    err.trace_id = batch[0].trace_id
                except AttributeError:
                    pass  # a __slots__ exception from a backend:
                    # losing the annotation must not kill the dispatcher
                self._flight_record(
                    "batch_fail",
                    error=f"{type(e).__name__}: {e}",
                    trace_ids=[r.trace_id for r in batch])
            # count BEFORE resolving futures: a caller that catches the
            # batch error and immediately checks health()/submits must
            # see the breaker already advanced
            self._note_internal_error(e, trip=batched)
            # failed dispatches are service-time evidence too: without
            # them a slow-failing outage would leave the shed estimator
            # trusting a stale fast-success p50
            now = time.perf_counter()
            self._batch_lat.record(now - t0)
            if obs_on:
                for r in batch:
                    if r.trace is None:
                        continue
                    # scatter() may have resolved the first futures
                    # before the raise: those requests SUCCEEDED from
                    # their callers' view and must not be error-labeled
                    # (or force-kept) in the trace
                    ok = False
                    if r.future.done():
                        try:
                            ok = r.future.exception() is None
                        except Exception:  # cancelled
                            ok = False
                    kept = self._finish_trace(
                        r, "ok" if ok else "error", now,
                        dispatch=(t0, now, {} if ok else
                                  {"error": type(e).__name__}))
                    if ok:
                        _smetrics.record_request_latency(
                            now - r.enqueued_at,
                            trace_id=r.trace_id if kept else None)
            for r in batch:
                if r.future.done():
                    continue  # scatter resolved it before the raise
                try:
                    r.future.set_exception(err)
                except Exception:  # cancelled between check and set
                    pass
            if obs_on:
                _smetrics.record_batch_error()
            return
        now = time.perf_counter()
        with self._lock:
            self._dispatched_batches += 1
            self._dispatched_rows += rows
            self._occupancy_sum += rows / float(bucket)
            # a successful dispatch is the breaker's close/probe signal
            breaker_was_open = self._breaker_open_until != 0.0
            self._consecutive_errors = 0
            self._breaker_open_until = 0.0
            self._last_dispatch_ok = now
        self._batch_lat.record(now - t0)
        if obs_on:
            if breaker_was_open:
                self._flight_record("breaker_close")
            _smetrics.record_batch(
                bucket=bucket, rows=rows, latency_s=now - t0)
            for r in batch:
                if r.trace is not None:
                    r.trace.annotate(rows=r.rows, bucket=bucket)
                kept = self._finish_trace(
                    r, "ok", now, dispatch=(t0, now, {"bucket": bucket}))
                # exemplars only reference KEPT traces — a link into
                # the merged trace must resolve
                _smetrics.record_request_latency(
                    now - r.enqueued_at,
                    trace_id=r.trace_id if kept else None)

    def _note_shape(self, key: Tuple) -> None:
        with self._lock:
            if key in self._shapes_seen:
                self._shape_hits += 1
            else:
                self._shapes_seen.add(key)
                self._shape_misses += 1

    # -- supervision / breaker -----------------------------------------

    def _note_internal_error(self, exc: BaseException,
                             trip: bool = True) -> None:
        """Count one failed dispatch; trip the breaker after
        breaker_threshold consecutive failures.  trip=False (the
        pass-through request-error path) counts the total but leaves the
        breaker streak alone — a per-request failure is not an engine
        health signal."""
        now = time.perf_counter()
        with self._lock:
            self._internal_errors += 1
            self._last_error = f"{type(exc).__name__}: {exc}"
            if not trip:
                return
            self._consecutive_errors += 1
            if (self._consecutive_errors >= self.config.breaker_threshold
                    and self._breaker_open_until <= now):
                # closed/half-open -> open (a re-failed probe re-trips)
                self._breaker_open_until = (
                    now + self.config.breaker_cooldown_s)
                self._breaker_trips += 1
                tripped = True
            else:
                tripped = False
        if tripped:
            _log.warning(
                "engine '%s': circuit breaker OPEN after %d consecutive "
                "dispatch failures (last: %s); fast-failing submits for "
                "%.2fs", self.name, self.config.breaker_threshold,
                self._last_error, self.config.breaker_cooldown_s)
            if _flags._VALUES["FLAGS_observability"]:
                _smetrics.record_breaker_trip()
                # the black box: a breaker trip IS the incident — dump
                # the last N lifecycle events as a JSONL artifact
                fl = _flight.default_flight()
                fl.record("breaker_open", engine=self.name,
                          consecutive_errors=self.config.breaker_threshold,
                          last_error=self._last_error,
                          cooldown_s=self.config.breaker_cooldown_s)
                try:
                    fl.dump("breaker_trip")
                except OSError as e:  # an unwritable dir must not
                    _log.warning(     # poison the dispatch path
                        "flight-recorder dump failed: %s", e)

    def _on_dispatcher_death(self, exc: BaseException) -> None:
        """Supervisor: the dispatcher thread died outside every
        protected region.  Restart it with the queue preserved (the
        queue lives on the engine, not the thread) unless the engine is
        already stopped — or chaos-killed (FAULT_SERVE_REPLICA_KILL):
        a killed replica process has no supervisor, so the engine goes
        BROKEN and its queued requests fail typed for callers (the
        router, serve_bench --chaos --replicas) to fail over."""
        self._note_internal_error(exc)
        with self._cond:
            if self._replica_killed:
                self._stopped = True
                leftovers, self._queue = self._queue, []
                self._cond.notify_all()
            elif self._stopped:
                self._cond.notify_all()
                return
            else:
                leftovers = None
                self._dispatcher_restarts += 1
        if leftovers is not None:
            _log.warning(
                "engine '%s': replica killed by chaos; failing %d "
                "queued requests over to survivors", self.name,
                len(leftovers))
            if _flags._VALUES["FLAGS_observability"]:
                self._flight_record(
                    "replica_kill", queued=len(leftovers),
                    error=f"{type(exc).__name__}: {exc}")
            for r in leftovers:  # outside the lock: done-callbacks
                self._fail(r, EngineInternalError(exc))
            return
        _log.warning(
            "engine '%s': dispatcher thread died (%s: %s); restarting "
            "with %d queued requests preserved", self.name,
            type(exc).__name__, exc, self.queue_depth())
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_dispatcher_restart()
            self._flight_record(
                "dispatcher_restart",
                error=f"{type(exc).__name__}: {exc}",
                queued=self.queue_depth())
        self._spawn_dispatcher()

    # -- health ---------------------------------------------------------

    def attach_pool(self, pool) -> "Engine":
        """Report a KVCachePool's utilization in health() — for engines
        fronting a decode loop."""
        self._pool = pool
        return self

    def health(self) -> Dict[str, Any]:
        """One operator-facing snapshot of engine liveness:

        - state: SERVING (healthy), DEGRADED (failing dispatches or a
          near-full queue, still admitting), DRAINING (no admissions,
          finishing queued work), BROKEN (breaker open, or the
          dispatcher is dead)
        - queue/breaker/dispatcher/shed/last-dispatch detail backing it

        Exported through observability gauges when FLAGS_observability
        is on."""
        now = time.perf_counter()
        with self._lock:
            depth = len(self._queue)
            cap = self.config.queue_depth
            breaker_open = self._breaker_open_until > now
            half_open = (self._breaker_open_until != 0.0
                         and not breaker_open)
            alive = self._thread.is_alive()
            last_ok = self._last_dispatch_ok
            snap = {
                "queue_depth": depth,
                "queue_capacity": cap,
                "inflight": self._inflight,
                "breaker": {
                    "state": ("open" if breaker_open
                              else "half_open" if half_open else "closed"),
                    "consecutive_errors": self._consecutive_errors,
                    "threshold": self.config.breaker_threshold,
                    "trips": self._breaker_trips,
                    "cooldown_remaining_s": max(
                        0.0, self._breaker_open_until - now),
                    "last_error": self._last_error,
                },
                "internal_errors": self._internal_errors,
                "last_dispatch_age_s": (
                    now - last_ok if last_ok is not None else None),
                "dispatcher_alive": alive,
                "dispatcher_restarts": self._dispatcher_restarts,
                "shed": self._shed,
                "close_timed_out": self._close_timed_out,
                # the admission latency ring the shed estimator reads —
                # operators see the same numbers shedding decides from
                "batch_latency_p50_s": self._batch_lat_p50_cached(),
                "batch_latency_p99_s": self._batch_lat_p99_cached(),
                "batch_latency_window": min(self._batch_lat.count,
                                            self._batch_lat.capacity),
            }
            draining = self._closed
            degraded = (self._consecutive_errors > 0
                        or depth >= 0.8 * cap)
            stopped = self._stopped
            killed = self._replica_killed
        if breaker_open or killed or (not alive and not stopped):
            state = "BROKEN"
        elif draining:
            state = "DRAINING"
        elif degraded:
            state = "DEGRADED"
        else:
            state = "SERVING"
        snap["state"] = state
        # atomic read-and-swap: concurrent health() pollers must see
        # each state edge exactly once (one BROKEN transition = one
        # flight dump, not one per poller)
        with self._lock:
            prev = self._last_health_state
            self._last_health_state = state
        if _flags._VALUES["FLAGS_observability"] and state != prev:
            fl = _flight.default_flight()
            fl.record("health", engine=self.name,
                      state=state, previous=prev)
            if state == "BROKEN":
                # entering BROKEN is the other dump trigger (a dead
                # dispatcher reaches here without a breaker trip)
                try:
                    fl.dump("health_broken")
                except OSError as e:
                    _log.warning(
                        "flight-recorder dump failed: %s", e)
        if self._pool is not None:
            st = self._pool.stats()
            snap["pool"] = {
                "used_pages": st["used_pages"],
                "num_pages": st["num_pages"],
                "utilization": st["used_pages"] / float(st["num_pages"]),
            }
        else:
            snap["pool"] = None
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_health(
                state, depth,
                breaker_open=breaker_open,
                pool_utilization=(snap["pool"] or {}).get("utilization"),
                pool=getattr(self._pool, "name", "kv"),
                replica=self.replica)
        return snap


def _dispatch_entry(ref: "weakref.ref") -> None:
    """Dispatcher thread body.  Holds the engine STRONGLY only while
    running one cycle; between cycles only the weakref survives, so an
    engine dropped without close() becomes collectable and this thread
    exits on the next _IDLE_PARK_S heartbeat instead of pinning the
    engine (and its backend/executor/scope) forever.

    A raise escaping the cycle (batch failures never do — _dispatch
    contains them) hands off to the engine's supervisor hook, which
    restarts the dispatcher with the queue preserved."""
    while True:
        eng = ref()
        if eng is None:
            return
        try:
            alive = eng._dispatch_cycle()
        except BaseException as e:  # noqa: BLE001 — supervisor restarts
            eng._on_dispatcher_death(e)
            return
        if not alive:
            return
        del eng
