"""Dynamic batcher primitives: bucket ladder, request records, padding.

The throughput lever at serving time is batch occupancy, not kernel speed
(arxiv 2605.25645: the Gemma-on-TPU serving comparison): a request served
alone leaves most of the chip idle, so queued requests are coalesced into
micro-batches.  But XLA compiles per shape — batching at ARBITRARY sizes
would compile every batch size traffic ever produces.  The ladder fixes
that: batches are padded up to a small fixed set of bucket sizes (default
``FLAGS_serving_buckets`` = 1/2/4/8/16), so the polymorphic-batch StableHLO
artifact compiles once per bucket and never again, regardless of the
request mix.  Padding replicates the last real row (a zeros pad can push
exotic models through log/divide domain errors; a replicated row is always
in-distribution) and the pad rows are sliced off before completion.

Numerics contract: coalesce/pad/slice itself is EXACT — a request's rows
come back bit-identical to running the model once at the bucket's batch
size with those rows in it.  Whether that also equals an unbatched
predict() bit-for-bit depends on the model: rows are independent in
inference-mode programs, but XLA specializes kernels per batch size, and
a large matmul may pick a different reduction tiling at batch 1 vs batch
8 (observed: lenet5 rows differ by ~1 ulp across buckets; the small-conv
tier-1 model is bit-stable, and that exact equality is asserted).
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import flags as _flags

__all__ = ["BucketLadder", "Request", "pad_rows", "parse_buckets"]


def parse_buckets(spec: Optional[str] = None) -> Tuple[int, ...]:
    """Parse a ladder spec ("1,2,4,8,16") into sorted unique bucket sizes;
    `None` reads FLAGS_serving_buckets."""
    if spec is None:
        spec = _flags.flag("serving_buckets")
    if isinstance(spec, (tuple, list)):
        vals = [int(v) for v in spec]
    else:
        vals = [int(p) for p in str(spec).split(",") if p.strip()]
    if not vals:
        return ()
    if any(v <= 0 for v in vals):
        raise ValueError(f"bucket sizes must be positive, got {vals}")
    return tuple(sorted(set(vals)))


class BucketLadder:
    """Smallest-bucket-that-fits lookup over a fixed sorted ladder."""

    def __init__(self, buckets: Sequence[int]):
        self.buckets: Tuple[int, ...] = tuple(sorted(set(int(b) for b in buckets)))
        if any(b <= 0 for b in self.buckets):
            raise ValueError(f"bucket sizes must be positive: {self.buckets}")

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1] if self.buckets else 0

    def bucket_for(self, rows: int) -> int:
        """Smallest bucket >= rows (rows must fit the ladder)."""
        for b in self.buckets:
            if rows <= b:
                return b
        raise ValueError(
            f"{rows} rows exceed the largest bucket {self.max_bucket} "
            f"(ladder {self.buckets})")

    def __len__(self) -> int:
        return len(self.buckets)

    def __repr__(self) -> str:
        return f"BucketLadder{self.buckets}"


@dataclasses.dataclass
class Request:
    """One queued inference request: a feed dict with a shared leading
    batch dim (`rows`; 0 for pass-through mode where the engine never
    splits), its completion future, and deadline bookkeeping."""

    feed: Dict[str, Any]
    future: Future
    rows: int
    enqueued_at: float
    deadline: Optional[float] = None  # absolute perf_counter time
    call_kwargs: Optional[Dict[str, Any]] = None  # pass-through mode only
    # request-scoped tracing (observability/requesttrace.py): both None
    # whenever FLAGS_observability is off — submit() mints them only on
    # the enabled path (the zero-allocation contract)
    trace_id: Optional[str] = None
    trace: Optional[Any] = None  # the live RequestTrace

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (now if now is not None else time.perf_counter()) >= self.deadline


def request_rows(feed: Dict[str, Any], feed_names: Sequence[str]) -> int:
    """Validate that every feed shares one leading batch dim; return it."""
    rows = None
    for n in feed_names:
        a = feed[n]
        shape = getattr(a, "shape", None)
        if not shape:
            raise ValueError(
                f"feed '{n}' has no leading batch dimension (shape {shape})")
        if rows is None:
            rows = int(shape[0])
        elif int(shape[0]) != rows:
            raise ValueError(
                f"feed '{n}' has {int(shape[0])} rows but other feeds in "
                f"this request have {rows}; one request = one batch")
    return int(rows or 0)


def pad_rows(stacked: np.ndarray, bucket: int) -> np.ndarray:
    """Pad [rows, ...] up to [bucket, ...] by replicating the last row."""
    rows = stacked.shape[0]
    if rows == bucket:
        return stacked
    reps = (bucket - rows,) + (1,) * (stacked.ndim - 1)
    return np.concatenate([stacked, np.tile(stacked[-1:], reps)], axis=0)


def coalesce(requests: List[Request], feed_names: Sequence[str],
             bucket: int) -> Dict[str, np.ndarray]:
    """Concatenate the requests' feeds row-wise and pad to `bucket`."""
    feed: Dict[str, np.ndarray] = {}
    for n in feed_names:
        parts = [np.asarray(r.feed[n]) for r in requests]
        stacked = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
        feed[n] = pad_rows(stacked, bucket)
    return feed


def scatter(requests: List[Request], outputs: Sequence[np.ndarray]) -> None:
    """Slice each request's rows back out of the batched outputs and
    complete its future."""
    row = 0
    for r in requests:
        sliced = [np.asarray(o[row:row + r.rows]) for o in outputs]
        row += r.rows
        if not r.future.set_running_or_notify_cancel():
            continue  # caller cancelled while queued
        r.future.set_result(sliced)
