"""Draft-model-free speculative drafting: prompt-lookup n-grams.

Decode is bandwidth-bound — every model step streams the full weight
set plus the live KV pages to produce ONE token per sequence.  The
multi-token verify step (kernels/paged_attention.py ``q_lengths`` arm)
can commit up to d+1 tokens for nearly the same HBM traffic, IF
something proposes plausible continuations.  A second "draft model" is
the classic proposer, but it costs HBM for its own weights and a second
compiled program; PROMPT LOOKUP gets surprisingly far for free on the
traffic this tier actually serves — templated prompts, code, retrieval
contexts, and multi-turn chat all repeat themselves, and a greedy
decode that enters a repeating span is a self-match: match the last
``n`` committed tokens against the prompt + generation history, and
propose the tokens that followed the most recent earlier occurrence.

The drafter is pure host bookkeeping — no device memory, no extra
model step, no speculative weights — so a miss costs only the wasted
query rows of the verify step (KV bytes are flat in d), and acceptance
is decided by the verifier, never trusted.

INCREMENTAL INDEX (ROADMAP speculative item 3).  The original lookup
was a reversed O(len) suffix scan per step — fine at test scale, not
at 32k contexts where every decode step would re-walk the whole
history.  With a ``seq_id`` the drafter now maintains a per-sequence
suffix map (n-gram -> ascending occurrence positions) updated as
tokens COMMIT: each call diffs the handed context against the cached
one at the longest common prefix, rewinds the index over rolled-back
tokens (``truncate_seq`` rejections land here — the next call's
context is shorter/diverged, and every n-gram the dead tokens
registered pops back off), then extends it over the new commits.  Per
step that is O(d * max_ngram) map maintenance plus an O(occurrences)
probe lookup — the per-step n-gram SCAN no longer grows with context
length.  (A linear residual remains: the loop still hands the FULL
visible context every call, so each call pays one O(len) list
copy + common-prefix compare.  That is a cheap branch-free pass next
to the old per-n-gram pattern scan, and it is what keeps the context
the source of truth: the index is only an accelerator, a
desynchronized cache is impossible by construction, and a stateless
call (``seq_id=None``) still works and must agree exactly — the
parity tests hold the two paths identical over random commit/rollback
histories.  Passing deltas instead of contexts would shave the copy
but put correctness at the mercy of every caller's bookkeeping.)
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["PromptLookupDrafter"]


class _SeqIndex:
    """One sequence's committed tokens + suffix map.

    ``occ`` maps each n-gram (min_ngram..max_ngram) to the ASCENDING
    list of its occurrence start positions; ``added[j]`` records the
    n-gram keys registered when token j committed (the ones ENDING at
    j), so a rollback pops exactly what the dead tokens added."""

    __slots__ = ("tokens", "occ", "added")

    def __init__(self) -> None:
        self.tokens: List[int] = []
        self.occ: Dict[Tuple[int, ...], List[int]] = {}
        self.added: List[List[Tuple[int, ...]]] = []

    def sync(self, ctx: List[int], min_ngram: int, max_ngram: int) -> None:
        """Re-sync to `ctx`: rewind past the longest common prefix,
        then extend over the new commits."""
        old = self.tokens
        common = 0
        limit = min(len(old), len(ctx))
        while common < limit and old[common] == ctx[common]:
            common += 1
        for j in range(len(old) - 1, common - 1, -1):
            for key in self.added[j]:
                stack = self.occ[key]
                stack.pop()  # occurrences end-ordered: the tail is j's
                if not stack:
                    del self.occ[key]
        del self.tokens[common:]
        del self.added[common:]
        for j in range(common, len(ctx)):
            tok = ctx[j]
            self.tokens.append(tok)
            keys: List[Tuple[int, ...]] = []
            for n in range(min_ngram, max_ngram + 1):
                i = j - n + 1
                if i < 0:
                    break
                key = tuple(self.tokens[i:j + 1])
                self.occ.setdefault(key, []).append(i)
                keys.append(key)
            self.added.append(keys)


class PromptLookupDrafter:
    """Propose up to ``max_draft`` continuation tokens by n-gram lookup.

    For ``n`` from ``max_ngram`` down to ``min_ngram``: take the last
    ``n`` context tokens as the probe, find its most RECENT earlier
    occurrence in the context, and propose the tokens that followed it.
    Longer probes win (they are more specific); among equal-length
    matches the most recent wins (local structure beats distant
    structure in chat/code traffic).  Returns [] when nothing matches —
    the loop then runs a plain d=0 decode step for that sequence, so a
    drafter can never make a step WORSE than unspeculated decode.

    ``seq_id`` routes the call through the incremental per-sequence
    suffix index (module docstring) — the serving loop passes it (the
    ``stateful`` attribute advertises support) and calls
    :meth:`release` when a sequence retires; an LRU cap
    (``max_sequences``) bounds host memory regardless.

    ``corpus`` (ISSUE 16) plugs in a SHARED n-gram source — any object
    exposing ``ngram_continuation(probe, limit) -> List[int]``, in
    practice the serving loop's ``PrefixCache`` riding its
    prompt-prefix trie.  Own-history matching runs first and a
    full-length own match wins outright (self-structure is the most
    specific signal); otherwise the corpus is probed longest-n-gram
    first and the LONGER of the two proposals is drafted (ties keep
    own-history).  Shared-prefix fleet traffic thus drafts from
    continuations OTHER sequences already inserted — a cold sequence
    entering a popular template speculates from step one."""

    stateful = True  # the loop may pass seq_id= and call release()
    # source of the most recent proposal ("own" | "corpus") — set by
    # every draft() call, read by the loop for verify attribution
    last_source = "own"
    # the loop may pass adapter_id= to confine corpus drafting to one
    # tenant's namespace (ISSUE 19) — see draft()
    adapter_aware = True

    def __init__(self, max_draft: int = 4, max_ngram: int = 3,
                 min_ngram: int = 1, max_sequences: int = 1024,
                 corpus=None):
        if max_draft < 1:
            raise ValueError(f"max_draft must be >= 1, got {max_draft}")
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"{min_ngram}..{max_ngram}")
        if max_sequences < 1:
            raise ValueError(
                f"max_sequences must be >= 1, got {max_sequences}")
        self.max_draft = int(max_draft)
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self.max_sequences = int(max_sequences)
        if corpus is not None and not hasattr(corpus,
                                              "ngram_continuation"):
            raise TypeError(
                "corpus must expose ngram_continuation(probe, limit)")
        self.corpus = corpus
        self._index: "OrderedDict[int, _SeqIndex]" = OrderedDict()

    def release(self, seq_id: int) -> None:
        """Drop a retired sequence's index (the loop calls this on
        retirement; the LRU cap covers anyone who forgets)."""
        self._index.pop(seq_id, None)

    def tracked_sequences(self) -> int:
        return len(self._index)

    def draft(self, context: Sequence[int], max_draft: int = None,
              seq_id: Optional[int] = None,
              adapter_id: Optional[str] = None) -> List[int]:
        """Propose continuation tokens for `context` (prompt + generated
        history, oldest first).  `max_draft` caps the proposal below
        the drafter's own limit (the loop passes the sequence's
        remaining max_new headroom).  With `seq_id` the incremental
        index answers the probe; without it a one-shot reversed scan
        does (identical output, O(len) per call).  `adapter_id`
        confines the CORPUS probe to that tenant's namespace (ISSUE
        19): own-history matching is per-sequence and needs no
        scoping, but the shared trie must not draft one tenant's
        continuations into another's verify slots."""
        limit = self.max_draft if max_draft is None else \
            min(self.max_draft, int(max_draft))
        # draft-source attribution (ISSUE 20): which n-gram source won
        # THIS proposal — the loop reads it right after draft() to
        # label the verify outcome, so an operator can see whether the
        # corpus trie or own-history is earning the acceptance rate
        self.last_source = "own"
        if limit < 1:
            return []
        ctx = [int(t) for t in context]
        if seq_id is None:
            own = self._scan_draft(ctx, limit)
        else:
            idx = self._index.get(seq_id)
            if idx is None:
                idx = _SeqIndex()
                self._index[seq_id] = idx
                while len(self._index) > self.max_sequences:
                    self._index.popitem(last=False)
            else:
                self._index.move_to_end(seq_id)
            idx.sync(ctx, self.min_ngram, self.max_ngram)
            own = self._indexed_draft(idx, ctx, limit)
        if len(own) < limit and self.corpus is not None:
            corp = self._corpus_draft(ctx, limit, adapter_id)
            if len(corp) > len(own):
                self.last_source = "corpus"
                return corp
        return own

    def _corpus_draft(self, ctx: List[int], limit: int,
                      adapter_id: Optional[str] = None) -> List[int]:
        """Probe the shared corpus longest-n-gram first (more specific
        probes win); a full-length continuation returns outright, the
        longest partial one is the cross-n fallback — the same decision
        rule as own-history matching.  Unlike the self-match scan the
        corpus probe may use the FULL suffix (n up to max_ngram, not
        max_ngram capped at len-1): occurrences there are other
        sequences' chains, so there is no suffix-matches-itself case to
        exclude."""
        L = len(ctx)
        best: List[int] = []
        for n in range(min(self.max_ngram, L), self.min_ngram - 1, -1):
            if adapter_id is None:
                # base namespace — the two-arg shape keeps custom
                # corpora without the adapter_id kwarg working
                raw = self.corpus.ngram_continuation(ctx[L - n:], limit)
            else:
                raw = self.corpus.ngram_continuation(
                    ctx[L - n:], limit, adapter_id=adapter_id)
            got = [int(t) for t in raw]
            if len(got) == limit:
                return got
            if len(got) > len(best):
                best = got
        return best

    def _indexed_draft(self, idx: _SeqIndex, ctx: List[int],
                       limit: int) -> List[int]:
        """The scan's exact decision rule answered from the suffix map:
        walk the probe's occurrences newest-first; a full-length
        continuation wins outright, the longest partial is the cross-n
        fallback (matches near the end truncate — the self-repetition
        case)."""
        L = len(ctx)
        best: List[int] = []
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            probe = tuple(ctx[L - n:])
            for i in reversed(idx.occ.get(probe, ())):
                if i >= L - n:
                    continue  # the suffix itself is not a match
                out = ctx[i + n:i + n + limit]
                if len(out) == limit:
                    return out
                if len(out) > len(best):
                    best = out
        return best

    def _scan_draft(self, ctx: List[int], limit: int) -> List[int]:
        """Stateless reversed suffix scan — the original O(len) rule,
        kept as the seq_id-free path and the parity oracle the index is
        tested against."""
        L = len(ctx)
        best: List[int] = []
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            probe = ctx[L - n:]
            # most recent earlier occurrence: scan right-to-left over
            # start positions whose match ends before the suffix
            # itself.  A match too close to the end truncates its
            # continuation (the self-repetition case — a decode cycle's
            # freshest match is always near the end), so a full-length
            # continuation wins outright and the LONGEST partial one is
            # kept as the cross-n fallback
            for i in range(L - n - 1, -1, -1):
                if ctx[i:i + n] == probe:
                    out = ctx[i + n:i + n + limit]
                    if len(out) == limit:
                        return out
                    if len(out) > len(best):
                        best = out
        return best
