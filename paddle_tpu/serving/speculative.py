"""Draft-model-free speculative drafting: prompt-lookup n-grams.

Decode is bandwidth-bound — every model step streams the full weight
set plus the live KV pages to produce ONE token per sequence.  The
multi-token verify step (kernels/paged_attention.py ``q_lengths`` arm)
can commit up to d+1 tokens for nearly the same HBM traffic, IF
something proposes plausible continuations.  A second "draft model" is
the classic proposer, but it costs HBM for its own weights and a second
compiled program; PROMPT LOOKUP gets surprisingly far for free on the
traffic this tier actually serves — templated prompts, code, retrieval
contexts, and multi-turn chat all repeat themselves, and a greedy
decode that enters a repeating span is a self-match: match the last
``n`` committed tokens against the prompt + generation history, and
propose the tokens that followed the most recent earlier occurrence.

The drafter is pure host bookkeeping — no device memory, no extra
model step, no speculative weights — so a miss costs only the wasted
query rows of the verify step (KV bytes are flat in d), and acceptance
is decided by the verifier, never trusted.

``PromptLookupDrafter`` is deliberately stateless across calls: the
loop hands it each sequence's full visible context (prompt + generated
tokens) every step, so quarantine/rollback can never desynchronize a
cached index.  Contexts at serving scale are a few thousand tokens and
the scan is a reversed O(n * len) suffix walk from the longest n-gram
down — cheap next to a model step; an incremental hash index is the
obvious upgrade if profiles ever say otherwise.
"""

from __future__ import annotations

from typing import List, Sequence

__all__ = ["PromptLookupDrafter"]


class PromptLookupDrafter:
    """Propose up to ``max_draft`` continuation tokens by n-gram lookup.

    For ``n`` from ``max_ngram`` down to ``min_ngram``: take the last
    ``n`` context tokens as the probe, find its most RECENT earlier
    occurrence in the context, and propose the tokens that followed it.
    Longer probes win (they are more specific); among equal-length
    matches the most recent wins (local structure beats distant
    structure in chat/code traffic).  Returns [] when nothing matches —
    the loop then runs a plain d=0 decode step for that sequence, so a
    drafter can never make a step WORSE than unspeculated decode."""

    def __init__(self, max_draft: int = 4, max_ngram: int = 3,
                 min_ngram: int = 1):
        if max_draft < 1:
            raise ValueError(f"max_draft must be >= 1, got {max_draft}")
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"{min_ngram}..{max_ngram}")
        self.max_draft = int(max_draft)
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def draft(self, context: Sequence[int],
              max_draft: int = None) -> List[int]:
        """Propose continuation tokens for `context` (prompt + generated
        history, oldest first).  `max_draft` caps the proposal below
        the drafter's own limit (the loop passes the sequence's
        remaining max_new headroom)."""
        limit = self.max_draft if max_draft is None else \
            min(self.max_draft, int(max_draft))
        if limit < 1:
            return []
        ctx = [int(t) for t in context]
        L = len(ctx)
        best: List[int] = []
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            probe = ctx[L - n:]
            # most recent earlier occurrence: scan right-to-left over
            # start positions whose match ends before the suffix
            # itself.  A match too close to the end truncates its
            # continuation (the self-repetition case — a decode cycle's
            # freshest match is always near the end), so a full-length
            # continuation wins outright and the LONGEST partial one is
            # kept as the cross-n fallback
            for i in range(L - n - 1, -1, -1):
                if ctx[i:i + n] == probe:
                    out = ctx[i + n:i + n + limit]
                    if len(out) == limit:
                        return out
                    if len(out) > len(best):
                        best = out
        return best
