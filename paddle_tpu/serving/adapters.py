"""Multi-tenant serving: a paged, refcounted pool of batched LoRA
adapters — per-request model variants over ONE base checkpoint.

Millions of users means thousands of cheap fine-tuned variants of one
base model (the Gemma fine-tune-and-serve lifecycle), not N full
checkpoints or N fleets.  A LoRA adapter is a per-layer low-rank delta
``W' = W + A @ B`` on the attention (wq/wk/wv/wo) and MLP (w1/w2)
projections; serving it means applying ``y += (x @ A) @ B`` per row —
cheap enough that one continuous-batching step can MIX tenants.

This module supplies the weight-side machinery; the decode step
(serving/generate.py) supplies the batched apply:

- :class:`AdapterPool` — the KV-page discipline generalized to weight
  pages.  Registered adapters live host-side as the tier of record
  (numpy, CRC-stamped at registration — the kvtier park/fetch bar:
  a corrupted payload is a typed rejection at fault-in, never garbage
  weights).  A bounded set of DEVICE slots holds the hot adapters as
  zero-padded packed arrays ``A_pack[slot, layer, d_in, max_rank]`` /
  ``B_pack[slot, layer, max_rank, d_out]`` per projection; slot 0 is
  permanently all-zero (the base-model identity — a base row gathers
  exact-zero deltas, so the mixed batch needs no masking).  Cold
  adapters FAULT IN on first acquire, LRU-evicting a refcount-zero
  resident ("spill" — the host copy remains); an in-flight adapter
  (refcount > 0) is never evicted, and a pool with no evictable slot
  rejects typed (:class:`AdapterPoolFullError`).
- The decode loop acquires at admission (refcount++, BEFORE any KV
  page is claimed — an unloadable adapter costs nothing) and releases
  at retirement/quarantine.  Each live row carries its slot index;
  the step gathers ``A_pack[slots, layer]`` per projection (the same
  scalar-prefetch page-table idiom as paged attention — the packed
  shapes are FIXED, so one compile serves every tenant mix) and the
  zoo's ``lora_decode`` entry prices the gather bytes chip-lessly.
- ``publish`` / ``retire`` are the hot-update seam
  (``FleetController.rolling_adapter_update`` drains each replica,
  swaps, and rejoins — the rolling_upgrade recipe): both refuse while
  the adapter is in flight, so a variant can never change under a
  decoding sequence.
- :func:`merge_adapter_params` is the correctness oracle: dense
  per-request weight merge, which the tenant-mixed batched apply must
  match token-for-token (tests/test_adapters.py holds it there across
  GQA x int8 x prefix-cache x speculation arms).

Chaos: ``FAULT_SERVE_ADAPTER_CORRUPT`` flips one byte of the next
registered adapter's host payload AFTER its CRC is recorded; the first
fault-in must reject it typed (:class:`AdapterCorruptError`) and drop
the registration.

Sizing math (README "Multi-tenant serving"): device bytes are
``(slots+1) * n_layer * sum(d_in*max_rank + max_rank*d_out) * 4`` over
the adapted projections — at rank r << d this is ~``2*r/d`` of one
extra checkpoint per slot, which is why thousands of registered
tenants fit one chip with a handful of resident slots.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import flags as _flags
from ..resilience import faultinject as _finject
from . import metrics as _smetrics

__all__ = [
    "ADAPTER_PROJECTIONS",
    "AdapterCorruptError",
    "AdapterError",
    "AdapterGeometryError",
    "AdapterHostFullError",
    "AdapterInUseError",
    "AdapterMismatchError",
    "AdapterNotRegisteredError",
    "AdapterPool",
    "AdapterPoolFullError",
    "adapter_gather_bytes_per_step",
    "adapter_proj_dims",
    "make_adapter",
    "merge_adapter_params",
]

# the projections a LoRA delta may target, in apply order.  K/V deltas
# change cached KV content — the reason the prefix cache namespaces by
# adapter id (a base-model cached prefix must never serve a tenant).
ADAPTER_PROJECTIONS = ("wq", "wk", "wv", "wo", "w1", "w2")


class AdapterError(RuntimeError):
    """Base of every typed adapter failure — the decode loop catches
    exactly this at admission and rejects the one request (its result
    carries the error; no KV page was claimed)."""


class AdapterNotRegisteredError(AdapterError):
    """The request names an adapter_id the pool has never seen (or one
    already retired) — a per-request typed rejection."""


class AdapterGeometryError(AdapterError):
    """Registration-time validation: wrong projection name, rank,
    dtype, or A/B shape for this model geometry."""


class AdapterInUseError(AdapterError):
    """publish/retire refused: the adapter is acquired by >= 1 live
    sequence — a variant must never change under a decoding row."""


class AdapterPoolFullError(AdapterError):
    """Fault-in found no free device slot and every resident adapter
    is in flight (refcount > 0) — the pool is sized too small for the
    concurrent tenant mix."""


class AdapterHostFullError(AdapterError):
    """The bounded host tier cannot hold this registration within
    ``host_bytes`` — retire cold tenants or raise the bound."""


class AdapterCorruptError(AdapterError):
    """The host payload failed its registration-time CRC at fault-in —
    the registration is dropped (never loaded as garbage weights) and
    the tenant must re-register."""


class AdapterMismatchError(AdapterError):
    """A KV payload (parked session, cross-process handoff) was
    produced under a DIFFERENT adapter than the resuming request's —
    adapter deltas on wq/wk/wv change the cached K/V itself, so the
    resume must reset/re-prefill instead of silently decoding a wrong
    variant."""


def adapter_proj_dims(cfg) -> Dict[str, Tuple[int, int]]:
    """(d_in, d_out) per adaptable projection for one DecodeConfig —
    the geometry registrations validate against (K/V project to the
    cfg's KV heads, so a GQA model's wk/wv adapters are narrower)."""
    d = int(cfg.d_model)
    d_kv = int(cfg.num_kv_heads) * int(cfg.head_dim)
    return {
        "wq": (d, d), "wk": (d, d_kv), "wv": (d, d_kv), "wo": (d, d),
        "w1": (d, int(cfg.d_inner)), "w2": (int(cfg.d_inner), d),
    }


def adapter_gather_bytes_per_step(cfg, rank: int, rows: int,
                                  projections: Sequence[str]
                                  = ADAPTER_PROJECTIONS) -> float:
    """Analytic bytes one step's per-row adapter gather moves: every
    adapter-bearing row reads its A/B slices for each layer and
    projection (fp32 packed width = the pool's max_rank).  The zoo's
    ``lora_decode`` entry prices the same pattern chip-lessly; the
    serve_bench --tenants gate banks this per step."""
    dims = adapter_proj_dims(cfg)
    per_row = sum(d_in * rank + rank * d_out
                  for d_in, d_out in (dims[p] for p in projections))
    return float(rows) * cfg.n_layer * per_row * 4.0


def make_adapter(cfg, rank: int, seed: int = 0, scale: float = 0.05,
                 projections: Sequence[str] = ADAPTER_PROJECTIONS,
                 ) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
    """Deterministic test/bench adapter: per-layer A at 1/sqrt(fan_in)
    scale, B shrunk by `scale` so the delta perturbs logits without
    swamping the base model (rank-r LoRA init convention, except B is
    nonzero so the variant actually diverges)."""
    rng = np.random.RandomState(seed)
    dims = adapter_proj_dims(cfg)
    L = int(cfg.n_layer)
    out: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    for p in projections:
        d_in, d_out = dims[p]
        A = (rng.standard_normal((L, d_in, rank))
             / np.sqrt(d_in)).astype(np.float32)
        B = (rng.standard_normal((L, rank, d_out))
             * scale / np.sqrt(rank)).astype(np.float32)
        out[p] = (A, B)
    return out


def merge_adapter_params(params: Dict, weights: Dict) -> Dict:
    """Dense per-tenant weight merge ``W' = W + A @ B`` — the
    sequential-oracle arm the batched per-row apply is held
    token-identical to.  Returns a new params dict (layer dicts copied;
    unadapted tensors shared)."""
    merged = dict(params)
    layers = []
    for li, lp in enumerate(params["layers"]):
        lp2 = dict(lp)
        for proj, (A, B) in weights.items():
            lp2[proj] = (np.asarray(lp[proj], np.float32)
                         + np.asarray(A[li], np.float32)
                         @ np.asarray(B[li], np.float32))
        layers.append(lp2)
    merged["layers"] = layers
    return merged


class _HostAdapter:
    """One registered adapter's host-tier state (the tier of record)."""

    __slots__ = ("adapter_id", "rank", "weights", "nbytes", "crc",
                 "refcount", "slot", "tick", "fault_ins")

    def __init__(self, adapter_id: str, rank: int,
                 weights: Dict[str, Tuple[np.ndarray, np.ndarray]],
                 nbytes: int, crc: int):
        self.adapter_id = adapter_id
        self.rank = rank
        self.weights = weights       # proj -> (A [L,din,r], B [L,r,dout])
        self.nbytes = nbytes
        self.crc = crc
        self.refcount = 0            # live sequences decoding with it
        self.slot: Optional[int] = None  # device slot when resident
        self.tick = 0                # LRU clock
        self.fault_ins = 0


def _crc_weights(weights: Dict[str, Tuple[np.ndarray, np.ndarray]]) -> int:
    crc = 0
    for proj in sorted(weights):
        A, B = weights[proj]
        crc = zlib.crc32(np.ascontiguousarray(A).view(np.uint8), crc)
        crc = zlib.crc32(np.ascontiguousarray(B).view(np.uint8), crc)
    return crc & 0xFFFFFFFF


class AdapterPool:
    """Paged batched-LoRA adapter pool over one model geometry.

    Wire it to the decode loop (or a fleet replica) and submit
    requests carrying ``adapter_id``::

        pool = AdapterPool(cfg, slots=4, max_rank=8)
        pool.register_adapter("tenant-a", make_adapter(cfg, rank=4,
                                                       seed=1))
        loop = ContinuousBatchingLoop(params, cfg, kv_pool,
                                      adapter_pool=pool)
        loop.run([DecodeRequest(prompt, n, adapter_id="tenant-a"),
                  DecodeRequest(prompt, n)])   # mixed-tenant batch

    ``slots`` device slots hold resident adapters (slot 0 is extra and
    permanently the all-zero identity); ``max_rank`` is the packed
    width lower-rank adapters zero-pad into (zero pad columns/rows
    contribute exact zeros, so padding never changes the math);
    ``host_bytes`` bounds the registration tier (0 = unbounded)."""

    def __init__(self, cfg, slots: int = 4, max_rank: int = 4,
                 host_bytes: int = 0, name: str = "adapters"):
        if slots < 1:
            raise ValueError("slots must be >= 1")
        if max_rank < 1:
            raise ValueError("max_rank must be >= 1")
        if host_bytes < 0:
            raise ValueError("host_bytes must be >= 0 (0 = unbounded)")
        self.cfg = cfg
        self.slots = int(slots)
        self.max_rank = int(max_rank)
        self.host_bytes = int(host_bytes)
        self.name = name
        self.dims = adapter_proj_dims(cfg)
        self._lock = threading.RLock()
        self._reg: Dict[str, _HostAdapter] = {}
        self._slot_of: Dict[int, str] = {}   # device slot -> adapter_id
        self._free_slots: List[int] = list(range(self.slots, 0, -1))
        self._packs = None  # proj -> [A_pack, B_pack] (built lazily)
        self._tick = 0
        self._stats = {
            "registered_total": 0, "hits": 0, "fault_ins": 0,
            "spills": 0, "evictions": 0, "corrupt_drops": 0,
            "acquires": 0, "releases": 0, "host_bytes": 0,
        }

    # -- registration (the host tier of record) -------------------------

    def register_adapter(self, adapter_id: str, weights: Dict,
                         ) -> int:
        """Validate + CRC-stamp a tenant's low-rank weights into the
        host tier.  ``weights`` maps projection name -> (A, B) with
        A [n_layer, d_in, rank] and B [n_layer, rank, d_out]; a missing
        projection is an exact-zero delta.  Returns the payload bytes.
        Typed raises: :class:`AdapterGeometryError` (shape/rank/dtype),
        :class:`AdapterHostFullError` (bounded tier), ValueError on a
        duplicate id (``publish`` replaces, registration never
        silently overwrites)."""
        if not isinstance(adapter_id, str) or not adapter_id:
            raise AdapterGeometryError(
                f"adapter_id must be a non-empty str, got {adapter_id!r}")
        canon, rank, nbytes = self._validate(adapter_id, weights)
        with self._lock:
            if adapter_id in self._reg:
                raise ValueError(
                    f"adapter {adapter_id!r} is already registered — "
                    "publish() is the replace seam")
            if self.host_bytes and \
                    self._stats["host_bytes"] + nbytes > self.host_bytes:
                raise AdapterHostFullError(
                    f"adapter pool '{self.name}' host tier holds "
                    f"{self._stats['host_bytes']} of {self.host_bytes} "
                    f"bytes; {adapter_id!r} needs {nbytes}")
            e = _HostAdapter(adapter_id, rank, canon, nbytes,
                             _crc_weights(canon))
            if _finject.serve_adapter_corrupt():
                # chaos: silent host corruption AFTER the CRC stamp —
                # the first fault-in must reject typed, never load
                # garbage weights
                first = canon[sorted(canon)[0]][0]
                first.reshape(-1).view(np.uint8)[0] ^= 0xFF
            self._reg[adapter_id] = e
            self._stats["registered_total"] += 1
            self._stats["host_bytes"] += nbytes
        self._note_event("load")
        self._note_gauges()
        return nbytes

    def _validate(self, adapter_id: str, weights: Dict):
        if not isinstance(weights, dict) or not weights:
            raise AdapterGeometryError(
                f"adapter {adapter_id!r}: weights must be a non-empty "
                "dict of projection -> (A, B)")
        L = int(self.cfg.n_layer)
        canon: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        rank = None
        nbytes = 0
        for proj in sorted(weights):
            if proj not in self.dims:
                raise AdapterGeometryError(
                    f"adapter {adapter_id!r}: unknown projection "
                    f"{proj!r} (adaptable: {ADAPTER_PROJECTIONS})")
            try:
                A, B = weights[proj]
            except (TypeError, ValueError):
                raise AdapterGeometryError(
                    f"adapter {adapter_id!r}: weights[{proj!r}] must "
                    "be an (A, B) pair")
            A, B = np.asarray(A), np.asarray(B)
            for nm, arr in (("A", A), ("B", B)):
                if not np.issubdtype(arr.dtype, np.floating):
                    raise AdapterGeometryError(
                        f"adapter {adapter_id!r}: {proj}.{nm} dtype "
                        f"{arr.dtype} is not floating")
            A = np.ascontiguousarray(A, np.float32)
            B = np.ascontiguousarray(B, np.float32)
            d_in, d_out = self.dims[proj]
            r = A.shape[-1] if A.ndim == 3 else -1
            if A.shape != (L, d_in, r) or B.shape != (L, r, d_out):
                raise AdapterGeometryError(
                    f"adapter {adapter_id!r}: {proj} wants A "
                    f"[{L}, {d_in}, r] / B [{L}, r, {d_out}], got "
                    f"A {A.shape} / B {B.shape}")
            if rank is None:
                rank = int(r)
            elif int(r) != rank:
                raise AdapterGeometryError(
                    f"adapter {adapter_id!r}: mixed ranks ({rank} vs "
                    f"{r} on {proj}) — one rank per adapter")
            canon[proj] = (A, B)
            nbytes += A.nbytes + B.nbytes
        if not 1 <= rank <= self.max_rank:
            raise AdapterGeometryError(
                f"adapter {adapter_id!r}: rank {rank} outside "
                f"[1, max_rank={self.max_rank}]")
        return canon, rank, nbytes

    def loadable(self, adapter_id: str) -> bool:
        """Admission probe: is this id registered?  (Fault-in may
        still reject a corrupted payload typed — the probe keeps the
        cheap unknown-tenant case from reaching allocation.)"""
        with self._lock:
            return adapter_id in self._reg

    def registered_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._reg)

    def resident_ids(self) -> List[str]:
        with self._lock:
            return sorted(aid for aid, e in self._reg.items()
                          if e.slot is not None)

    # -- acquire / release (the decode loop's admission surface) --------

    def acquire(self, adapter_id: str) -> int:
        """Pin the adapter for one live sequence and return its device
        slot (faulting it in first if cold).  Every typed failure
        leaves the pool untouched — the loop rejects the one request
        before any KV page is claimed."""
        with self._lock:
            e = self._reg.get(adapter_id)
            if e is None:
                raise AdapterNotRegisteredError(
                    f"adapter {adapter_id!r} is not registered in pool "
                    f"'{self.name}'")
            if e.slot is None:
                self._fault_in(e)
            else:
                self._stats["hits"] += 1
            e.refcount += 1
            e.tick = self._tick
            self._tick += 1
            self._stats["acquires"] += 1
            slot = e.slot
        self._note_gauges()
        return slot

    def release(self, adapter_id: str) -> None:
        """Drop one sequence's pin (retirement/quarantine).  The
        adapter stays resident — eviction is lazy, at the next
        fault-in that needs its slot."""
        with self._lock:
            e = self._reg.get(adapter_id)
            if e is None or e.refcount <= 0:
                raise ValueError(
                    f"release without acquire for adapter "
                    f"{adapter_id!r} in pool '{self.name}'")
            e.refcount -= 1
            self._stats["releases"] += 1

    def _fault_in(self, e: _HostAdapter) -> None:
        """Host -> device load (caller holds the lock): CRC-verify the
        payload, find a slot (LRU-spilling a refcount-zero resident),
        and write the zero-padded pack rows."""
        if _crc_weights(e.weights) != e.crc:
            # drop the registration: a corrupt payload must never be
            # retried into a tenant forever
            self._reg.pop(e.adapter_id, None)
            self._stats["host_bytes"] -= e.nbytes
            self._stats["corrupt_drops"] += 1
            self._stats["evictions"] += 1
            self._note_event("evict")
            raise AdapterCorruptError(
                f"adapter {e.adapter_id!r} failed its registration CRC "
                "at fault-in — registration dropped, never loaded as "
                "garbage weights")
        if self._free_slots:
            slot = self._free_slots.pop()
        else:
            victim = min(
                (v for v in self._reg.values()
                 if v.slot is not None and v.refcount == 0),
                key=lambda v: v.tick, default=None)
            if victim is None:
                raise AdapterPoolFullError(
                    f"adapter pool '{self.name}' has no evictable slot "
                    f"({self.slots} slots, all in flight) for "
                    f"{e.adapter_id!r}")
            slot = victim.slot
            victim.slot = None
            self._slot_of.pop(slot, None)
            self._stats["spills"] += 1
            self._note_event("spill")
        self._write_slot(slot, e.weights)
        e.slot = slot
        e.fault_ins += 1
        self._slot_of[slot] = e.adapter_id
        self._stats["fault_ins"] += 1
        self._note_event("fault_in")

    # -- the device packs (what the decode step gathers) ----------------

    def _ensure_packs(self):
        if self._packs is None:
            import jax.numpy as jnp

            L, r = int(self.cfg.n_layer), self.max_rank
            self._packs = {
                proj: [jnp.zeros((self.slots + 1, L, d_in, r),
                                 jnp.float32),
                       jnp.zeros((self.slots + 1, L, r, d_out),
                                 jnp.float32)]
                for proj, (d_in, d_out) in self.dims.items()
            }
        return self._packs

    def _write_slot(self, slot: int, weights: Dict) -> None:
        """Overwrite pack row `slot` with zero-padded A/B (padding the
        FULL row, so a lower-rank tenant reusing a wider predecessor's
        slot leaves no stale columns behind)."""
        packs = self._ensure_packs()
        L, r = int(self.cfg.n_layer), self.max_rank
        for proj, (d_in, d_out) in self.dims.items():
            A = np.zeros((L, d_in, r), np.float32)
            B = np.zeros((L, r, d_out), np.float32)
            w = weights.get(proj)
            if w is not None:
                rk = w[0].shape[-1]
                A[:, :, :rk] = w[0]
                B[:, :rk, :] = w[1]
            packs[proj][0] = packs[proj][0].at[slot].set(A)
            packs[proj][1] = packs[proj][1].at[slot].set(B)

    def _clear_slot(self, slot: int) -> None:
        packs = self._ensure_packs()
        for proj in self.dims:
            packs[proj][0] = packs[proj][0].at[slot].set(0.0)
            packs[proj][1] = packs[proj][1].at[slot].set(0.0)

    def device_arrays(self) -> Dict[str, Tuple]:
        """proj -> (A_pack, B_pack) for the step's per-row gather.
        Fixed [slots+1, ...] shapes — one compile per batch geometry
        regardless of which tenants are resident."""
        packs = self._ensure_packs()
        with self._lock:
            return {proj: (p[0], p[1]) for proj, p in packs.items()}

    def gather_bytes_per_step(self, rows: int) -> float:
        """Analytic adapter-gather bytes for `rows` adapter-bearing
        rows in one step (packed width = max_rank)."""
        return adapter_gather_bytes_per_step(self.cfg, self.max_rank,
                                             rows)

    # -- hot publish / retire (the rolling-upgrade seam) ----------------

    def publish(self, adapter_id: str, weights: Dict) -> int:
        """Register-or-replace: the hot-update seam
        ``rolling_adapter_update`` drives replica by replica.  Refuses
        (:class:`AdapterInUseError`) while the adapter is in flight —
        a drained replica never is."""
        with self._lock:
            e = self._reg.get(adapter_id)
            if e is not None:
                if e.refcount > 0:
                    raise AdapterInUseError(
                        f"adapter {adapter_id!r} is pinned by "
                        f"{e.refcount} live sequence(s) — drain before "
                        "publishing")
                self._drop(e)
        return self.register_adapter(adapter_id, weights)

    def retire(self, adapter_id: str) -> None:
        """Unregister a tenant: host payload dropped, device slot
        freed (zeroed).  Typed raises on unknown or in-flight ids."""
        with self._lock:
            e = self._reg.get(adapter_id)
            if e is None:
                raise AdapterNotRegisteredError(
                    f"adapter {adapter_id!r} is not registered in pool "
                    f"'{self.name}'")
            if e.refcount > 0:
                raise AdapterInUseError(
                    f"adapter {adapter_id!r} is pinned by {e.refcount} "
                    "live sequence(s) — drain before retiring")
            self._drop(e)
        self._note_event("evict")
        self._note_gauges()

    def _drop(self, e: _HostAdapter) -> None:
        """Remove a registration entirely (caller holds the lock)."""
        self._reg.pop(e.adapter_id, None)
        self._stats["host_bytes"] -= e.nbytes
        self._stats["evictions"] += 1
        if e.slot is not None:
            self._clear_slot(e.slot)
            self._slot_of.pop(e.slot, None)
            self._free_slots.append(e.slot)
            e.slot = None

    # -- oracle / introspection -----------------------------------------

    def merged_params(self, params: Dict, adapter_id: Optional[str]
                      ) -> Dict:
        """Dense-merge oracle: params with this tenant's deltas folded
        in (None = the base model, unchanged)."""
        if adapter_id is None:
            return params
        with self._lock:
            e = self._reg.get(adapter_id)
            if e is None:
                raise AdapterNotRegisteredError(
                    f"adapter {adapter_id!r} is not registered in pool "
                    f"'{self.name}'")
            weights = e.weights
        return merge_adapter_params(params, weights)

    def device_bytes(self) -> int:
        """Bytes the packed device arrays hold (allocated lazily at
        the first fault-in; 0 before)."""
        if self._packs is None:
            return 0
        L, r = int(self.cfg.n_layer), self.max_rank
        per_slot = sum(d_in * r + r * d_out
                       for d_in, d_out in self.dims.values())
        return (self.slots + 1) * L * per_slot * 4

    def check_invariants(self) -> Dict:
        """Pool audit (the KVCachePool.check_invariants discipline):
        slot bijection, refcount sanity, in-flight-implies-resident,
        host byte accounting, and every registration's CRC (the
        host-tier teeth — silent corruption is caught here even before
        a fault-in trips over it)."""
        with self._lock:
            problems: List[str] = []
            seen_slots: Dict[int, str] = {}
            host = 0
            for aid, e in self._reg.items():
                host += e.nbytes
                if e.refcount < 0:
                    problems.append(f"{aid!r}: negative refcount "
                                    f"{e.refcount}")
                if e.refcount > 0 and e.slot is None:
                    problems.append(f"{aid!r}: in flight but not "
                                    "resident")
                if e.slot is not None:
                    if not 1 <= e.slot <= self.slots:
                        problems.append(f"{aid!r}: slot {e.slot} out "
                                        "of range")
                    if e.slot in seen_slots:
                        problems.append(
                            f"slot {e.slot} double-owned by "
                            f"{seen_slots[e.slot]!r} and {aid!r}")
                    seen_slots[e.slot] = aid
                    if self._slot_of.get(e.slot) != aid:
                        problems.append(f"{aid!r}: slot map disagrees "
                                        f"on slot {e.slot}")
                if _crc_weights(e.weights) != e.crc:
                    problems.append(f"{aid!r}: host payload fails its "
                                    "registration CRC")
            if host != self._stats["host_bytes"]:
                problems.append(
                    f"host_bytes {self._stats['host_bytes']} != sum of "
                    f"registrations {host}")
            if len(self._free_slots) + len(seen_slots) != self.slots:
                problems.append(
                    f"slot accounting: {len(self._free_slots)} free + "
                    f"{len(seen_slots)} resident != {self.slots}")
            return {"ok": not problems, "problems": problems,
                    "registered": len(self._reg),
                    "resident": len(seen_slots)}

    def stats(self) -> Dict:
        with self._lock:
            st = dict(self._stats)
            st["registered"] = len(self._reg)
            st["resident"] = len(self._slot_of)
            st["slots"] = self.slots
            st["utilization"] = len(self._slot_of) / float(self.slots)
            st["device_bytes"] = self.device_bytes()
            probes = st["hits"] + st["fault_ins"]
            st["hit_rate"] = st["hits"] / probes if probes else 0.0
            st["in_flight"] = sum(e.refcount
                                  for e in self._reg.values())
            return st

    # -- observability (callers pay one flag read when off) -------------

    def _note_event(self, event: str, n: int = 1) -> None:
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_adapter_event(event, n)

    def _note_gauges(self) -> None:
        if _flags._VALUES["FLAGS_observability"]:
            with self._lock:
                resident = len(self._slot_of)
                host = self._stats["host_bytes"]
                registered = len(self._reg)
            _smetrics.record_adapter_gauges(
                device_bytes=self.device_bytes(),
                device_utilization=resident / float(self.slots),
                host_bytes=host, resident=resident,
                registered=registered)
