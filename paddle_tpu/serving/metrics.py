"""Serving instruments, emitted into the observability default registry.

Callers (engine.py, generate.py, kvcache.py) check FLAGS_observability
THEMSELVES before calling in — the established executor pattern: the
disabled hot path performs one dict lookup and never enters this module,
so serving adds zero allocation/locking to a run with telemetry off.

Metrics:
- paddle_tpu_serving_queue_depth            gauge    requests waiting
- paddle_tpu_serving_requests_total         counter  {outcome=admitted|
                                                      rejected_closed|
                                                      rejected_queue_full|
                                                      timeout}
- paddle_tpu_serving_batches_total          counter  {bucket=N}
- paddle_tpu_serving_batch_errors_total     counter  backend raised
- paddle_tpu_serving_batch_occupancy        histogram rows/bucket (0..1]
- paddle_tpu_serving_batch_latency_seconds  histogram dispatch wall time
- paddle_tpu_serving_request_latency_seconds histogram submit->complete
- paddle_tpu_serving_ttft_seconds           histogram admit->first token
- paddle_tpu_serving_token_seconds          histogram {impl=} per generated
                                                      token (labeled with
                                                      the active paged-
                                                      attention impl)
- paddle_tpu_serving_attention_bytes_per_step gauge  {impl=,kv_dtype=}
                                                      analytic HBM bytes the
                                                      decode attention KV
                                                      path moves per step
- paddle_tpu_serving_spec_tokens_total      counter  {outcome=accepted|
                                                      rejected, source=own|
                                                      corpus} speculative
                                                      draft tokens by verify
                                                      outcome and the n-gram
                                                      source that proposed
                                                      them (rejected ones
                                                      rolled back from the
                                                      page table)
- paddle_tpu_serving_spec_disabled_total    counter  {reason=} speculation
                                                      silently degraded to
                                                      d=0 (e.g. a program
                                                      without verify_step)
                                                      — a fleet where
                                                      speculation stopped
                                                      paying is diagnosable
- paddle_tpu_serving_fallback_total         counter  {kernel=} kernel
                                                      selections that fell
                                                      back off the
                                                      requested impl (CI
                                                      gates assert zero)
- paddle_tpu_serving_page_pool_used_pages   gauge    {pool=} pages in use
- paddle_tpu_serving_page_pool_utilization  gauge    {pool=} used/total
- paddle_tpu_serving_sequences_total        counter  {event=admitted|
                                                      retired|quarantined}
- paddle_tpu_serving_prefix_events_total    counter  {event=hit|miss|
                                                      insert|evict|
                                                      invalidate}
- paddle_tpu_serving_prefix_cached_tokens_total counter prompt tokens
                                                      served from cached
                                                      prefix pages
- paddle_tpu_serving_prefix_cache_pages     gauge    pages pinned by
                                                      prefix-cache entries

Fleet instruments (ISSUE 15 — disaggregated prefill/decode + elastic
autoscaling, serving/fleet/):
- paddle_tpu_serving_fleet_events_total     counter  {event=scale_up|
                                                      scale_down|handoff|
                                                      handoff_drop|upgrade|
                                                      replica_dead|failover,
                                                      role=prefill|decode|-}
- paddle_tpu_serving_fleet_handoff_bytes_total counter KV bytes staged
                                                      through prefill→decode
                                                      handoffs
- paddle_tpu_serving_fleet_replicas         gauge    {role=} live replicas
                                                      per class

Tiered-KV-cache instruments (ISSUE 18 — host-RAM session parking,
serving/kvtier.py):
- paddle_tpu_serving_kvtier_events_total    counter  {event=spill|
                                                      resume_resident|
                                                      resume_host|evict|
                                                      re_prefill} session
                                                      spill/resume outcomes
- paddle_tpu_serving_kvtier_transfer_bytes_total counter {direction=
                                                      spill|resume} KV bytes
                                                      moved device<->host
- paddle_tpu_serving_host_tier_bytes        gauge    payload bytes parked
                                                      in the host tier
- paddle_tpu_serving_host_tier_utilization  gauge    parked/capacity (0
                                                      when unbounded)
- paddle_tpu_serving_parked_sessions        gauge    sessions whose KV
                                                      lives host-side
- paddle_tpu_serving_hbm_tier_utilization   gauge    pool used/total as
                                                      seen by the tier
                                                      manager

Multi-tenant adapter instruments (ISSUE 19 — paged batched-LoRA
adapters, serving/adapters.py):
- paddle_tpu_serving_adapter_events_total   counter  {event=load|evict|
                                                      spill|fault_in|
                                                      reject} adapter
                                                      lifecycle: register /
                                                      retire-or-corrupt-drop /
                                                      device-slot LRU spill /
                                                      host→device load /
                                                      typed admission
                                                      rejection
- paddle_tpu_serving_adapter_pool_bytes     gauge    {tier=device|host}
                                                      packed slot bytes vs
                                                      registered payload
                                                      bytes
- paddle_tpu_serving_adapter_pool_utilization gauge  resident/slots
- paddle_tpu_serving_adapter_pool_resident  gauge    adapters in device
                                                      slots
- paddle_tpu_serving_adapter_pool_registered gauge   adapters in the host
                                                      tier of record
- paddle_tpu_serving_adapter_gather_bytes_per_step gauge analytic bytes
                                                      one step's per-row
                                                      A/B gather moves

Fault-isolation instruments (ISSUE 6):
- paddle_tpu_serving_breaker_trips_total    counter  circuit-breaker opens
- paddle_tpu_serving_dispatcher_restarts_total counter supervisor restarts
- paddle_tpu_serving_health_state           gauge    0 SERVING / 1 DEGRADED
                                                     / 2 DRAINING / 3 BROKEN
- paddle_tpu_serving_pool_invariant_violations_total counter {pool=}
                                                     check_invariants fails
- paddle_tpu_serving_pool_orphans_reclaimed_total counter {pool=} pages
                                                     repaired by
                                                     reclaim_orphans
(rejected_breaker_open / rejected_deadline_shed ride the existing
requests{outcome=} counter.)
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..observability import default_registry

__all__ = [
    "record_adapter_event",
    "record_adapter_gather_bytes",
    "record_adapter_gauges",
    "record_submit",
    "record_reject",
    "record_timeout",
    "record_batch",
    "record_batch_error",
    "record_request_latency",
    "record_ttft",
    "record_token",
    "record_fallback",
    "record_page_pool",
    "record_sequence",
    "record_spec_disabled",
    "record_breaker_trip",
    "record_dispatcher_restart",
    "record_fleet_event",
    "record_fleet_replicas",
    "record_handoff_bytes",
    "record_health",
    "record_pool_invariant_violation",
    "record_pool_reclaim",
    "record_prefix_cache_pages",
    "record_prefix_cached_tokens",
    "record_prefix_event",
    "record_replica_health",
    "record_router_decision",
    "record_tier_event",
    "record_tier_gauges",
    "record_tier_transfer",
]

# occupancy lives in (0, 1]; the default step-time buckets would collapse
# it into two bins
_OCCUPANCY_BUCKETS: Tuple[float, ...] = (
    0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


def record_submit(queue_depth: int) -> None:
    reg = default_registry()
    reg.gauge(
        "paddle_tpu_serving_queue_depth",
        "requests waiting in the engine's bounded queue",
    ).set(queue_depth)
    reg.counter(
        "paddle_tpu_serving_requests",
        "engine submissions by outcome",
    ).inc(outcome="admitted")


def record_reject(reason: str) -> None:
    default_registry().counter(
        "paddle_tpu_serving_requests",
        "engine submissions by outcome",
    ).inc(outcome=f"rejected_{reason}")


def record_timeout() -> None:
    default_registry().counter(
        "paddle_tpu_serving_requests",
        "engine submissions by outcome",
    ).inc(outcome="timeout")


def record_batch(bucket: int, rows: int, latency_s: float) -> None:
    reg = default_registry()
    reg.counter(
        "paddle_tpu_serving_batches",
        "dispatched micro-batches by bucket size",
    ).inc(bucket=str(bucket))
    reg.histogram(
        "paddle_tpu_serving_batch_occupancy",
        "real rows / bucket size per dispatched batch (1.0 = no padding)",
        buckets=_OCCUPANCY_BUCKETS,
    ).observe(rows / float(bucket))
    reg.histogram(
        "paddle_tpu_serving_batch_latency_seconds",
        "backend dispatch wall time per micro-batch",
    ).observe(latency_s)


def record_batch_error() -> None:
    default_registry().counter(
        "paddle_tpu_serving_batch_errors",
        "micro-batches whose backend dispatch raised",
    ).inc()


def record_request_latency(seconds: float,
                           trace_id: Optional[str] = None) -> None:
    """`trace_id` attaches an OpenMetrics exemplar to the bucket this
    latency lands in — callers pass it only for requests whose trace
    survived tail sampling, so the exemplar always points at a span
    tree that actually exists in the merged trace."""
    default_registry().histogram(
        "paddle_tpu_serving_request_latency_seconds",
        "submit-to-complete wall time per request",
    ).observe(seconds,
              exemplar={"trace_id": trace_id} if trace_id else None)


def record_ttft(seconds: float, trace_id: Optional[str] = None) -> None:
    default_registry().histogram(
        "paddle_tpu_serving_ttft_seconds",
        "decode admit-to-first-token wall time per sequence",
    ).observe(seconds,
              exemplar={"trace_id": trace_id} if trace_id else None)


def record_token(seconds: float, impl: str = "reference") -> None:
    default_registry().histogram(
        "paddle_tpu_serving_token_seconds",
        "wall time per generated token (per sequence-step)",
    ).observe(seconds, impl=impl)


def record_spec(drafted: int, accepted: int,
                source: str = "own") -> None:
    """One sequence's speculative verify outcome: `drafted` proposed
    tokens, `accepted` of them committed (acceptance_rate is the
    counter ratio; rejected = drafted - accepted rolled back).
    `source` attributes the proposal to the n-gram source that won it
    (``own`` history vs the shared ``corpus`` trie — ISSUE 20), so the
    acceptance split per source is a dashboard ratio, not a guess."""
    default_registry().counter(
        "paddle_tpu_serving_spec_tokens_total",
        "speculative draft tokens by verify outcome",
    ).inc(accepted, outcome="accepted", source=source)
    rejected = drafted - accepted
    if rejected:
        default_registry().counter(
            "paddle_tpu_serving_spec_tokens_total",
            "speculative draft tokens by verify outcome",
        ).inc(rejected, outcome="rejected", source=source)


def record_spec_disabled(reason: str) -> None:
    """Speculation was requested but degraded to d=0 — `reason` names
    why (e.g. ``program_no_verify``: a custom SPMD program exposes no
    ``verify_step``).  ISSUE 16 bugfix: this used to be only a one-time
    log line, invisible to a fleet dashboard."""
    default_registry().counter(
        "paddle_tpu_serving_spec_disabled_total",
        "speculative decoding disables (degrades to d=0) by reason",
    ).inc(reason=reason)


def record_fallback(kernel: str) -> None:
    """A kernel selection fell back off its requested implementation
    (e.g. an explicit pallas paged-attention outside the Mosaic
    envelope resolving to the reference gather).  The one-time log is
    human-visible; this counter is what CI gates assert zero on."""
    default_registry().counter(
        "paddle_tpu_serving_fallback",
        "kernel-selection fallbacks off the requested implementation",
    ).inc(kernel=kernel)


def record_attention_bytes(nbytes: int, impl: str,
                           kv_dtype: str = "float32") -> None:
    """Analytic decode-attention KV bytes per step for the current
    batch/pool geometry (kernels.paged_attention.attention_bytes_per_step)
    — the live counterpart of the banked AOT_COST_PAGED.json A/B.
    ``kv_dtype`` labels the series with the POOL's element type, so an
    int8 pool's halved stream and an fp32 pool's land on distinct
    series instead of silently overwriting each other."""
    default_registry().gauge(
        "paddle_tpu_serving_attention_bytes_per_step",
        "analytic HBM bytes the decode attention KV path moves per step",
    ).set(float(nbytes), impl=impl, kv_dtype=kv_dtype)


def record_page_pool(used: int, total: int, pool: str = "kv") -> None:
    reg = default_registry()
    reg.gauge(
        "paddle_tpu_serving_page_pool_used_pages",
        "KV-cache pages currently allocated",
    ).set(used, pool=pool)
    reg.gauge(
        "paddle_tpu_serving_page_pool_utilization",
        "KV-cache page-pool utilization (used/total)",
    ).set(used / float(total) if total else 0.0, pool=pool)


def record_sequence(event: str) -> None:
    default_registry().counter(
        "paddle_tpu_serving_sequences",
        "continuous-batching sequence lifecycle events",
    ).inc(event=event)


def record_breaker_trip() -> None:
    default_registry().counter(
        "paddle_tpu_serving_breaker_trips",
        "engine circuit-breaker opens (consecutive-failure limit hit)",
    ).inc()


def record_dispatcher_restart() -> None:
    default_registry().counter(
        "paddle_tpu_serving_dispatcher_restarts",
        "dispatcher threads restarted by the engine supervisor",
    ).inc()


_HEALTH_CODES = {"SERVING": 0, "DEGRADED": 1, "DRAINING": 2, "BROKEN": 3}


def record_health(state: str, queue_depth: int,
                  breaker_open: bool = False,
                  pool_utilization: Optional[float] = None,
                  pool: str = "kv",
                  replica: Optional[str] = None) -> None:
    """engine.health() snapshot gauges: numeric state (0 SERVING /
    1 DEGRADED / 2 DRAINING / 3 BROKEN) plus the queue/breaker/pool
    levels an alerting rule would page on.  `pool` labels the
    utilization gauge so it lands on the SAME series the pool's own
    _note_pool() publishes.  `replica` (engines serving behind a
    distributed.Router) labels the state/queue/breaker gauges so
    per-replica series survive an aggregate_dir() merge distinct."""
    reg = default_registry()
    labels = {"replica": replica} if replica is not None else {}
    reg.gauge(
        "paddle_tpu_serving_health_state",
        "engine health: 0 SERVING, 1 DEGRADED, 2 DRAINING, 3 BROKEN",
    ).set(_HEALTH_CODES.get(state, 3), **labels)
    reg.gauge(
        "paddle_tpu_serving_queue_depth",
        "requests waiting in the engine's bounded queue",
    ).set(queue_depth, **labels)
    reg.gauge(
        "paddle_tpu_serving_breaker_open",
        "1 while the engine circuit breaker is open",
    ).set(1 if breaker_open else 0, **labels)
    if pool_utilization is not None:
        reg.gauge(
            "paddle_tpu_serving_page_pool_utilization",
            "KV-cache page-pool utilization (used/total)",
        ).set(pool_utilization, pool=pool)


def record_router_decision(decision: str, replica: str) -> None:
    """One Router routing decision: ``routed`` (the request landed
    here), ``skipped_unhealthy`` (a candidate was passed over — lease
    expired, BROKEN/DRAINING health, or a raced rejection), or
    ``handoff`` (drain_replica claimed the replica's traffic)."""
    default_registry().counter(
        "paddle_tpu_serving_router_decisions",
        "admission-router routing decisions by replica",
    ).inc(decision=decision, replica=replica)


def record_replica_health(replica: str, state: str,
                          queue_depth: int) -> None:
    """Router-side per-replica health gauges (the aggregate_dir-merged
    fleet view: one series per replica name)."""
    reg = default_registry()
    reg.gauge(
        "paddle_tpu_serving_replica_health_state",
        "replica health as seen by the router: 0 SERVING, 1 DEGRADED, "
        "2 DRAINING, 3 BROKEN",
    ).set(_HEALTH_CODES.get(state, 3), replica=replica)
    reg.gauge(
        "paddle_tpu_serving_replica_queue_depth",
        "replica engine queue depth as seen by the router",
    ).set(queue_depth, replica=replica)


def record_fleet_event(event: str, role: str = "-", n: int = 1,
                       pid: Optional[int] = None) -> None:
    """One fleet lifecycle event: ``scale_up`` / ``scale_down`` (the
    autoscaler acted), ``handoff`` (a prefilled sequence moved to a
    decode replica), ``handoff_drop`` (lost in transit, requeued),
    ``upgrade`` (a replica's weights were swapped under drain),
    ``replica_dead`` (a silent/killed replica was quarantined),
    ``failover`` (a request rerouted off a dead replica), or the
    process-fleet trio ``proc_spawn`` / ``proc_exit`` / ``proc_kill``
    (which carry the replica's OS ``pid`` label — the post-mortem key
    that joins fleet metrics to kernel/oom logs)."""
    labels = {"event": event, "role": role}
    if pid is not None:
        labels["pid"] = str(int(pid))
    default_registry().counter(
        "paddle_tpu_serving_fleet_events",
        "disaggregated-fleet lifecycle events by replica class",
    ).inc(n, **labels)


def record_handoff_bytes(nbytes: int) -> None:
    """KV bytes staged host-side through one prefill→decode handoff."""
    default_registry().counter(
        "paddle_tpu_serving_fleet_handoff_bytes",
        "KV bytes staged through prefill-to-decode handoffs",
    ).inc(nbytes)


def record_fleet_replicas(role: str, n: int) -> None:
    default_registry().gauge(
        "paddle_tpu_serving_fleet_replicas",
        "live fleet replicas per class",
    ).set(n, role=role)


def record_prefix_event(event: str, n: int = 1) -> None:
    """Prefix-cache lifecycle counter: ``hit`` / ``miss`` (admission
    matches), ``insert`` (new trie entries), ``evict`` (LRU pressure
    releases), ``invalidate`` (poisoned-chain quarantine drops)."""
    default_registry().counter(
        "paddle_tpu_serving_prefix_events",
        "prefix-cache lifecycle events",
    ).inc(n, event=event)


def record_prefix_cached_tokens(tokens: int) -> None:
    """Prompt tokens served straight from cached K/V pages — prefill
    compute the shared prefix did NOT cost."""
    default_registry().counter(
        "paddle_tpu_serving_prefix_cached_tokens",
        "prompt tokens served from cached prefix pages (prefill skipped)",
    ).inc(tokens)


def record_prefix_cache_pages(entries: int) -> None:
    default_registry().gauge(
        "paddle_tpu_serving_prefix_cache_pages",
        "KV pages currently pinned by prefix-cache entries",
    ).set(entries)


def record_pool_invariant_violation(pool: str = "kv") -> None:
    default_registry().counter(
        "paddle_tpu_serving_pool_invariant_violations",
        "KVCachePool.check_invariants audits that found a violation",
    ).inc(pool=pool)


def record_tier_event(event: str, n: int = 1) -> None:
    """One tiered-KV-cache outcome: ``spill`` (a session's KV parked
    host-side), ``resume_resident`` (next turn found its KV still in
    HBM), ``resume_host`` (parked payload imported back), ``evict``
    (a parked payload dropped for capacity/pressure/mismatch — its
    session re-prefills), ``re_prefill`` (a corrupt/lost payload was
    rejected typed and the turn recomputed from the prompt)."""
    default_registry().counter(
        "paddle_tpu_serving_kvtier_events",
        "tiered KV cache session spill/resume outcomes",
    ).inc(n, event=event)


def record_tier_transfer(nbytes: int, direction: str) -> None:
    """KV payload bytes moved across the device<->host boundary by the
    tier (``direction`` = spill | resume)."""
    default_registry().counter(
        "paddle_tpu_serving_kvtier_transfer_bytes",
        "KV bytes moved between HBM and the host tier",
    ).inc(nbytes, direction=direction)


def record_tier_gauges(host_bytes: int, host_utilization: float,
                       parked_sessions: int,
                       hbm_utilization: float) -> None:
    """Point-in-time tier occupancy (both tiers in one call)."""
    reg = default_registry()
    reg.gauge(
        "paddle_tpu_serving_host_tier_bytes",
        "payload bytes parked in the host KV tier",
    ).set(host_bytes)
    reg.gauge(
        "paddle_tpu_serving_host_tier_utilization",
        "host KV tier utilization (0 when unbounded)",
    ).set(host_utilization)
    reg.gauge(
        "paddle_tpu_serving_parked_sessions",
        "sessions whose KV currently lives host-side",
    ).set(parked_sessions)
    reg.gauge(
        "paddle_tpu_serving_hbm_tier_utilization",
        "KV page-pool utilization as seen by the tier manager",
    ).set(hbm_utilization)


def record_adapter_event(event: str, n: int = 1) -> None:
    """One adapter-pool lifecycle event: ``load`` (a tenant's LoRA
    weights registered host-side), ``fault_in`` (host → device slot),
    ``spill`` (a refcount-zero resident LRU-evicted from its device
    slot; the host copy remains), ``evict`` (a registration dropped —
    retire, publish-replace, or a corrupt payload), ``reject`` (a
    request named an unloadable adapter and was rejected typed at
    admission, before any KV page was claimed)."""
    default_registry().counter(
        "paddle_tpu_serving_adapter_events",
        "multi-tenant adapter-pool lifecycle events",
    ).inc(n, event=event)


def record_adapter_gauges(device_bytes: int, device_utilization: float,
                          host_bytes: int, resident: int,
                          registered: int) -> None:
    """Point-in-time adapter-pool occupancy (both tiers in one call)."""
    reg = default_registry()
    reg.gauge(
        "paddle_tpu_serving_adapter_pool_bytes",
        "adapter-pool bytes by tier (packed device slots vs registered "
        "host payloads)",
    ).set(device_bytes, tier="device")
    reg.gauge(
        "paddle_tpu_serving_adapter_pool_bytes",
        "adapter-pool bytes by tier (packed device slots vs registered "
        "host payloads)",
    ).set(host_bytes, tier="host")
    reg.gauge(
        "paddle_tpu_serving_adapter_pool_utilization",
        "adapter device-slot utilization (resident/slots)",
    ).set(device_utilization)
    reg.gauge(
        "paddle_tpu_serving_adapter_pool_resident",
        "adapters currently resident in device slots",
    ).set(resident)
    reg.gauge(
        "paddle_tpu_serving_adapter_pool_registered",
        "adapters registered in the host tier of record",
    ).set(registered)


def record_adapter_gather_bytes(nbytes: float) -> None:
    """Analytic bytes the last step's per-row adapter gather moved —
    the live counterpart of the banked ``lora_decode`` zoo entry."""
    default_registry().gauge(
        "paddle_tpu_serving_adapter_gather_bytes_per_step",
        "analytic bytes one decode step's per-row adapter A/B gather "
        "moves",
    ).set(float(nbytes))


def record_pool_reclaim(pages: int, pool: str = "kv") -> None:
    default_registry().counter(
        "paddle_tpu_serving_pool_orphans_reclaimed",
        "orphaned KV pages returned to the free list by reclaim_orphans",
    ).inc(pages, pool=pool)
