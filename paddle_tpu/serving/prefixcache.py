"""Refcounted prefix cache over the paged KV pool: shared-prefix
prompts reuse cached K/V pages instead of re-prefilling them.

The millions-of-users serving workload is dominated by shared-prefix
traffic — system prompts, few-shot headers, multi-turn chat histories.
Without this module an N-way-shared prefix costs N full page-sets in
KVCachePool and N full prefill passes.  With it:

- **Page-granular rolling-hash trie.**  A prompt is split into
  page_size-token runs; each run is a trie node keyed by a rolling
  hash (sha1 of the parent key + this run's tokens) and carrying ONE
  pool page that holds the run's K/V for every layer.  Matching walks
  the trie (longest-cached-prefix match), verifying each hop against
  the literal token run — the hash names the entry, the tokens decide
  it, so a hash collision can never splice the wrong K/V into a
  sequence.  The final node of an inserted prompt may be PARTIAL (the
  prompt tail that doesn't fill a page); partial nodes are leaves.
- **Attach, don't copy.**  A hit attaches the matched pages READ-ONLY
  to the new sequence's page table (``KVCachePool.attach_prefix`` —
  refcount++ per page, zero free-list pressure, zero prefill compute
  for the matched tokens).  The first divergent append into a shared
  partially-filled tail page triggers the pool's copy-on-write
  (kvcache.py), so cached content is immutable by construction.
- **Refcounted lifetime.**  ``free_seq`` only returns pages whose
  refcount hits zero; an entry's hold keeps a popular prefix alive
  across the sequences that used it.  Matching always leaves at least
  ONE prompt token uncached — the model must still run the final
  prompt token to produce the first generated token's logits.
- **LRU eviction under pressure.**  The cache registers as the pool's
  reclaimer: when an append cannot find enough free pages, cache-only
  pages (refcount 1 — no live sequence attached) are released leaf-
  first in least-recently-used order before PagePoolExhausted can
  fire.  ``max_pages`` optionally caps the cache's footprint the same
  way at insert time.
- **Adapter namespacing (ISSUE 19).**  The trie is partitioned by
  adapter id: LoRA deltas on the QKV projections change the K/V a
  prompt produces, so a prefix cached under one model variant is
  content-wrong for every other.  ``match``/``insert``/
  ``ngram_continuation`` take ``adapter_id`` (None = base model) and
  confine themselves to that namespace — cross-tenant attachment is
  structurally impossible, not merely unlikely.
- **Poison containment.**  A quarantined sequence that was served a
  cached prefix invalidates the matched chain (``quarantine_seq``) —
  a corrupted cached page (chaos: FAULT_SERVE_PREFIX_CORRUPT) costs
  the sequences that read it, never the cache's future correctness or
  the batch-mates that didn't.

Thread-safety: all cache state is guarded by the POOL's lock (an
RLock) — the pool's pressure reclaimer calls back into the cache from
inside ``append_tokens``'s critical section, and a single shared lock
makes that re-entrant instead of an ordering hazard.

Observability rides the established pattern: every instrument call is
gated on FLAGS_observability at the call site, and eviction/corrupt
events land in the flight recorder ring.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from .. import flags as _flags
from ..resilience import faultinject as _finject
from . import metrics as _smetrics
from .kvcache import KVCachePool

__all__ = ["PrefixCache", "PrefixMatch"]


def _chain_key(parent: Optional[str], tokens: Tuple[int, ...],
               ns: str = "") -> str:
    """Rolling prompt-prefix hash: the entry's name folds its parent's
    name with this page's token run, salted by the namespace (adapter
    id) so identical prompts under different model variants can never
    share an entry key in the flat ``_entries`` map."""
    h = hashlib.sha1()
    h.update(ns.encode())
    h.update(b"\x00")
    h.update((parent or "").encode())
    h.update((",".join(str(t) for t in tokens)).encode())
    return h.hexdigest()[:20]


@dataclasses.dataclass
class PrefixMatch:
    """Longest-cached-prefix match for one prompt: the trie keys walked,
    the pool pages they carry (in prompt order), and the number of
    prompt tokens they cover (page-granular except a partial leaf;
    always <= len(prompt) - 1)."""

    keys: List[str] = dataclasses.field(default_factory=list)
    pages: List[int] = dataclasses.field(default_factory=list)
    tokens: int = 0


@dataclasses.dataclass
class _Entry:
    key: str
    parent: Optional[str]
    tokens: Tuple[int, ...]   # this page's literal token run
    page: int                 # pool page holding the run's K/V
    last_used: int
    children: Dict[Tuple[int, ...], str] = dataclasses.field(
        default_factory=dict)
    ns: str = ""              # adapter namespace ("" = base model)


class PrefixCache:
    """Prefix-to-page trie over one :class:`KVCachePool`.

    Wire it to a pool and hand it to the decode loop::

        pool = KVCachePool(...)
        cache = PrefixCache(pool)
        loop = ContinuousBatchingLoop(params, cfg, pool,
                                      prefix_cache=cache)

    The constructor registers the cache as the pool's pressure
    reclaimer, external owner (so ``check_invariants`` audits entry
    holds as legitimate refcounts), and defrag remap listener."""

    def __init__(self, pool: KVCachePool,
                 max_pages: Optional[int] = None):
        self.pool = pool
        self.max_pages = int(max_pages) if max_pages else 0
        self._lock = pool._lock  # ONE lock: see module docstring
        self._entries: Dict[str, _Entry] = {}
        # root tries keyed by namespace (adapter id; "" = base model).
        # LoRA on QKV changes the cached K/V content, so a prefix cached
        # under one variant must never be attached to another (ISSUE 19).
        self._roots: Dict[str, Dict[Tuple[int, ...], str]] = {}
        self._seq_keys: Dict[int, List[str]] = {}
        self._tick = 0
        self._stats = {
            "hits": 0, "misses": 0, "inserts": 0, "evictions": 0,
            "cached_tokens_served": 0, "invalidations": 0,
        }
        pool.register_reclaimer(self._reclaim)
        pool.register_owner(self._holds)
        pool.register_remap_hook(self._remap)

    # -- the admission path --------------------------------------------

    def match(self, prompt: Sequence[int],
              adapter_id: Optional[str] = None) -> PrefixMatch:
        """Longest cached prefix of `prompt`, page by page, verifying
        every hop against the literal tokens.  Caps the match at
        len(prompt) - 1 so at least one token still runs through the
        model (the logits source for the first generated token).
        Touches matched entries' LRU clocks; counts nothing — stats
        land at attach/note_miss so a retried admission probe doesn't
        double-count.  Matching is confined to `adapter_id`'s namespace
        (None = base model): cached K/V is variant-specific."""
        prompt = [int(t) for t in prompt]
        limit = len(prompt) - 1
        m = PrefixMatch()
        with self._lock:
            children = self._roots.get(adapter_id or "", {})
            pos = 0
            while pos < limit:
                best: Optional[_Entry] = None
                for toks, key in children.items():
                    if pos + len(toks) > limit:
                        continue
                    if tuple(prompt[pos:pos + len(toks)]) != toks:
                        continue
                    if best is None or len(toks) > len(best.tokens):
                        best = self._entries[key]
                if best is None:
                    break
                m.keys.append(best.key)
                m.pages.append(best.page)
                pos += len(best.tokens)
                best.last_used = self._tick
                self._tick += 1
                children = best.children
                if len(best.tokens) < self.pool.page_size:
                    break  # partial nodes are leaves
            m.tokens = pos
        return m

    def attach(self, seq_id: int, m: PrefixMatch) -> int:
        """Attach a match to a freshly-allocated sequence: the pages
        join its table read-only (refcount++ each) and the sequence
        starts at ``m.tokens`` — the prefill then covers only the
        unshared tail.  Returns the cached token count."""
        if not m.tokens:
            self.note_miss()
            return 0
        with self._lock:
            self.pool.attach_prefix(seq_id, m.pages, m.tokens)
            self._seq_keys[seq_id] = list(m.keys)
            self._stats["hits"] += 1
            self._stats["cached_tokens_served"] += m.tokens
            if _finject.serve_prefix_corrupt():
                # chaos: a cached page goes bad exactly at reuse time
                self.pool.corrupt_page(m.pages[0])
                if _flags._VALUES["FLAGS_observability"]:
                    from ..observability import flight as _flight

                    _flight.default_flight().record(
                        "prefix_corrupt", page=m.pages[0], seq_id=seq_id)
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_prefix_event("hit")
            _smetrics.record_prefix_cached_tokens(m.tokens)
            _smetrics.record_prefix_cache_pages(len(self._entries))
        return m.tokens

    def note_miss(self) -> None:
        with self._lock:
            self._stats["misses"] += 1
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_prefix_event("miss")

    # -- the retirement/insert path ------------------------------------

    def insert(self, seq_id: int, prompt: Sequence[int],
               adapter_id: Optional[str] = None) -> int:
        """Cache a finished prefill's prompt pages: walk/extend the trie
        page by page, pinning (refcount++) each NEW entry's pool page.
        The sequence keeps decoding — its next append into a pinned
        partial tail page copy-on-writes, leaving the cached content
        frozen.  Entries land in `adapter_id`'s namespace (None = base
        model).  Returns the number of entries created."""
        prompt = [int(t) for t in prompt]
        ps = self.pool.page_size
        ns = adapter_id or ""
        created = 0
        with self._lock:
            pages, length = self.pool.table_snapshot(seq_id)
            if length < len(prompt):
                raise ValueError(
                    f"sequence {seq_id} holds {length} tokens < prompt "
                    f"{len(prompt)} — insert only after prefill completes")
            children = self._roots.setdefault(ns, {})
            parent: Optional[str] = None
            pos = idx = 0
            while pos < len(prompt):
                n = min(ps, len(prompt) - pos)
                toks = tuple(prompt[pos:pos + n])
                key = children.get(toks)
                if key is not None:
                    e = self._entries[key]
                else:
                    page = pages[idx]
                    self.pool.retain_pages([page])
                    key = _chain_key(parent, toks, ns)
                    e = _Entry(key=key, parent=parent, tokens=toks,
                               page=page, last_used=self._tick, ns=ns)
                    self._entries[key] = e
                    children[toks] = key
                    created += 1
                    self._stats["inserts"] += 1
                e.last_used = self._tick
                self._tick += 1
                parent, children = key, e.children
                pos += n
                idx += 1
                if n < ps:
                    break  # the partial tail is this prompt's leaf
            if self.max_pages:
                while len(self._entries) > self.max_pages:
                    # -1 = nothing evictable; 0 = entry dropped but its
                    # page stays live (attached elsewhere) — keep going
                    if self._evict_one(require_free=False) < 0:
                        break
        if created and _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_prefix_event("insert", created)
            _smetrics.record_prefix_cache_pages(len(self._entries))
        return created

    # -- eviction / invalidation ---------------------------------------

    def _evict_one(self, require_free: bool) -> int:
        """Evict the least-recently-used leaf entry; with require_free,
        only entries whose page the cache alone holds (refcount 1 —
        releasing it actually frees a page).  Returns pages freed (0
        also when an entry was dropped but its page stays live).
        Caller holds the lock."""
        best: Optional[_Entry] = None
        for e in self._entries.values():
            if e.children:
                continue
            if require_free and self.pool._ref[e.page] != 1:
                continue
            if best is None or e.last_used < best.last_used:
                best = e
        if best is None:
            return -1  # nothing evictable
        self._drop_entry(best)
        freed = self.pool.release_pages([best.page])
        self._stats["evictions"] += 1
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_prefix_event("evict")
            from ..observability import flight as _flight

            _flight.default_flight().record(
                "prefix_evict", page=best.page,
                pool=self.pool.name, freed=freed)
        return freed

    def _drop_entry(self, e: _Entry) -> None:
        self._entries.pop(e.key, None)
        siblings = (self._entries[e.parent].children
                    if e.parent in self._entries
                    else self._roots.get(e.ns, {}))
        if siblings.get(e.tokens) == e.key:
            siblings.pop(e.tokens, None)

    def _reclaim(self, short: int) -> int:
        """Pool pressure hook: release LRU cache-only pages until
        `short` pages came free or nothing evictable remains.  Runs
        under the pool lock (same RLock — re-entrant)."""
        freed = 0
        while freed < short:
            got = self._evict_one(require_free=True)
            if got < 0:
                break
            freed += got
        if freed and _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_prefix_cache_pages(len(self._entries))
        return freed

    def _invalidate_tree(self, key: str) -> int:
        e = self._entries.get(key)
        if e is None:
            return 0
        n = 0
        for ck in list(e.children.values()):
            n += self._invalidate_tree(ck)
        self._drop_entry(e)
        # scrub on free: the chain is being dropped on poison suspicion
        self.pool.release_pages([e.page], scrub=True)
        self._stats["invalidations"] += 1
        return n + 1

    def quarantine_seq(self, seq_id: int) -> int:
        """A sequence served from this cache went non-finite: presume
        the matched chain poisoned and invalidate it (with every
        descendant) so the corruption cannot be served again.  Pages
        still attached to live sequences stay alive via their table
        refcounts; only the cache's holds drop.  Returns entries
        invalidated."""
        with self._lock:
            keys = self._seq_keys.pop(seq_id, [])
            n = self._invalidate_tree(keys[0]) if keys else 0
        if n and _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_prefix_event("invalidate", n)
            _smetrics.record_prefix_cache_pages(len(self._entries))
        return n

    def forget_seq(self, seq_id: int) -> None:
        """Drop the seq -> matched-chain bookkeeping at retirement."""
        with self._lock:
            self._seq_keys.pop(seq_id, None)

    def clear(self) -> int:
        """Release every entry (the leak-audit epilogue: after clear(),
        a healthy run's pool must be fully free again)."""
        with self._lock:
            n = 0
            for roots in list(self._roots.values()):
                for key in list(roots.values()):
                    n += self._invalidate_tree(key)
            self._seq_keys.clear()
            self._roots.clear()
        return n

    # -- corpus drafting (ISSUE 16) ------------------------------------

    def ngram_continuation(self, probe: Sequence[int], limit: int,
                           adapter_id: Optional[str] = None) -> List[int]:
        """Cross-request n-gram lookup over the trie's cached token
        chains — the CORPUS arm of ``PromptLookupDrafter``: shared-
        prefix fleet traffic (system prompts, few-shot headers,
        multi-turn histories) drafts from continuations OTHER sequences
        already inserted, not just its own history.

        Finds `probe` inside any root-to-leaf token chain and returns
        up to `limit` tokens that followed it.  Within a chain the scan
        runs newest-position-first and a full-length continuation wins
        outright; across chains a longer continuation wins and ties go
        to the more recently used leaf (popular prefixes beat stale
        ones).  Returns [] on no match — the drafter then falls back to
        own-history matching, so the corpus can never make a draft
        WORSE.  Pure host bookkeeping under the pool lock; chains here
        are verified literal tokens (the trie's collision rule), so a
        wrong-content proposal is impossible — and harmless anyway,
        since the verifier decides acceptance.

        Drafting is confined to `adapter_id`'s namespace (None = base
        model): a tenant's cached continuations never leak into another
        tenant's drafts — cross-tenant speculation would both reveal a
        neighbour's traffic shape and waste verify slots on systematic
        misses."""
        probe = tuple(int(t) for t in probe)
        n = len(probe)
        limit = int(limit)
        if not n or limit < 1:
            return []
        best: List[int] = []
        best_used = -1

        def scan(chain: List[int], last_used: int) -> None:
            nonlocal best, best_used
            L = len(chain)
            for i in range(L - n, -1, -1):
                if tuple(chain[i:i + n]) != probe:
                    continue
                out = chain[i + n:i + n + limit]
                if (len(out), last_used) > (len(best), best_used):
                    best, best_used = out, last_used
                if len(out) == limit:
                    return  # full-length: newest such wins this chain

        def visit(key: str, prefix: List[int]) -> None:
            e = self._entries.get(key)
            if e is None:
                return
            chain = prefix + list(e.tokens)
            if e.children:
                # interior chains are covered by their leaves' scans
                for ck in list(e.children.values()):
                    visit(ck, chain)
            else:
                scan(chain, e.last_used)

        with self._lock:
            roots = self._roots.get(adapter_id or "", {})
            for key in list(roots.values()):
                visit(key, [])
        return best

    # -- pool integration ----------------------------------------------

    def _holds(self) -> Dict[int, int]:
        """External-owner hook for KVCachePool.check_invariants: one
        refcount hold per entry page."""
        holds: Dict[int, int] = {}
        for e in self._entries.values():
            holds[e.page] = holds.get(e.page, 0) + 1
        return holds

    def _remap(self, remap: Dict[int, int]) -> None:
        for e in self._entries.values():
            e.page = remap.get(e.page, e.page)

    def locked_pages(self) -> int:
        """Distinct cached ENTRY pages currently attached to >= 1 live
        sequence (refcount > 1) — introspection/stats.  Admission uses
        the pool's own ``uncharged_live_pages()`` instead: this count
        goes blind when an entry is dropped (capacity cap, quarantine
        invalidation) while its page stays attached."""
        with self._lock:
            return sum(1 for e in self._entries.values()
                       if self.pool._ref[e.page] > 1)

    # -- introspection --------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats,
                        entries=len(self._entries),
                        locked_pages=sum(
                            1 for e in self._entries.values()
                            if self.pool._ref[e.page] > 1))
