"""Locate the (single) distributed lookup table in a program.

reference: python/paddle/fluid/distribute_lookup_table.py — the downpour /
pserver sparse path needs to know which embedding table is remote so its
lookup ops can be skipped on workers and served by pull/push RPCs.  Here
the "RPC" is the in-process PS core (paddle_tpu/distributed/ps_core.py) or
a mesh-sharded table (paddle_tpu/parallel), but the program analysis is
identical: find lookup_table ops whose `is_distributed` attr is set.
"""

from __future__ import annotations

LOOKUP_TABLE_TYPE = "lookup_table"

__all__ = [
    "find_distributed_lookup_table",
    "find_distributed_lookup_table_inputs",
    "find_distributed_lookup_table_outputs",
]


def _dist_lookup_ops(program):
    for op in program.global_block().ops:
        if op.type == LOOKUP_TABLE_TYPE and op.attr("is_distributed", False):
            yield op


def find_distributed_lookup_table(program):
    """Return the name of the distributed table, or None.

    The reference supports exactly one distributed table per program
    (distribute_lookup_table.py find_distributed_lookup_table) and asserts
    every distributed lookup shares it; same contract here.
    """
    table_name = None
    for op in _dist_lookup_ops(program):
        w = op.input("W")[0]
        if table_name is None:
            table_name = w
        elif table_name != w:
            raise ValueError(
                "all distributed lookup_table ops must share one table; "
                f"found both '{table_name}' and '{w}'"
            )
    return table_name


def find_distributed_lookup_table_inputs(program, table_name):
    """Id variables feeding the distributed table's lookups."""
    local_vars = program.current_block().vars
    inputs = []
    for op in program.global_block().ops:
        if op.type == LOOKUP_TABLE_TYPE and op.input("W")[0] == table_name:
            inputs.extend(local_vars[name] for name in op.input("Ids"))
    return inputs


def find_distributed_lookup_table_outputs(program, table_name):
    """Embedding output variables of the distributed table's lookups."""
    local_vars = program.current_block().vars
    outputs = []
    for op in program.global_block().ops:
        if op.type == LOOKUP_TABLE_TYPE and op.input("W")[0] == table_name:
            outputs.extend(local_vars[name] for name in op.output("Out"))
    return outputs
