"""Tensor creation/manipulation layer fns
(reference: python/paddle/fluid/layers/tensor.py — 22 defs)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core.framework import Variable
from ..core.proto import DataType, convert_dtype
from ..layer_helper import LayerHelper

__all__ = [
    "create_tensor",
    "create_parameter",
    "create_global_var",
    "cast",
    "concat",
    "sums",
    "assign",
    "fill_constant",
    "fill_constant_batch_size_like",
    "ones",
    "zeros",
    "reverse",
    "argmax",
    "argmin",
    "argsort",
    "reshape",
    "squeeze",
    "unsqueeze",
    "flatten",
    "transpose",
    "split",
    "stack",
    "unstack",
    "expand",
    "slice",
    "shape",
    "gather",
    "scatter",
    "one_hot_v2",
    "has_inf",
    "has_nan",
    "isfinite",
    "range",
    "increment",
    "cumsum",
    "scale",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "tensor_array_to_tensor",
    "sum",
    "merge_selected_rows",
    "get_tensor_from_selected_rows",
    "load",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.block.create_var(
        name=name or helper.name, dtype=dtype, persistable=persistable, shape=[]
    )


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..param_attr import ParamAttr

    helper = LayerHelper("create_parameter", name=name)
    attr = ParamAttr._to_attr(attr)
    if name is not None and attr.name is None:
        attr.name = name
    return helper.create_parameter(attr, shape, dtype, is_bias, default_initializer)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    from ..initializer import ConstantInitializer

    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        persistable=persistable, dtype=dtype, shape=list(shape)
    )
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast", input=x)
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"in_dtype": int(x.dtype), "out_dtype": int(dtype)},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(
        type="concat", inputs={"X": list(input)}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="sum", inputs={"X": list(input)}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op(type="assign", inputs={"X": [input]}, outputs={"Out": [output]})
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(convert_dtype(arr.dtype))
        attrs = {"shape": list(arr.shape), "dtype": int(convert_dtype(arr.dtype))}
        if arr.dtype in (np.int32, np.int64):
            attrs["int32_values"] = arr.astype(np.int64).reshape(-1).tolist()
        else:
            attrs["fp32_values"] = arr.astype(np.float64).reshape(-1).tolist()
        helper.append_op(type="assign_value", outputs={"Out": [output]}, attrs=attrs)
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    dtype = convert_dtype(dtype)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": int(dtype), "value": float(value),
               "force_cpu": force_cpu},
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": int(dtype), "value": float(value),
               "input_dim_idx": input_dim_idx, "output_dim_idx": output_dim_idx},
    )
    out.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0, force_cpu)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0, force_cpu)


def reverse(x, axis):
    helper = LayerHelper("reverse", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="reverse", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": axis if isinstance(axis, (list, tuple)) else [axis]},
    )
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max", input=x)
    out = helper.create_variable_for_type_inference(DataType.INT64, stop_gradient=True)
    helper.append_op(type="arg_max", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min", input=x)
    out = helper.create_variable_for_type_inference(DataType.INT64, stop_gradient=True)
    helper.append_op(type="arg_min", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis})
    return out


def argsort(x, axis=-1, name=None):
    helper = LayerHelper("argsort", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    idx = helper.create_variable_for_type_inference(DataType.INT64, stop_gradient=True)
    helper.append_op(
        type="argsort", inputs={"X": [x]},
        outputs={"Out": [out], "Indices": [idx]}, attrs={"axis": axis},
    )
    return out, idx


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", input=x, act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="reshape2", inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"shape": list(shape)},
    )
    return helper.append_activation(out)


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="squeeze2", inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]}, attrs={"axes": list(axes)},
    )
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        type="unsqueeze2", inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]}, attrs={"axes": list(axes)},
    )
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="flatten2", inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]}, attrs={"axis": axis},
    )
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="transpose2", inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]}, attrs={"axis": list(perm)},
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", input=input, name=name)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = 0
        sections = list(num_or_sections)
    outs = [
        helper.create_variable_for_type_inference(input.dtype)
        for _ in builtins_range(num or len(sections))
    ]
    helper.append_op(
        type="split", inputs={"X": [input]}, outputs={"Out": outs},
        attrs={"num": num, "sections": sections, "axis": dim},
    )
    return outs


def stack(x, axis=0):
    helper = LayerHelper("stack")
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(type="stack", inputs={"X": list(x)}, outputs={"Y": [out]},
                     attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack", input=x)
    if num is None:
        num = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in builtins_range(num)]
    helper.append_op(type="unstack", inputs={"X": [x]}, outputs={"Y": outs},
                     attrs={"axis": axis, "num": num})
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="expand", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"expand_times": list(expand_times)})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="slice", inputs={"Input": [input]}, outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def shape(input):
    helper = LayerHelper("shape", input=input)
    out = helper.create_variable_for_type_inference(DataType.INT32, stop_gradient=True)
    helper.append_op(type="shape", inputs={"Input": [input]}, outputs={"Out": [out]})
    return out


def gather(input, index):
    helper = LayerHelper("gather", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="gather", inputs={"X": [input], "Index": [index]}, outputs={"Out": [out]}
    )
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]}, attrs={"overwrite": overwrite},
    )
    return out


def one_hot_v2(input, depth):
    from .nn import one_hot

    return one_hot(input, depth)


def _scalar_reduce_bool(op_core, x):
    from .nn import _simple_act

    helper = LayerHelper(op_core, input=x)
    out = helper.create_variable_for_type_inference(DataType.BOOL, stop_gradient=True)
    helper.append_op(type=op_core, inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def has_inf(x):
    return _scalar_reduce_bool("isinf", x)


def has_nan(x):
    return _scalar_reduce_bool("isnan", x)


def isfinite(x):
    return _scalar_reduce_bool("isfinite", x)


import builtins


def builtins_range(n):
    return builtins.range(n)


def range(start, end, step, dtype):
    helper = LayerHelper("range")
    dtype = convert_dtype(dtype)

    # python-scalar bounds ride as attrs so the lowering sees static values
    # (a Variable bound would be a tracer under jit, and the output length
    # fixes an XLA shape); Variable bounds must be compile-time constants
    attrs = {"dtype": int(dtype)}
    inputs = {}
    for slot, v in (("Start", start), ("End", end), ("Step", step)):
        if isinstance(v, Variable):
            inputs[slot] = [v]
        else:
            attrs[f"const_{slot.lower()}"] = float(v)

    out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(type="range", inputs=inputs, outputs={"Out": [out]}, attrs=attrs)
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment", input=x)
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="increment", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"step": float(value)})
    return out


def cumsum(x, axis=None, exclusive=None, reverse=None):
    helper = LayerHelper("cumsum", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    attrs = {}
    if axis is not None:
        attrs["axis"] = axis
    if exclusive is not None:
        attrs["exclusive"] = exclusive
    if reverse is not None:
        attrs["reverse"] = reverse
    helper.append_op(type="cumsum", inputs={"X": [x]}, outputs={"Out": [out]}, attrs=attrs)
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", input=x, act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"scale": float(scale), "bias": float(bias),
               "bias_after_scale": bias_after_scale},
    )
    return helper.append_activation(out)


def _reduce(op_type, input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper(op_type, input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is None:
        attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
    else:
        dims = dim if isinstance(dim, (list, tuple)) else [dim]
        attrs = {"dim": list(dims), "keep_dim": keep_dim, "reduce_all": False}
    helper.append_op(type=op_type, inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def tensor_array_to_tensor(input, axis=0, use_stack=False, name=None):
    """Concat/stack a LoDTensorArray into one tensor; also returns the
    per-step sizes (reference: layers/tensor.py tensor_array_to_tensor over
    tensor_array_to_tensor_op.cc)."""
    helper = LayerHelper("tensor_array_to_tensor", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_index = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="tensor_array_to_tensor",
        inputs={"X": [input]},
        outputs={"Out": [out], "OutIndex": [out_index]},
        attrs={"axis": int(axis), "use_stack": bool(use_stack)},
    )
    return out, out_index


def sum(x):
    """Elementwise sum of a list of tensors (reference: layers/tensor.py
    sum over operators/sum_op.cc); single-tensor input passes through the
    same op for API parity."""
    return sums(x if isinstance(x, (list, tuple)) else [x])


def merge_selected_rows(x, name=None):
    """Dedup a SelectedRows value's rows by id-sum (reference:
    layers/nn.py merge_selected_rows over merge_selected_rows_op.cc)."""
    helper = LayerHelper("merge_selected_rows", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="merge_selected_rows", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def get_tensor_from_selected_rows(x, name=None):
    """SelectedRows -> dense row tensor (reference: layers/nn.py
    get_tensor_from_selected_rows)."""
    helper = LayerHelper("get_tensor_from_selected_rows", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="get_tensor_from_selected_rows",
                     inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def load(out, file_path, load_as_fp16=False):
    """Load a saved blob into `out` at run time (reference:
    layers/tensor.py load over operators/load_op.cc; the blob is the .npy
    written by io.save_vars)."""
    helper = LayerHelper("load", input=out)
    helper.append_op(
        type="load", inputs={}, outputs={"Out": [out]},
        attrs={"file_path": file_path, "load_as_fp16": bool(load_as_fp16)},
    )
    return out
