"""Control-flow layers (reference: python/paddle/fluid/layers/control_flow.py).

This module currently carries the compare/logical layer fns; While /
StaticRNN / DynamicRNN / IfElse land with the control-flow op lowerings.
"""

from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = [
    "equal", "not_equal", "less_than", "less_equal",
    "greater_than", "greater_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not",
]


def _compare(op_type, x, y, cond=None):
    helper = LayerHelper(op_type, input=x)
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool")
        cond.stop_gradient = True
    helper.append_op(
        type=op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]}
    )
    return cond


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


def less_than(x, y, cond=None, force_cpu=None):
    return _compare("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def logical_and(x, y, out=None, name=None):
    return _compare("logical_and", x, y, out)


def logical_or(x, y, out=None, name=None):
    return _compare("logical_or", x, y, out)


def logical_xor(x, y, out=None, name=None):
    return _compare("logical_xor", x, y, out)


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not", input=x)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype="bool")
        out.stop_gradient = True
    helper.append_op(type="logical_not", inputs={"X": [x]}, outputs={"Out": [out]})
    return out
