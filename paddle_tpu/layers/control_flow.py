"""Control-flow layers (reference: python/paddle/fluid/layers/control_flow.py).

While / StaticRNN / DynamicRNN / IfElse / Switch plus the tensor-array and
rank-table helper layers.  The graph-building contract matches the reference
(sub-blocks under `while`/`conditional_block` ops, LOD_TENSOR_ARRAY vars,
lod_rank_table machinery); execution is TPU-native — static trip counts via
padded sequence shapes, trace-time unrolling, and if-conversion (see
paddle_tpu/ops/control_flow_ops.py).
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

from ..core.framework import Variable, default_main_program, unique_name
from ..core.proto import DataType, VarType, convert_dtype
from ..layer_helper import LayerHelper
from . import tensor as tensor_layers

__all__ = [
    "equal", "not_equal", "less_than", "less_equal",
    "greater_than", "greater_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not",
    "While", "StaticRNN", "DynamicRNN", "IfElse", "Switch",
    "increment", "array_write", "array_read", "array_length", "create_array",
    "lod_rank_table", "max_sequence_len", "lod_tensor_to_array",
    "array_to_lod_tensor", "shrink_memory", "split_lod_tensor",
    "merge_lod_tensor", "Print", "is_empty",
    "reorder_lod_tensor_by_rank",
]


def _compare(op_type, x, y, cond=None):
    helper = LayerHelper(op_type, input=x)
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool")
        cond.stop_gradient = True
    helper.append_op(
        type=op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]}
    )
    return cond


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


def less_than(x, y, cond=None, force_cpu=None):
    return _compare("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def logical_and(x, y, out=None, name=None):
    return _compare("logical_and", x, y, out)


def logical_or(x, y, out=None, name=None):
    return _compare("logical_or", x, y, out)


def logical_xor(x, y, out=None, name=None):
    return _compare("logical_xor", x, y, out)


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not", input=x)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype="bool")
        out.stop_gradient = True
    helper.append_op(type="logical_not", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


increment = tensor_layers.increment


# ---------------------------------------------------------------------------
# tensor arrays
# ---------------------------------------------------------------------------
def create_array(dtype, name=None):
    """Create a LOD_TENSOR_ARRAY var with an empty runtime value
    (reference: control_flow.py create_array — var only; here an op also
    seeds the functional array value)."""
    helper = LayerHelper("create_array", name=name)
    out = helper.block.create_var(
        name=unique_name("array"),
        shape=[],
        dtype=dtype,
        type=VarType.LOD_TENSOR_ARRAY,
    )
    helper.append_op(type="create_array", inputs={}, outputs={"Out": [out]})
    return out


def array_write(x, i, array=None):
    """array[i] = x (reference: tensor_array_read_write_op.cc)."""
    helper = LayerHelper("array_write", input=x)
    if array is None:
        array = create_array(x.dtype)
    helper.append_op(
        type="write_to_array",
        inputs={"X": [x], "I": [i], "Array": [array]},
        outputs={"Out": [array]},
    )
    return array


def array_read(array, i):
    helper = LayerHelper("array_read", input=array)
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(
        type="read_from_array", inputs={"X": [array], "I": [i]},
        outputs={"Out": [out]},
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length", input=array)
    out = helper.create_variable_for_type_inference("int64")
    out.stop_gradient = True
    helper.append_op(
        type="lod_array_length", inputs={"X": [array]}, outputs={"Out": [out]}
    )
    return out


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty", input=x)
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool")
        cond.stop_gradient = True
    helper.append_op(type="is_empty", inputs={"X": [x]}, outputs={"Out": [cond]})
    return cond


# ---------------------------------------------------------------------------
# rank table machinery
# ---------------------------------------------------------------------------
def lod_rank_table(x, level=0):
    helper = LayerHelper("lod_rank_table", input=x)
    table = helper.block.create_var(
        name=unique_name("lod_rank_table"), shape=[], dtype=DataType.INT64,
        type=VarType.RAW,
    )
    helper.append_op(
        type="lod_rank_table", inputs={"X": [x]}, outputs={"Out": [table]},
        attrs={"level": level},
    )
    return table


def max_sequence_len(rank_table):
    helper = LayerHelper("max_seqence_len", input=rank_table)
    out = helper.create_variable_for_type_inference("int64")
    out.stop_gradient = True
    helper.append_op(
        type="max_sequence_len", inputs={"RankTable": [rank_table]},
        outputs={"Out": [out]},
    )
    return out


def lod_tensor_to_array(x, table):
    helper = LayerHelper("lod_tensor_to_array", input=x)
    array = helper.block.create_var(
        name=unique_name("lod_tensor_to_array"), shape=list(x.shape),
        dtype=x.dtype, type=VarType.LOD_TENSOR_ARRAY,
    )
    helper.append_op(
        type="lod_tensor_to_array", inputs={"X": [x], "RankTable": [table]},
        outputs={"Out": [array]},
    )
    return array


def array_to_lod_tensor(x, table):
    helper = LayerHelper("array_to_lod_tensor", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="array_to_lod_tensor", inputs={"X": [x], "RankTable": [table]},
        outputs={"Out": [out]},
    )
    return out


def shrink_memory(x, i, table):
    helper = LayerHelper("shrink_memory", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="shrink_rnn_memory",
        inputs={"X": [x], "I": [i], "RankTable": [table]},
        outputs={"Out": [out]},
    )
    return out


def split_lod_tensor(input, mask, level=0):
    helper = LayerHelper("split_lod_tensor", input=input)
    out_true = helper.create_variable_for_type_inference(input.dtype)
    out_false = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="split_lod_tensor",
        inputs={"X": [input], "Mask": [mask]},
        outputs={"OutTrue": [out_true], "OutFalse": [out_false]},
        attrs={"level": level},
    )
    return out_true, out_false


def merge_lod_tensor(in_true, in_false, x, mask, level=0):
    helper = LayerHelper("merge_lod_tensor", input=x)
    out = helper.create_variable_for_type_inference(in_true.dtype)
    helper.append_op(
        type="merge_lod_tensor",
        inputs={"X": [x], "Mask": [mask], "InTrue": [in_true],
                "InFalse": [in_false]},
        outputs={"Out": [out]},
        attrs={"level": level},
    )
    return out


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug-print a tensor in-graph (reference: operators/print_op.cc)."""
    helper = LayerHelper("print", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={
            "first_n": first_n, "message": message or "",
            "summarize": summarize, "print_tensor_name": print_tensor_name,
            "print_phase": print_phase.upper(),
        },
    )
    return out


# ---------------------------------------------------------------------------
# sub-block capture analysis
# ---------------------------------------------------------------------------
def _analyze_block_io(sub_block, include_read_outputs: bool):
    """Names a sub-block reads from / writes to enclosing scopes.

    x_names: external names read by ops in the block (in first-read order).
    out_names: external names written by ops in the block.
    include_read_outputs adds externally-existing written vars to x_names
    (conditional_block needs their prior values for if-conversion selects).
    """
    def _in_ancestors(name: str) -> bool:
        b = sub_block.parent_block
        while b is not None:
            if b.desc.has_var(name):
                return True
            b = b.parent_block
        return False

    # op-order dataflow: infer_shape may shadow parent vars into the
    # sub-block desc, so "local" means *first defined by an op here before
    # any read*, and external names must resolve in an ancestor block.
    defined: set = set()
    reads: List[str] = []
    writes: List[str] = []
    seen_r, seen_w = set(), set()
    for op in sub_block.ops:
        for n in op.input_arg_names:
            if n and n not in defined and n not in seen_r and _in_ancestors(n):
                seen_r.add(n)
                reads.append(n)
        for n in op.output_arg_names:
            if n:
                if n not in seen_w and _in_ancestors(n):
                    seen_w.add(n)
                    writes.append(n)
                defined.add(n)
    if include_read_outputs:
        for n in writes:
            if n not in seen_r:
                reads.append(n)
                seen_r.add(n)
    return reads, writes


# ---------------------------------------------------------------------------
# While
# ---------------------------------------------------------------------------
class While:
    """Run a sub-block while a bool scalar condition holds
    (reference: control_flow.py While, operators/controlflow/while_op.cc).

    with While(cond).block():
        ...ops...; update cond
    """

    def __init__(self, cond, is_test: bool = False, name: Optional[str] = None):
        self.helper = LayerHelper("while", name=name)
        if cond.dtype not in ("bool", DataType.BOOL):
            raise TypeError("While condition must be a bool Variable")
        self.cond_var = cond
        self.is_test = is_test

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent_block = program.current_block()
        sub_block = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        x_names, out_names = _analyze_block_io(
            sub_block, include_read_outputs=False
        )
        # drop reads with no runtime value yet (arrays created empty are read
        # via create_array's output, which exists; params/feeds exist)
        parent_block.append_op(
            type="while",
            inputs={"X": x_names, "Condition": [self.cond_var]},
            outputs={"Out": out_names, "StepScopes": []},
            attrs={
                "sub_block": sub_block.idx,
                "is_test": self.is_test,
                "__x_names__": x_names,
                "__out_names__": out_names,
                "__cond_name__": self.cond_var.name,
            },
        )


# ---------------------------------------------------------------------------
# StaticRNN
# ---------------------------------------------------------------------------
class StaticRNN:
    """Unrolled RNN over time-major dense inputs [T, N, ...]
    (reference: control_flow.py StaticRNN / recurrent_op.cc).

    with rnn.step():
        word = rnn.step_input(x)          # [N, ...]
        prev = rnn.memory(init=boot)      # or shape=/value=
        hidden = fc([word, prev], ...)
        rnn.update_memory(prev, hidden)
        rnn.step_output(hidden)
    out = rnn()                           # [T, N, ...]
    """

    def __init__(self, name: Optional[str] = None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._parent_block = None
        self._sub_block = None
        self._counter = None
        self._cond = None
        self._seq_len_var = None
        self._seq_ref = None
        self._num_steps: Optional[int] = None
        self._outputs: List[tuple] = []  # (out_array, step_var)
        self._mem_updates: List[tuple] = []  # (mem_var, new_var)
        self._in_rnn = False

    @contextlib.contextmanager
    def step(self):
        program = self.helper.main_program
        self._parent_block = program.current_block()
        # loop counter + condition live in the parent block
        self._counter = _parent_fill_constant(
            self._parent_block, shape=[1], dtype="int64", value=0
        )
        self._cond = self._parent_block.create_var(
            name=unique_name("static_rnn_cond"), shape=[1], dtype=DataType.BOOL
        )
        self._sub_block = program._create_block()
        self._in_rnn = True
        try:
            yield
        except BaseException:
            program._rollback()
            raise
        self._in_rnn = False
        self._complete()

    def _assert_in_rnn(self):
        if not self._in_rnn:
            raise RuntimeError("StaticRNN method used outside rnn.step()")

    def step_input(self, x):
        self._assert_in_rnn()
        T = x.shape[0]
        if self._num_steps is None:
            if T is None or T < 0:
                raise ValueError(
                    "StaticRNN needs a static sequence length on axis 0"
                )
            self._num_steps = int(T)
        if self._seq_ref is None:
            self._seq_ref = x
        pb = self._parent_block
        array = pb.create_var(
            name=unique_name("static_rnn_input_array"), shape=[], dtype=x.dtype,
            type=VarType.LOD_TENSOR_ARRAY,
        )
        pb.append_op(
            type="unstack_into_array", inputs={"X": [x]},
            outputs={"Out": [array]}, attrs={"axis": 0},
        )
        step = self._sub_block.create_var(
            name=unique_name("static_rnn_step_in"),
            shape=list(x.shape[1:]), dtype=x.dtype,
        )
        self._sub_block.append_op(
            type="read_from_array", inputs={"X": [array], "I": [self._counter]},
            outputs={"Out": [step]},
        )
        return step

    def memory(self, init=None, shape=None, batch_ref=None, value=0.0,
               init_value=0.0, dtype="float32"):
        self._assert_in_rnn()
        pb = self._parent_block
        if init is None:
            if shape is None or self._seq_ref is None:
                raise ValueError(
                    "StaticRNN.memory needs init= or shape= (after step_input)"
                )
            boot = pb.create_var(
                name=unique_name("static_rnn_mem_boot"),
                shape=list(shape), dtype=dtype,
            )
            # batch dim comes from axis 1 of the time-major [T, N, ...] input
            pb.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [self._seq_ref]}, outputs={"Out": [boot]},
                attrs={
                    "shape": list(shape),
                    "dtype": convert_dtype(dtype),
                    "value": float(value if value else init_value),
                    "input_dim_idx": 1, "output_dim_idx": 0,
                },
            )
            init = boot
        mem = self._sub_block.create_var(
            name=unique_name("static_rnn_mem"),
            shape=list(init.shape), dtype=init.dtype,
        )
        # first iteration reads the boot value; later ones the updated value.
        # The loop-carried slot is a parent var seeded with the boot value.
        carry = pb.create_var(
            name=unique_name("static_rnn_mem_carry"),
            shape=list(init.shape), dtype=init.dtype,
        )
        pb.append_op(
            type="assign", inputs={"X": [init]}, outputs={"Out": [carry]}
        )
        self._sub_block.append_op(
            type="assign", inputs={"X": [carry]}, outputs={"Out": [mem]}
        )
        mem._carry_name = carry.name
        return mem

    def update_memory(self, mem, var):
        self._assert_in_rnn()
        carry = getattr(mem, "_carry_name", None)
        if carry is None:
            raise ValueError("update_memory target was not created by memory()")
        self._sub_block.append_op(
            type="assign", inputs={"X": [var]}, outputs={"Out": [carry]}
        )

    def step_output(self, o):
        self._assert_in_rnn()
        pb = self._parent_block
        array = pb.create_var(
            name=unique_name("static_rnn_out_array"), shape=[], dtype=o.dtype,
            type=VarType.LOD_TENSOR_ARRAY,
        )
        pb.append_op(type="create_array", inputs={}, outputs={"Out": [array]})
        self._sub_block.append_op(
            type="write_to_array",
            inputs={"X": [o], "I": [self._counter], "Array": [array]},
            outputs={"Out": [array]},
        )
        out_shape = [self._num_steps] + list(o.shape)
        out = pb.create_var(
            name=unique_name("static_rnn_out"), shape=out_shape, dtype=o.dtype
        )
        self._outputs.append((array, out))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        program = self.helper.main_program
        sub_block = self._sub_block
        pb = self._parent_block
        if self._num_steps is None:
            raise RuntimeError("StaticRNN needs at least one step_input")
        # trip bookkeeping appended at the end of the sub-block
        seq_len = _parent_fill_constant(
            pb, shape=[1], dtype="int64", value=self._num_steps
        )
        pb.append_op(
            type="less_than", inputs={"X": [self._counter], "Y": [seq_len]},
            outputs={"Out": [self._cond]},
        )
        sub_block.append_op(
            type="increment", inputs={"X": [self._counter]},
            outputs={"Out": [self._counter]}, attrs={"step": 1.0},
        )
        sub_block.append_op(
            type="less_than", inputs={"X": [self._counter], "Y": [seq_len]},
            outputs={"Out": [self._cond]},
        )
        program._rollback()
        x_names, out_names = _analyze_block_io(
            sub_block, include_read_outputs=False
        )
        pb.append_op(
            type="while",
            inputs={"X": x_names, "Condition": [self._cond]},
            outputs={"Out": out_names, "StepScopes": []},
            attrs={
                "sub_block": sub_block.idx,
                "is_test": False,
                "__x_names__": x_names,
                "__out_names__": out_names,
                "__cond_name__": self._cond.name,
            },
        )
        # stack step outputs back to [T, N, ...]
        for array, out in self._outputs:
            pb.append_op(
                type="stack_from_array", inputs={"X": [array]},
                outputs={"Out": [out]}, attrs={"axis": 0},
            )

    def __call__(self):
        outs = [out for _, out in self._outputs]
        if len(outs) == 1:
            return outs[0]
        return outs


def _parent_fill_constant(block, shape, dtype, value):
    out = block.create_var(
        name=unique_name("fill_constant"), shape=list(shape),
        dtype=convert_dtype(dtype),
    )
    out.stop_gradient = True
    block.append_op(
        type="fill_constant", inputs={}, outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": convert_dtype(dtype),
               "value": float(value), "force_cpu": False},
    )
    return out


# ---------------------------------------------------------------------------
# DynamicRNN
# ---------------------------------------------------------------------------
class DynamicRNN:
    """RNN over variable-length LoD sequences
    (reference: control_flow.py DynamicRNN).

    drnn = DynamicRNN()
    with drnn.block():
        word = drnn.step_input(sent)      # LoD input -> per-step [N, F]
        prev = drnn.memory(shape=[H], value=0.0)  # or init=
        hidden = fc([word, prev], ...)
        drnn.update_memory(prev, hidden)
        drnn.output(hidden)
    out = drnn()                          # LoD [N, T, F] result

    Design note vs the reference: the reference sorts sequences by length
    (lod_rank_table) and shrinks the batch each step so finished sequences
    drop out; that is a dynamic-shape optimization XLA cannot express.  Here
    every step runs the full padded batch and downstream ops mask by length
    — same math for row-independent cells, static shapes for the MXU.
    """

    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name: Optional[str] = None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.status = DynamicRNN.BEFORE_RNN
        self._parent_block = None
        self._sub_block = None
        self._counter = None
        self._cond = None
        self._rank_table = None
        self._max_len = None
        self._first_input = None
        self._outputs: List[tuple] = []
        self._mem_dict: Dict[str, str] = {}

    @contextlib.contextmanager
    def block(self):
        if self.status != DynamicRNN.BEFORE_RNN:
            raise RuntimeError("DynamicRNN.block() can only be entered once")
        program = self.helper.main_program
        self._parent_block = program.current_block()
        self._counter = _parent_fill_constant(
            self._parent_block, shape=[1], dtype="int64", value=0
        )
        self._cond = self._parent_block.create_var(
            name=unique_name("dynamic_rnn_cond"), shape=[1], dtype=DataType.BOOL
        )
        self._sub_block = program._create_block()
        self.status = DynamicRNN.IN_RNN
        try:
            yield
        except BaseException:
            program._rollback()
            raise
        self.status = DynamicRNN.AFTER_RNN
        self._complete()

    def _assert_in_rnn(self, method):
        if self.status != DynamicRNN.IN_RNN:
            raise RuntimeError(f"DynamicRNN.{method} must be called in block()")

    def step_input(self, x, level=0):
        self._assert_in_rnn("step_input")
        pb = self._parent_block
        if self._first_input is None:
            self._first_input = x
        if self._rank_table is None:
            with _block_guard(self.helper.main_program, pb):
                self._rank_table = lod_rank_table(x, level=level)
                self._max_len = max_sequence_len(self._rank_table)
                pb.append_op(
                    type="less_than",
                    inputs={"X": [self._counter], "Y": [self._max_len]},
                    outputs={"Out": [self._cond]},
                )
        with _block_guard(self.helper.main_program, pb):
            array = lod_tensor_to_array(x, self._rank_table)
        # LoD desc shapes are token-major [-1, F]; a step slice is [N, F],
        # which has the same desc shape
        step = self._sub_block.create_var(
            name=unique_name("dynamic_rnn_step_in"),
            shape=list(x.shape),
            dtype=x.dtype,
        )
        self._sub_block.append_op(
            type="read_from_array", inputs={"X": [array], "I": [self._counter]},
            outputs={"Out": [step]},
        )
        return step

    def static_input(self, x):
        """Whole-batch non-sequence input visible at every step.  The
        reference reorders rows to rank-table order; here row order is
        preserved, so this is the identity."""
        self._assert_in_rnn("static_input")
        return x

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32"):
        self._assert_in_rnn("memory")
        pb = self._parent_block
        if init is None:
            if shape is None:
                raise ValueError("DynamicRNN.memory needs init= or shape=")
            if self._rank_table is None:
                raise RuntimeError(
                    "call step_input before value-initialized memory()"
                )
            boot = pb.create_var(
                name=unique_name("dynamic_rnn_mem_boot"),
                shape=[-1] + list(shape), dtype=dtype,
            )
            pb.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [self._first_input]},
                outputs={"Out": [boot]},
                attrs={
                    "shape": [-1] + list(shape),
                    "dtype": convert_dtype(dtype),
                    "value": float(value),
                    "input_dim_idx": 0, "output_dim_idx": 0,
                },
            )
            init = boot
        carry = pb.create_var(
            name=unique_name("dynamic_rnn_mem_carry"),
            shape=list(init.shape), dtype=init.dtype,
        )
        pb.append_op(
            type="assign", inputs={"X": [init]}, outputs={"Out": [carry]}
        )
        mem = self._sub_block.create_var(
            name=unique_name("dynamic_rnn_mem"),
            shape=list(init.shape), dtype=init.dtype,
        )
        self._sub_block.append_op(
            type="assign", inputs={"X": [carry]}, outputs={"Out": [mem]}
        )
        self._mem_dict[mem.name] = carry.name
        return mem

    def update_memory(self, ex_mem, new_mem):
        self._assert_in_rnn("update_memory")
        carry = self._mem_dict.get(ex_mem.name)
        if carry is None:
            raise ValueError("update_memory target was not created by memory()")
        self._sub_block.append_op(
            type="assign", inputs={"X": [new_mem]}, outputs={"Out": [carry]}
        )

    def output(self, *outputs):
        self._assert_in_rnn("output")
        pb = self._parent_block
        for o in outputs:
            array = pb.create_var(
                name=unique_name("dynamic_rnn_out_array"), shape=[],
                dtype=o.dtype, type=VarType.LOD_TENSOR_ARRAY,
            )
            pb.append_op(type="create_array", inputs={}, outputs={"Out": [array]})
            self._sub_block.append_op(
                type="write_to_array",
                inputs={"X": [o], "I": [self._counter], "Array": [array]},
                outputs={"Out": [array]},
            )
            out = pb.create_var(
                name=unique_name("dynamic_rnn_out"),
                shape=[-1] + list(o.shape[1:] if len(o.shape) > 1 else []),
                dtype=o.dtype,
            )
            out.desc.lod_level = 1
            self._outputs.append((array, out))

    def _complete(self):
        if self._rank_table is None:
            raise RuntimeError("DynamicRNN needs at least one step_input")
        program = self.helper.main_program
        sub_block = self._sub_block
        pb = self._parent_block
        sub_block.append_op(
            type="increment", inputs={"X": [self._counter]},
            outputs={"Out": [self._counter]}, attrs={"step": 1.0},
        )
        sub_block.append_op(
            type="less_than",
            inputs={"X": [self._counter], "Y": [self._max_len]},
            outputs={"Out": [self._cond]},
        )
        program._rollback()
        x_names, out_names = _analyze_block_io(
            sub_block, include_read_outputs=False
        )
        pb.append_op(
            type="while",
            inputs={"X": x_names, "Condition": [self._cond]},
            outputs={"Out": out_names, "StepScopes": []},
            attrs={
                "sub_block": sub_block.idx,
                "is_test": False,
                "__x_names__": x_names,
                "__out_names__": out_names,
                "__cond_name__": self._cond.name,
            },
        )
        for array, out in self._outputs:
            pb.append_op(
                type="array_to_lod_tensor",
                inputs={"X": [array], "RankTable": [self._rank_table]},
                outputs={"Out": [out]},
            )

    def __call__(self, *args, **kwargs):
        if self.status != DynamicRNN.AFTER_RNN:
            raise RuntimeError("DynamicRNN result is only available after block()")
        outs = [out for _, out in self._outputs]
        if len(outs) == 1:
            return outs[0]
        return outs


@contextlib.contextmanager
def _block_guard(program, block):
    """Temporarily make `block` the program's current block."""
    saved = program.current_block_idx
    program.current_block_idx = block.idx
    try:
        yield
    finally:
        program.current_block_idx = saved


# ---------------------------------------------------------------------------
# IfElse
# ---------------------------------------------------------------------------
class IfElse:
    """Per-row branch on a [N, 1] bool mask
    (reference: control_flow.py IfElse via split/merge_lod_tensor).

    The reference physically routes rows into two smaller batches; here both
    branches compute on the full batch and merge_lod_tensor selects rows —
    if-conversion, the SPMD-friendly equivalent.
    """

    OUT_IF_ELSE_BLOCKS = 0
    IN_IF_ELSE_TRUE_BLOCKS = 1
    IN_IF_ELSE_FALSE_BLOCKS = 2

    def __init__(self, cond, name: Optional[str] = None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self.status = IfElse.OUT_IF_ELSE_BLOCKS
        # per-branch outputs, by call order
        self.output_table: List[List[Optional[Variable]]] = [[], []]
        self._inputs: Dict[str, tuple] = {}

    @contextlib.contextmanager
    def true_block(self):
        self.status = IfElse.IN_IF_ELSE_TRUE_BLOCKS
        yield
        self.status = IfElse.OUT_IF_ELSE_BLOCKS

    @contextlib.contextmanager
    def false_block(self):
        self.status = IfElse.IN_IF_ELSE_FALSE_BLOCKS
        yield
        self.status = IfElse.OUT_IF_ELSE_BLOCKS

    def input(self, x):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise RuntimeError("IfElse.input must be called inside a branch")
        if x.name not in self._inputs:
            self._inputs[x.name] = split_lod_tensor(x, self.cond)
        out_true, out_false = self._inputs[x.name]
        return (
            out_true
            if self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS
            else out_false
        )

    def output(self, *outs):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise RuntimeError("IfElse.output must be called inside a branch")
        branch = 0 if self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS else 1
        self.output_table[branch].extend(outs)

    def __call__(self):
        t, f = self.output_table
        if len(t) != len(f):
            raise RuntimeError(
                "IfElse branches produced different numbers of outputs"
            )
        return [
            merge_lod_tensor(ti, fi, ti, self.cond) for ti, fi in zip(t, f)
        ]


# ---------------------------------------------------------------------------
# Switch
# ---------------------------------------------------------------------------
class Switch:
    """First-matching-case scalar branch (reference: control_flow.py Switch;
    used by learning-rate schedules).  Each case body runs in a sub-block
    lowered via conditional_block if-conversion with `cond AND NOT matched`.
    """

    def __init__(self, name: Optional[str] = None):
        self.helper = LayerHelper("switch", name=name)
        self.inside_scope = False
        self._matched = None

    def __enter__(self):
        self.inside_scope = True
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.inside_scope = False
        return False

    @contextlib.contextmanager
    def case(self, condition):
        if not self.inside_scope:
            raise RuntimeError("Switch.case used outside 'with Switch()'")
        if self._matched is None:
            effective = condition
            self._matched = condition
        else:
            effective = logical_and(condition, logical_not(self._matched))
            self._matched = logical_or(self._matched, condition)
        yield from _conditional_block_ctx(self.helper, effective)

    @contextlib.contextmanager
    def default(self):
        if self._matched is None:
            raise RuntimeError("Switch.default needs at least one case first")
        effective = logical_not(self._matched)
        yield from _conditional_block_ctx(self.helper, effective)


def _conditional_block_ctx(helper, cond):
    """Shared body for Switch.case/default: build a sub-block, then append a
    conditional_block op (reference: conditional_block_op.cc)."""
    program = helper.main_program
    parent_block = program.current_block()
    sub_block = program._create_block()
    try:
        yield
    finally:
        program._rollback()
    x_names, out_names = _analyze_block_io(sub_block, include_read_outputs=True)
    parent_block.append_op(
        type="conditional_block",
        inputs={"Cond": [cond], "X": x_names},
        outputs={"Out": out_names, "Scope": []},
        attrs={
            "sub_block": sub_block.idx,
            "is_scalar_condition": True,
            "__x_names__": x_names,
            "__out_names__": out_names,
        },
    )


def reorder_lod_tensor_by_rank(x, rank_table):
    """Reorder batch rows into rank-table order (reference:
    layers/control_flow.py reorder_lod_tensor_by_rank)."""
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="reorder_lod_tensor_by_rank",
        inputs={"X": [x], "RankTable": [rank_table]},
        outputs={"Out": [out]},
    )
    return out
