"""Core NN layer functions (reference: python/paddle/fluid/layers/nn.py —
148 defs; this module covers the workhorses, widened over rounds)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..core.framework import Variable
from ..core.proto import DataType
from ..initializer import ConstantInitializer, NormalInitializer, XavierInitializer
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr

__all__ = [
    "fc",
    "embedding",
    "linear_chain_crf",
    "crf_decoding",
    "chunk_eval",
    "warpctc",
    "ctc_greedy_decoder",
    "beam_search",
    "beam_search_decode",
    "fused_attention",
    "edit_distance",
    "conv2d",
    "conv3d",
    "conv2d_transpose",
    "pool2d",
    "pool3d",
    "batch_norm",
    "fused_bn_add_act",
    "conv_bn_add_act",
    "layer_norm",
    "group_norm",
    "dropout",
    "softmax",
    "cross_entropy",
    "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "square_error_cost",
    "smooth_l1",
    "log_loss",
    "huber_loss",
    "accuracy",
    "auc",
    "topk",
    "matmul",
    "mul",
    "l2_normalize",
    "lrn",
    "label_smooth",
    "one_hot",
    "nce",
    "prelu",
    "brelu",
    "leaky_relu",
    "relu",
    "elu",
    "relu6",
    "pow",
    "stanh",
    "hard_sigmoid",
    "swish",
    "soft_relu",
    "maxout",
    "image_resize",
    "resize_bilinear",
    "resize_nearest",
    "pad",
    "pad2d",
    "pad_constant_like",
    "mean_iou",
    "clip",
    "clip_by_norm",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "elementwise_mod",
    "elementwise_floordiv",
    "cos_sim",
    "selu",
    "random_crop",
    "hash",
    "add_position_encoding",
    "similarity_focus",
    "adaptive_pool2d",
    "adaptive_pool3d",
    "conv3d_transpose",
    "unpool",
    "spp",
    "hsigmoid",
    "rank_loss",
    "margin_rank_loss",
    "bpr_loss",
    "dice_loss",
    "bilinear_tensor_product",
    "multiplex",
    "sampling_id",
    "space_to_depth",
    "crop",
    "image_resize_short",
]


def _pair(x, n=2):
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x] * n


def fc(
    input,
    size: int,
    num_flatten_dims: int = 1,
    param_attr=None,
    bias_attr=None,
    act: Optional[str] = None,
    is_test: bool = False,
    name: Optional[str] = None,
):
    """Fully-connected layer (reference: layers/nn.py fc) — composed from
    `mul` ops (one per input) + sum + bias + activation, exactly like the
    reference's generated program."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    inputs = helper.multiple_input()
    dtype = helper.input_dtype()
    param_attrs = helper.param_attr
    if not isinstance(param_attrs, list):
        param_attrs = [param_attrs] * len(inputs)

    mul_results = []
    for inp, pattr in zip(inputs, param_attrs):
        in_shape = list(inp.shape)
        fan_in = int(np.prod([abs(d) for d in in_shape[num_flatten_dims:]]))
        w = helper.create_parameter(pattr, shape=[fan_in, size], dtype=dtype)
        out = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [inp], "Y": [w]},
            outputs={"Out": [out]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(out)

    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op(type="sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(
    input,
    size: Sequence[int],
    is_sparse: bool = False,
    is_distributed: bool = False,
    padding_idx: Optional[int] = None,
    param_attr=None,
    dtype="float32",
    name: Optional[str] = None,
):
    """Embedding lookup (reference: layers/nn.py embedding -> lookup_table).
    is_sparse=True emits SelectedRows sparse gradients — (ids, rows) pairs
    whose size is the batch's id count, never the vocab (matches
    operators/lookup_table_op.cc:80).  sgd/adagrad apply them row-wise;
    adam/momentum stay dense-equivalent by default (their moments decay
    even at zero grad) and update only touched rows under
    Adam(lazy_mode=True).  Sharded tables go through paddle_tpu.parallel."""
    helper = LayerHelper("embedding", param_attr=param_attr, name=name)
    w = helper.create_parameter(
        helper.param_attr, shape=list(size), dtype=dtype,
        default_initializer=XavierInitializer(),
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="lookup_table",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [out]},
        attrs={
            "is_sparse": is_sparse,
            "is_distributed": is_distributed,
            "padding_idx": -1 if padding_idx is None else padding_idx,
        },
    )
    return out


def conv2d(
    input,
    num_filters: int,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups: int = 1,
    param_attr=None,
    bias_attr=None,
    use_cudnn: bool = True,
    act: Optional[str] = None,
    name: Optional[str] = None,
):
    """2-D convolution, NCHW (reference: layers/nn.py conv2d).  use_cudnn is
    accepted and ignored — XLA picks the conv algorithm on TPU."""
    helper = LayerHelper("conv2d", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    fsize = _pair(filter_size)
    filter_shape = [num_filters, num_channels // groups] + fsize
    fan_in = (num_channels // groups) * fsize[0] * fsize[1]
    std = (2.0 / fan_in) ** 0.5
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, std),
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": _pair(stride),
            "paddings": _pair(padding),
            "dilations": _pair(dilation),
            "groups": groups,
        },
    )
    pre_act = out
    if helper.bias_attr is not None:
        b = helper.create_parameter(helper.bias_attr, shape=[num_filters], dtype=dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="elementwise_add",
            inputs={"X": [out], "Y": [b]},
            outputs={"Out": [pre_act]},
            attrs={"axis": 1},
        )
    return helper.append_activation(pre_act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    helper = LayerHelper("conv3d", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    fsize = _pair(filter_size, 3)
    filter_shape = [num_filters, num_channels // groups] + fsize
    w = helper.create_parameter(helper.param_attr, shape=filter_shape, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv3d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": _pair(stride, 3),
            "paddings": _pair(padding, 3),
            "dilations": _pair(dilation, 3),
            "groups": groups,
        },
    )
    pre_act = out
    if helper.bias_attr is not None:
        b = helper.create_parameter(helper.bias_attr, shape=[num_filters], dtype=dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="elementwise_add", inputs={"X": [out], "Y": [b]},
            outputs={"Out": [pre_act]}, attrs={"axis": 1},
        )
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv2d_transpose", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    if filter_size is None:
        if output_size is None:
            raise ValueError("filter_size or output_size required")
        # invert out = (in-1)*stride - 2*pad + dilation*(k-1) + 1 for k
        output_size = _pair(output_size)
        h, w_ = input.shape[2], input.shape[3]
        filter_size = [
            (output_size[0] - (h - 1) * stride[0] + 2 * padding[0] - 1) // dilation[0] + 1,
            (output_size[1] - (w_ - 1) * stride[1] + 2 * padding[1] - 1) // dilation[1] + 1,
        ]
    else:
        filter_size = _pair(filter_size)
    w = helper.create_parameter(
        helper.param_attr,
        shape=[num_channels, num_filters // groups] + filter_size,
        dtype=dtype,
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation, "groups": groups},
    )
    pre_act = out
    if helper.bias_attr is not None:
        b = helper.create_parameter(helper.bias_attr, shape=[num_filters], dtype=dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="elementwise_add", inputs={"X": [out], "Y": [b]},
            outputs={"Out": [pre_act]}, attrs={"axis": 1},
        )
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False,
           exclusive=True, name=None):
    helper = LayerHelper("pool2d", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": _pair(pool_size),
            "strides": _pair(pool_stride),
            "paddings": _pair(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        },
    )
    return out


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False,
           exclusive=True, name=None):
    helper = LayerHelper("pool3d", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool3d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": _pair(pool_size, 3),
            "strides": _pair(pool_stride, 3),
            "paddings": _pair(pool_padding, 3),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        },
    )
    return out


def _bn_state(helper, c, dtype, param_attr, bias_attr, moving_mean_name,
              moving_variance_name):
    """The ONE copy of BN parameter/state creation (scale, bias, moving
    mean/variance with initializers, saved stats, output var) shared by
    batch_norm, fused_bn_add_act, and conv_bn_add_act."""
    scale = helper.create_parameter(
        param_attr or ParamAttr(),
        shape=[c], dtype=dtype, default_initializer=ConstantInitializer(1.0),
    )
    bias = helper.create_parameter(
        bias_attr or ParamAttr(),
        shape=[c], dtype=dtype, is_bias=True,
    )
    from ..core.framework import unique_name

    mean = helper.main_program.global_block().create_var(
        name=moving_mean_name or unique_name(f"{helper.name}.mean"),
        shape=[c], dtype=dtype, persistable=True, stop_gradient=True,
    )
    helper.set_variable_initializer(mean, ConstantInitializer(0.0))
    variance = helper.main_program.global_block().create_var(
        name=moving_variance_name or unique_name(f"{helper.name}.var"),
        shape=[c], dtype=dtype, persistable=True, stop_gradient=True,
    )
    helper.set_variable_initializer(variance, ConstantInitializer(1.0))

    saved_mean = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    return scale, bias, mean, variance, saved_mean, saved_var, out


def _bn_build(helper, input, data_layout, moving_mean_name,
              moving_variance_name):
    """Shared scale/bias/moving-stat setup for batch_norm and its fused
    twin: returns (inputs dict, outputs dict, out var)."""
    dtype = input.dtype
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale, bias, mean, variance, saved_mean, saved_var, out = _bn_state(
        helper, c, dtype, helper.param_attr, helper.bias_attr,
        moving_mean_name, moving_variance_name)
    inputs = {
        "X": [input], "Scale": [scale], "Bias": [bias],
        "Mean": [mean], "Variance": [variance],
    }
    outputs = {
        "Y": [out],
        "MeanOut": [mean],
        "VarianceOut": [variance],
        "SavedMean": [saved_mean],
        "SavedVariance": [saved_var],
    }
    return inputs, outputs, out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False,
               use_global_stats=False):
    """Batch normalization (reference: layers/nn.py batch_norm).  Moving
    mean/variance are persistable state vars updated in-graph."""
    helper = LayerHelper("batch_norm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    inputs, outputs, out = _bn_build(helper, input, data_layout,
                                     moving_mean_name, moving_variance_name)
    helper.append_op(
        type="batch_norm",
        inputs=inputs,
        outputs=outputs,
        attrs={
            "momentum": momentum, "epsilon": epsilon, "is_test": is_test,
            "data_layout": data_layout, "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out)


def fused_bn_add_act(x, y=None, act="relu", is_test=False, momentum=0.9,
                     epsilon=1e-5, param_attr=None, bias_attr=None,
                     data_layout="NCHW", name=None, moving_mean_name=None,
                     moving_variance_name=None, use_global_stats=False):
    """batch_norm(x) [+ y] -> act, fused into one op whose backward
    RECOMPUTES the normalize/add/act chain instead of storing it (the op
    carries @recompute@; see ops/nn_ops.py _fused_bn_add_act).  Replaces
    the batch_norm -> elementwise_add -> relu tail of a residual block
    (reference kernels being subsumed: operators/batch_norm_op.cu.cc:1 +
    elementwise/add + activation; later Paddle's
    contrib.layers.fused_bn_add_act has this same surface).  Numerics
    match the unfused chain exactly — parity-tested."""
    helper = LayerHelper("fused_bn_add_act", input=x, param_attr=param_attr,
                         bias_attr=bias_attr, act=None, name=name)
    inputs, outputs, out = _bn_build(helper, x, data_layout,
                                     moving_mean_name, moving_variance_name)
    if y is not None:
        inputs["Z"] = [y]
    helper.append_op(
        type="fused_bn_add_act",
        inputs=inputs,
        outputs=outputs,
        attrs={
            "momentum": momentum, "epsilon": epsilon, "is_test": is_test,
            "data_layout": data_layout, "use_global_stats": use_global_stats,
            "act": act, "@recompute@": True,
        },
    )
    return out


def conv_bn_add_act(input, num_filters, filter_size, residual=None,
                    stride=1, padding=0, groups=1, act="relu",
                    is_test=False, momentum=0.9, epsilon=1e-5,
                    param_attr=None, bn_param_attr=None,
                    bn_bias_attr=None, moving_mean_name=None,
                    moving_variance_name=None, name=None):
    """conv2d (no bias) + batch_norm + residual + activation as ONE op —
    the whole ResNet block tail including the conv (reference
    counterpart: operators/conv_fusion_op.cu.cc).  Where
    fused_bn_add_act fuses everything AFTER the conv, this op also owns
    the conv so the pallas implementation (FLAGS_conv_epilogue=pallas)
    can accumulate BN statistics inside the conv pass — the extra
    full-tensor stats read over the conv output disappears.  The default
    implementation ("reference") composes the same XLA conv + BN math in
    one lowering: numerics match the conv2d -> batch_norm -> add -> act
    chain exactly (parity-tested).  NCHW contract, square
    stride/padding."""
    helper = LayerHelper("conv_bn_add_act", input=input,
                         param_attr=param_attr, act=None, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    fsize = _pair(filter_size)
    if fsize[0] != fsize[1]:
        raise ValueError("conv_bn_add_act needs a square filter")
    if _pair(stride)[0] != _pair(stride)[1] or \
            _pair(padding)[0] != _pair(padding)[1]:
        # fail at model-definition time, not first exe.run (the lowering
        # would raise the same constraint much later)
        raise NotImplementedError(
            "conv_bn_add_act needs square stride/padding "
            f"(got stride={stride}, padding={padding})")
    filter_shape = [num_filters, num_channels // groups] + fsize
    fan_in = (num_channels // groups) * fsize[0] * fsize[1]
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, (2.0 / fan_in) ** 0.5),
    )
    scale, bias, mean, variance, saved_mean, saved_var, out = _bn_state(
        helper, num_filters, dtype, bn_param_attr, bn_bias_attr,
        moving_mean_name, moving_variance_name)

    inputs = {"X": [input], "Filter": [w], "Scale": [scale], "Bias": [bias],
              "Mean": [mean], "Variance": [variance]}
    if residual is not None:
        inputs["Z"] = [residual]
    helper.append_op(
        type="conv_bn_add_act",
        inputs=inputs,
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        attrs={
            "strides": _pair(stride), "paddings": _pair(padding),
            "groups": groups,
            "momentum": momentum, "epsilon": epsilon, "is_test": is_test,
            "act": act,
            # NO @recompute@ tag: the pallas impl's custom_vjp already
            # recomputes in backward, and the reference impl checkpoints
            # INSIDE the lowering — a compiler-level wrap here would
            # re-run the forward kernels a second time (review r5)
        },
    )
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("layer_norm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    norm_shape = [int(np.prod([abs(d) for d in input.shape[begin_norm_axis:]]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            helper.param_attr, shape=norm_shape, dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(helper.bias_attr, shape=norm_shape, dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    c = input.shape[1]
    inputs = {"X": [input]}
    if helper.param_attr is not None:
        s = helper.create_parameter(
            helper.param_attr, shape=[c], dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if helper.bias_attr is not None:
        b = helper.create_parameter(helper.bias_attr, shape=[c], dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="group_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"groups": groups, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(DataType.UINT8, stop_gradient=True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed if seed is not None else 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def softmax(input, use_cudnn=True, name=None, axis=-1):
    helper = LayerHelper("softmax", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="softmax", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=False,
                               return_softmax=False, smooth_eps=0.0):
    """smooth_eps (TPU extension, hard labels only): fold uniform label
    smoothing into the op analytically —
        loss = (1-eps) * CE(label) + eps * mean_V(-log p)
    identical to one_hot -> label_smooth -> soft-label CE but WITHOUT
    materializing any [*, V] label tensor (at vocab 32k and bench batch
    that chain moves ~1 GB/step of HBM)."""
    if smooth_eps and soft_label:
        # validate BEFORE creating any program vars: a rejected call must
        # not leave orphan Softmax/Loss descs behind
        raise ValueError("smooth_eps folds smoothing over HARD labels; "
                         "pre-smoothed soft labels must not smooth twice")
    helper = LayerHelper("softmax_with_cross_entropy", input=logits)
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax_out], "Loss": [loss]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index,
               "smooth_eps": float(smooth_eps)},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index},
    )
    return out


def square_error_cost(input, label):
    """(input-label)^2 via sub+square ops (reference: layers/nn.py
    square_error_cost builds the same two-op pattern)."""
    helper = LayerHelper("square_error_cost", input=input)
    minus_out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="elementwise_sub",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [minus_out]},
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="square", inputs={"X": [minus_out]}, outputs={"Out": [out]})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1", input=x)
    diff = helper.create_variable_for_type_inference(x.dtype)
    loss = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(
        type="smooth_l1_loss",
        inputs=inputs,
        outputs={"Diff": [diff], "Out": [loss]},
        attrs={"sigma": sigma if sigma is not None else 1.0},
    )
    return loss


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="log_loss",
        inputs={"Predicted": [input], "Labels": [label]},
        outputs={"Loss": [out]},
        attrs={"epsilon": epsilon},
    )
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss", input=input)
    residual = helper.create_variable_for_type_inference(input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="huber_loss",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out], "Residual": [residual]},
        attrs={"delta": delta},
    )
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", input=input, name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference(DataType.INT64, stop_gradient=True)
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [values], "Indices": [indices]},
        attrs={"k": k},
    )
    return values, indices


def accuracy(input, label, k=1, correct=None, total=None):
    """Classification accuracy: top_k + accuracy op (reference:
    layers/metric_op.py accuracy)."""
    helper = LayerHelper("accuracy", input=input)
    topk_out, topk_indices = topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference(DataType.FP32, stop_gradient=True)
    correct = correct or helper.create_variable_for_type_inference(DataType.INT32, stop_gradient=True)
    total = total or helper.create_variable_for_type_inference(DataType.INT32, stop_gradient=True)
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices], "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct], "Total": [total]},
    )
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    """Streaming AUC with persistable stat buffers (reference:
    layers/metric_op.py auc)."""
    helper = LayerHelper("auc", input=input)
    stat_pos = helper.create_global_variable(
        persistable=True, dtype=DataType.INT64, shape=[num_thresholds + 1]
    )
    stat_neg = helper.create_global_variable(
        persistable=True, dtype=DataType.INT64, shape=[num_thresholds + 1]
    )
    for v in (stat_pos, stat_neg):
        helper.set_variable_initializer(v, ConstantInitializer(0.0))
        v.stop_gradient = True
    auc_out = helper.create_variable_for_type_inference(DataType.FP64, stop_gradient=True)
    helper.append_op(
        type="auc",
        inputs={
            "Predict": [input], "Label": [label],
            "StatPos": [stat_pos], "StatNeg": [stat_neg],
        },
        outputs={
            "AUC": [auc_out], "StatPosOut": [stat_pos], "StatNegOut": [stat_neg],
        },
        attrs={"curve": curve, "num_thresholds": num_thresholds},
    )
    return auc_out, auc_out, [stat_pos, stat_neg]


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y, "alpha": float(alpha)},
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="mul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="norm",
        inputs={"X": [x]},
        outputs={"Out": [out], "Norm": [norm]},
        attrs={"axis": axis, "epsilon": epsilon},
    )
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="lrn", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"n": n, "k": k, "alpha": alpha, "beta": beta},
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    helper = LayerHelper("label_smooth", input=label, name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(
        type="label_smooth", inputs=inputs, outputs={"Out": [out]},
        attrs={"epsilon": float(epsilon)},
    )
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot", input=input)
    out = helper.create_variable_for_type_inference(DataType.FP32)
    helper.append_op(
        type="one_hot", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"depth": depth},
    )
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    helper = LayerHelper("nce", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[1]
    w = helper.create_parameter(helper.param_attr, shape=[num_total_classes, dim],
                                dtype=input.dtype)
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if helper.bias_attr is not None:
        b = helper.create_parameter(helper.bias_attr, shape=[num_total_classes, 1],
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    cost = helper.create_variable_for_type_inference(input.dtype)
    sample_logits = helper.create_variable_for_type_inference(input.dtype)
    sample_labels = helper.create_variable_for_type_inference(DataType.INT64, stop_gradient=True)
    helper.append_op(
        type="nce",
        inputs=inputs,
        outputs={"Cost": [cost], "SampleLogits": [sample_logits], "SampleLabels": [sample_labels]},
        attrs={
            "num_total_classes": num_total_classes,
            "num_neg_samples": num_neg_samples or 10,
            "seed": seed,
        },
    )
    return cost


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", input=x, param_attr=param_attr, name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [1, x.shape[1], 1, 1]
    else:
        alpha_shape = [1] + list(x.shape)[1:]
    alpha = helper.create_parameter(
        helper.param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25),
    )
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="prelu", inputs={"X": [x], "Alpha": [alpha]},
        outputs={"Out": [out]}, attrs={"mode": mode},
    )
    return out


def _simple_act(op_type, x, attrs=None, name=None):
    helper = LayerHelper(op_type, input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs=attrs or {})
    return out


def relu(x, name=None):
    return _simple_act("relu", x, name=name)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _simple_act("brelu", x, {"t_min": t_min, "t_max": t_max}, name)


def leaky_relu(x, alpha=0.02, name=None):
    return _simple_act("leaky_relu", x, {"alpha": alpha}, name)


def elu(x, alpha=1.0, name=None):
    return _simple_act("elu", x, {"alpha": alpha}, name)


def relu6(x, threshold=6.0, name=None):
    return _simple_act("relu6", x, {"threshold": threshold}, name)


def pow(x, factor=1.0, name=None):
    return _simple_act("pow", x, {"factor": factor}, name)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _simple_act("stanh", x, {"scale_a": scale_a, "scale_b": scale_b}, name)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _simple_act("hard_sigmoid", x, {"slope": slope, "offset": offset}, name)


def swish(x, beta=1.0, name=None):
    return _simple_act("swish", x, {"beta": beta}, name)


def soft_relu(x, threshold=40.0, name=None):
    return _simple_act("soft_relu", x, {"threshold": threshold}, name)


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="maxout", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"groups": groups})
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True):
    op_type = {"BILINEAR": "bilinear_interp", "NEAREST": "nearest_interp"}[resample]
    helper = LayerHelper(op_type, input=input, name=name)
    if out_shape is None:
        out_shape = [int(input.shape[2] * scale), int(input.shape[3] * scale)]
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type=op_type, inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"out_h": out_shape[0], "out_w": out_shape[1]},
    )
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None, actual_shape=None):
    return image_resize(input, out_shape, scale, name, "BILINEAR", actual_shape)


def resize_nearest(input, out_shape=None, scale=None, name=None, actual_shape=None):
    return image_resize(input, out_shape, scale, name, "NEAREST", actual_shape)


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="pad", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "pad_value": float(pad_value)},
    )
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pad2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "mode": mode,
               "pad_value": float(pad_value), "data_format": data_format},
    )
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    helper = LayerHelper("pad_constant_like", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="pad_constant_like", inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]}, attrs={"pad_value": float(pad_value)},
    )
    return out


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou", input=input)
    out_mean_iou = helper.create_variable_for_type_inference(DataType.FP32)
    out_wrong = helper.create_variable_for_type_inference(DataType.INT32)
    out_correct = helper.create_variable_for_type_inference(DataType.INT32)
    helper.append_op(
        type="mean_iou",
        inputs={"Predictions": [input], "Labels": [label]},
        outputs={"OutMeanIou": [out_mean_iou], "OutWrong": [out_wrong],
                 "OutCorrect": [out_correct]},
        attrs={"num_classes": num_classes},
    )
    return out_mean_iou, out_wrong, out_correct


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="clip", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"min": float(min), "max": float(max)})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="clip_by_norm", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"max_norm": float(max_norm)})
    return out


def _elementwise(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, input=x, act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type=op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_pow", x, y, axis, act, name)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mod", x, y, axis, act, name)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_floordiv", x, y, axis, act, name)


# ---------------------------------------------------------------------------
# structured prediction (reference: layers/nn.py linear_chain_crf,
# crf_decoding, chunk_eval, warpctc, ctc_greedy_decoder)
# ---------------------------------------------------------------------------
def linear_chain_crf(input, label, param_attr=None):
    helper = LayerHelper("linear_chain_crf", **locals())
    size = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[size + 2, size], dtype=input.dtype
    )
    alpha = helper.create_variable_for_type_inference(input.dtype)
    emission_exps = helper.create_variable_for_type_inference(input.dtype)
    transition_exps = helper.create_variable_for_type_inference(input.dtype)
    log_likelihood = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="linear_chain_crf",
        inputs={"Emission": [input], "Transition": transition, "Label": [label]},
        outputs={
            "Alpha": [alpha],
            "EmissionExps": [emission_exps],
            "TransitionExps": [transition_exps],
            "LogLikelihood": [log_likelihood],
        },
    )
    return log_likelihood


def crf_decoding(input, param_attr, label=None):
    helper = LayerHelper("crf_decoding", **locals())
    transition = helper.get_parameter(param_attr.name)
    viterbi_path = helper.create_variable_for_type_inference("int64")
    inputs = {"Emission": [input], "Transition": transition}
    if label is not None:
        inputs["Label"] = [label]
    helper.append_op(
        type="crf_decoding", inputs=inputs,
        outputs={"ViterbiPath": [viterbi_path]},
    )
    return viterbi_path


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    helper = LayerHelper("chunk_eval", **locals())
    precision = helper.create_variable_for_type_inference("float32")
    recall = helper.create_variable_for_type_inference("float32")
    f1_score = helper.create_variable_for_type_inference("float32")
    num_infer_chunks = helper.create_variable_for_type_inference("int64")
    num_label_chunks = helper.create_variable_for_type_inference("int64")
    num_correct_chunks = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="chunk_eval",
        inputs={"Inference": [input], "Label": [label]},
        outputs={
            "Precision": [precision],
            "Recall": [recall],
            "F1-Score": [f1_score],
            "NumInferChunks": [num_infer_chunks],
            "NumLabelChunks": [num_label_chunks],
            "NumCorrectChunks": [num_correct_chunks],
        },
        attrs={
            "num_chunk_types": num_chunk_types,
            "chunk_scheme": chunk_scheme,
            "excluded_chunk_types": excluded_chunk_types or [],
        },
    )
    return (precision, recall, f1_score, num_infer_chunks, num_label_chunks,
            num_correct_chunks)


def warpctc(input, label, blank=0, norm_by_times=False):
    helper = LayerHelper("warpctc", **locals())
    loss_out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="warpctc",
        inputs={"Logits": [input], "Label": [label]},
        outputs={"Loss": [loss_out]},
        attrs={"blank": blank, "norm_by_times": norm_by_times},
    )
    return loss_out


def ctc_greedy_decoder(input, blank, name=None):
    """argmax per step -> ctc_align (reference: layers/nn.py
    ctc_greedy_decoder)."""
    from . import tensor as tensor_layers

    helper = LayerHelper("ctc_greedy_decoder", **locals())
    topk_indices = tensor_layers.argmax(input, axis=-1)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="ctc_align",
        inputs={"Input": [topk_indices]},
        outputs={"Output": [out]},
        attrs={"blank": blank, "merge_repeated": True},
    )
    return out


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, name=None):
    """One beam-selection step (reference: layers/nn.py beam_search over
    operators/beam_search_op.cc).  Returns (selected_ids, selected_scores);
    the parent-index tensor is retrievable as the third output var."""
    helper = LayerHelper("beam_search", **locals())
    selected_ids = helper.create_variable_for_type_inference("int64")
    selected_scores = helper.create_variable_for_type_inference("float32")
    parent_idx = helper.create_variable_for_type_inference("int64")
    inputs = {
        "pre_ids": [pre_ids],
        "pre_scores": [pre_scores],
        "scores": [scores],
    }
    if ids is not None:
        inputs["ids"] = [ids]
    helper.append_op(
        type="beam_search",
        inputs=inputs,
        outputs={
            "selected_ids": [selected_ids],
            "selected_scores": [selected_scores],
            "parent_idx": [parent_idx],
        },
        attrs={"level": level, "beam_size": beam_size, "end_id": end_id},
    )
    selected_ids._parent_idx = parent_idx
    return selected_ids, selected_scores


def beam_search_decode(ids, scores, beam_size, end_id, name=None,
                       parent_idx=None):
    """Backtrack beam arrays into sentences (reference: layers/nn.py
    beam_search_decode)."""
    helper = LayerHelper("beam_search_decode", **locals())
    sentence_ids = helper.create_variable_for_type_inference("int64")
    sentence_scores = helper.create_variable_for_type_inference("float32")
    inputs = {"Ids": [ids], "Scores": [scores]}
    if parent_idx is not None:
        inputs["ParentIdx"] = [parent_idx]
    helper.append_op(
        type="beam_search_decode",
        inputs=inputs,
        outputs={
            "SentenceIds": [sentence_ids],
            "SentenceScores": [sentence_scores],
        },
        attrs={"beam_size": beam_size, "end_id": end_id},
    )
    return sentence_ids, sentence_scores


def fused_attention(q, k, v, causal=False, scale=None, k_lengths=None,
                    name=None):
    """Flash-attention in one op: q/k/v [B, H, S, D], optional [B] valid key
    counts instead of an additive bias (TPU-native; see
    paddle_tpu/kernels/flash_attention.py)."""
    helper = LayerHelper("fused_attention", input=q, name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if k_lengths is not None:
        inputs["KLengths"] = [k_lengths]
    helper.append_op(
        type="fused_attention", inputs=inputs, outputs={"Out": [out]},
        attrs={"causal": causal, "scale": float(scale) if scale else 0.0},
    )
    return out


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  name=None):
    """Levenshtein distance per sequence pair + batch sequence count
    (reference: layers/nn.py edit_distance over edit_distance_op.cc)."""
    helper = LayerHelper("edit_distance", **locals())
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="edit_distance",
        inputs={"Hyps": [input], "Refs": [label]},
        outputs={"Out": [out], "SequenceNum": [seq_num]},
        attrs={"normalized": normalized,
               "ignored_tokens": ignored_tokens or []},
    )
    return out, seq_num


def cos_sim(X, Y, name=None):
    """Row-wise cosine similarity (reference: layers/nn.py cos_sim over
    operators/cos_sim_op.cc); Y may be [1, D] to broadcast."""
    helper = LayerHelper("cos_sim", **locals())
    out = helper.create_variable_for_type_inference(X.dtype)
    xnorm = helper.create_variable_for_type_inference(X.dtype)
    ynorm = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op(
        type="cos_sim", inputs={"X": [X], "Y": [Y]},
        outputs={"Out": [out], "XNorm": [xnorm], "YNorm": [ynorm]},
    )
    return out


def selu(x, scale=None, alpha=None, name=None):
    """Scaled ELU (reference: layers/nn.py selu over operators/selu_op.cc)."""
    helper = LayerHelper("selu", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    attrs = {}
    if scale is not None:
        attrs["scale"] = float(scale)
    if alpha is not None:
        attrs["alpha"] = float(alpha)
    helper.append_op(type="selu", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def random_crop(x, shape, seed=None, name=None):
    """Random per-instance crop of the trailing dims to `shape`
    (reference: layers/nn.py random_crop over operators/random_crop_op.h)."""
    helper = LayerHelper("random_crop", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    outputs = {"Out": [out]}
    if seed is not None:
        inputs["Seed"] = [seed]
        outputs["SeedOut"] = [
            helper.create_variable_for_type_inference("int64")
        ]
    helper.append_op(type="random_crop", inputs=inputs, outputs=outputs,
                     attrs={"shape": list(shape)})
    return out


def hash(input, hash_size, num_hash=1, name=None):
    """Hash int rows into [N, num_hash, 1] int64 buckets
    (reference: layers/nn.py hash over operators/hash_op.h)."""
    helper = LayerHelper("hash", input=input, name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="hash", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"num_hash": num_hash, "mod_by": hash_size},
    )
    return out


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    """alpha*x + beta*sinusoid(pos) (reference: layers/nn.py
    add_position_encoding over operators/add_position_encoding_op.h)."""
    helper = LayerHelper("add_position_encoding", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="add_position_encoding", inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"alpha": float(alpha), "beta": float(beta)},
    )
    return out


def similarity_focus(input, axis, indexes, name=None):
    """Similarity-focus 0/1 mask (reference: layers/nn.py similarity_focus
    over operators/similarity_focus_op.h)."""
    helper = LayerHelper("similarity_focus", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="similarity_focus", inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"axis": int(axis), "indexes": [int(i) for i in indexes]},
    )
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """Adaptive pooling to a fixed output grid (reference: layers/nn.py
    adaptive_pool2d over pool_op.cc's `adaptive` attr; require_index=True
    uses max_pool2d_with_index and also returns the argmax mask)."""
    helper = LayerHelper("adaptive_pool2d", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {
        "pooling_type": pool_type,
        "ksize": _pair(pool_size),
        "adaptive": True,
    }
    if require_index:
        if pool_type != "max":
            raise ValueError("require_index needs pool_type='max'")
        mask = helper.create_variable_for_type_inference("int32")
        helper.append_op(
            type="max_pool2d_with_index", inputs={"X": [input]},
            outputs={"Out": [out], "Mask": [mask]}, attrs=attrs,
        )
        return out, mask
    helper.append_op(type="pool2d", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """3-D adaptive pooling (see adaptive_pool2d)."""
    helper = LayerHelper("adaptive_pool3d", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {
        "pooling_type": pool_type,
        "ksize": _pair(pool_size, 3),
        "adaptive": True,
    }
    if require_index:
        if pool_type != "max":
            raise ValueError("require_index needs pool_type='max'")
        mask = helper.create_variable_for_type_inference("int32")
        helper.append_op(
            type="max_pool3d_with_index", inputs={"X": [input]},
            outputs={"Out": [out], "Mask": [mask]}, attrs=attrs,
        )
        return out, mask
    helper.append_op(type="pool3d", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """3-D transposed convolution (reference: layers/nn.py conv3d_transpose
    over conv_transpose_op.cc:358)."""
    helper = LayerHelper("conv3d_transpose", input=input,
                         param_attr=param_attr, bias_attr=bias_attr, act=act,
                         name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    stride = _pair(stride, 3)
    padding = _pair(padding, 3)
    dilation = _pair(dilation, 3)
    if filter_size is None:
        if output_size is None:
            raise ValueError("filter_size or output_size required")
        # invert out = (in-1)*stride - 2*pad + dilation*(k-1) + 1 for k
        output_size = _pair(output_size, 3)
        filter_size = [
            (output_size[i] - (input.shape[i + 2] - 1) * stride[i]
             + 2 * padding[i] - 1) // dilation[i] + 1
            for i in range(3)
        ]
    else:
        filter_size = _pair(filter_size, 3)
    w = helper.create_parameter(
        helper.param_attr,
        shape=[num_channels, num_filters // groups] + filter_size,
        dtype=dtype,
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv3d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups},
    )
    pre_act = out
    if helper.bias_attr is not None:
        b = helper.create_parameter(helper.bias_attr, shape=[num_filters],
                                    dtype=dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(dtype)
        helper.append_op(type="elementwise_add",
                         inputs={"X": [out], "Y": [b]},
                         outputs={"Out": [pre_act]}, attrs={"axis": 1})
    return helper.append_activation(pre_act)


def unpool(input, indices, ksize, strides=1, paddings=0, name=None):
    """Max-unpooling with indices from adaptive_pool2d(require_index=True) or
    max_pool2d_with_index (reference: operators/unpool_op.cc)."""
    helper = LayerHelper("unpool", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="unpool",
        inputs={"X": [input], "Indices": [indices]},
        outputs={"Out": [out]},
        attrs={"unpooling_type": "max", "ksize": _pair(ksize),
               "strides": _pair(strides), "paddings": _pair(paddings)},
    )
    return out


def spp(input, pyramid_height, pool_type="max", name=None):
    """Spatial pyramid pooling (reference: operators/spp_op.cc)."""
    helper = LayerHelper("spp", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="spp", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pyramid_height": int(pyramid_height),
               "pooling_type": pool_type},
    )
    return out


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """Hierarchical sigmoid loss layer (reference: layers/nn.py hsigmoid
    over operators/hierarchical_sigmoid_op.cc).  Default: complete binary
    tree over num_classes (W is [num_classes-1, D]); custom trees pass
    path_table/path_code.  is_sparse is accepted for API parity — grads
    here are dense (the embedding path owns the SelectedRows story)."""
    helper = LayerHelper("hsigmoid", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = input.dtype
    dim = input.shape[1]
    if is_custom and (path_table is None or path_code is None):
        raise ValueError("is_custom=True needs path_table/path_code")
    num_nodes = (
        path_table.shape[0] if is_custom else num_classes - 1
    )
    w = helper.create_parameter(helper.param_attr, shape=[num_nodes, dim],
                                dtype=dtype)
    inputs = {"X": [input], "W": [w], "Label": [label]}
    if helper.bias_attr is not None:
        b = helper.create_parameter(helper.bias_attr, shape=[num_nodes, 1],
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    if is_custom:
        inputs["PTable"] = [path_table]
        inputs["PathCode"] = [path_code]
    out = helper.create_variable_for_type_inference(dtype)
    pre_out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="hierarchical_sigmoid",
        inputs=inputs,
        outputs={"Out": [out], "PreOut": [pre_out]},
        attrs={"num_classes": num_classes, "is_sparse": is_sparse},
    )
    return out


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (reference: layers/nn.py rank_loss over
    operators/rank_loss_op.cc)."""
    helper = LayerHelper("rank_loss", **locals())
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(
        type="rank_loss",
        inputs={"Label": [label], "Left": [left], "Right": [right]},
        outputs={"Out": [out]},
    )
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """Margin ranking loss (reference: layers/nn.py margin_rank_loss)."""
    helper = LayerHelper("margin_rank_loss", **locals())
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(
        type="margin_rank_loss",
        inputs={"Label": [label], "X1": [left], "X2": [right]},
        outputs={"Out": [out], "Activated": [act]},
        attrs={"margin": float(margin)},
    )
    return out


def bpr_loss(input, label, name=None):
    """Bayesian personalized ranking loss (reference: layers/nn.py bpr_loss
    over operators/bpr_loss_op.cc)."""
    helper = LayerHelper("bpr_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="bpr_loss", inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
    )
    return out


def dice_loss(input, label, epsilon=1e-5):
    """Dice coefficient loss, 1 - 2|X*Y|/(|X|+|Y|) (reference:
    layers/nn.py dice_loss — a pure composition of elementwise/reduce
    layers, same here)."""
    from ..layers import one_hot, reduce_mean, reduce_sum, scale

    # label arrives [N, 1] (fluid id-column convention); one_hot folds it
    label_oh = one_hot(label, depth=input.shape[-1])
    reduce_dims = list(range(1, len(input.shape)))
    inse = reduce_sum(elementwise_mul(input, label_oh), dim=reduce_dims)
    denom = elementwise_add(
        reduce_sum(input, dim=reduce_dims),
        reduce_sum(label_oh, dim=reduce_dims),
    )
    # epsilon on the DENOMINATOR only (reference dice_loss): an empty
    # ground-truth mask yields loss 1, not 0
    frac = elementwise_div(
        scale(inse, scale=2.0),
        scale(denom, scale=1.0, bias=float(epsilon)),
    )
    return reduce_mean(scale(frac, scale=-1.0, bias=1.0))


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """out_k = x W_k y + b (reference: layers/nn.py bilinear_tensor_product
    over operators/bilinear_tensor_product_op.cc)."""
    helper = LayerHelper("bilinear_tensor_product", input=x,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = x.dtype
    w = helper.create_parameter(
        helper.param_attr, shape=[size, x.shape[1], y.shape[1]], dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if helper.bias_attr is not None:
        b = helper.create_parameter(helper.bias_attr, shape=[1, size],
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    helper.append_op(type="bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def multiplex(inputs, index):
    """Row-wise select among candidate tensors (reference: layers/nn.py
    multiplex over operators/multiplex_op.cc)."""
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(
        type="multiplex",
        inputs={"X": list(inputs), "Ids": [index]},
        outputs={"Out": [out]},
    )
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32", name=None):
    """Sample a category index per row from a probability matrix
    (reference: layers/nn.py sampling_id)."""
    helper = LayerHelper("sampling_id", input=x, name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="sampling_id", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"min": float(min), "max": float(max), "seed": seed},
    )
    if dtype not in ("int64", DataType.INT64):
        from .tensor import cast

        return cast(out, dtype)
    return out


def space_to_depth(x, blocksize, name=None):
    """Rearrange spatial blocks into channels (reference: layers/nn.py
    space_to_depth over operators/space_to_depth_op.cc)."""
    helper = LayerHelper("space_to_depth", input=x, name=name)
    n, c, h, w = x.shape
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="space_to_depth", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"blocksize": int(blocksize)},
    )
    return out


def crop(x, shape=None, offsets=None, name=None):
    """Static crop (reference: layers/nn.py crop over operators/crop_op.cc)."""
    helper = LayerHelper("crop", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    if shape is None:
        shape = list(x.shape)
    if hasattr(shape, "dtype"):  # Variable reference form: use its shape
        shape = list(shape.shape)
    if offsets is None:
        offsets = [0] * len(shape)
    helper.append_op(
        type="crop", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"shape": [int(s) for s in shape],
               "offsets": [int(o) for o in offsets]},
    )
    return out


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT side equals out_short_len, keeping aspect
    (reference: layers/nn.py image_resize_short)."""
    in_shape = list(input.shape)
    hw = in_shape[2:4]
    short_idx = hw.index(min(hw))
    out_shape = list(hw)
    out_shape[short_idx] = out_short_len
    out_shape[1 - short_idx] = int(
        round(hw[1 - short_idx] * (out_short_len / float(hw[short_idx])))
    )
    return image_resize(input, out_shape=out_shape, resample=resample)
