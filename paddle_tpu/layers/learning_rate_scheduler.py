"""In-graph learning-rate schedules
(reference: python/paddle/fluid/layers/learning_rate_scheduler.py).

Each schedule builds ops over an auto-incremented global step counter and
returns a [1] float Variable, passed as `learning_rate=` to an Optimizer.
As in the reference, the schedule is *part of the program* — under XLA it
folds into the fused update computation, there is no host-side LR logic.
"""

from __future__ import annotations

import math

from ..core.framework import default_main_program, default_startup_program, unique_name
from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper
from . import control_flow
from . import ops as act_ops
from . import tensor

__all__ = [
    "autoincreased_step_counter",
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "noam_decay",
    "cosine_decay",
    "linear_lr_warmup",
    "append_LARS",
]


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable int64 step counter, incremented at the top of every run
    (reference: layers/nn.py autoincreased_step_counter)."""
    counter_name = counter_name or "@STEP_COUNTER@"
    main = default_main_program().global_block()
    if main.desc.has_var(counter_name):
        return main.var(counter_name)
    counter = main.create_var(
        name=counter_name, dtype="int64", shape=[1], persistable=True,
        stop_gradient=True,
    )
    startup = default_startup_program().global_block()
    sv = startup.create_var(
        name=counter_name, dtype="int64", shape=[1], persistable=True
    )
    ConstantInitializer(float(begin - step))(sv, startup)
    main._prepend_op(
        type="increment", inputs={"X": [counter]}, outputs={"Out": [counter]},
        attrs={"step": float(step)},
    )
    return counter


def _decay_step_counter(begin=0):
    return tensor.cast(
        autoincreased_step_counter(
            counter_name="@LR_DECAY_COUNTER@", begin=begin, step=1
        ),
        "float32",
    )


def _const(value):
    return tensor.fill_constant(shape=[1], dtype="float32", value=float(value))


def _pow(x, y):
    from . import nn

    if not hasattr(y, "name"):
        y = _const(y)
    return nn._elementwise("elementwise_pow", x, y)


def _div(x, y):
    from . import nn

    if not hasattr(y, "name"):
        y = _const(y)
    return nn._elementwise("elementwise_div", x, y)


def noam_decay(d_model, warmup_steps):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (reference: learning_rate_scheduler.py noam_decay; Vaswani et al.)."""
    step = _decay_step_counter(begin=1)
    a = _pow(step, -0.5)
    b = tensor.scale(step, scale=warmup_steps ** -1.5)
    from . import nn

    return tensor.scale(
        nn._elementwise("elementwise_min", a, b), scale=d_model ** -0.5
    )


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """lr = base * decay_rate ^ (step / decay_steps)."""
    step = _decay_step_counter()
    div = tensor.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = act_ops.floor(div)
    return tensor.scale(_pow(_const(decay_rate), div), scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """lr = base * exp(-decay_rate * step / decay_steps)."""
    step = _decay_step_counter()
    div = tensor.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = act_ops.floor(div)
    return tensor.scale(
        act_ops.exp(tensor.scale(div, scale=-float(decay_rate))),
        scale=float(learning_rate),
    )


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """lr = base / (1 + decay_rate * step / decay_steps)."""
    step = _decay_step_counter()
    div = tensor.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = act_ops.floor(div)
    denom = tensor.scale(div, scale=float(decay_rate), bias=1.0)
    return _div(_const(learning_rate), denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    """lr = (base - end) * (1 - step/decay_steps)^power + end."""
    from . import nn

    step = _decay_step_counter()
    if cycle:
        div_res = act_ops.ceil(tensor.scale(step, scale=1.0 / decay_steps))
        # at step 0 the ceil is 0; use 1 so the first cycle spans decay_steps
        zero = _const(0.0)
        eq = tensor.cast(control_flow.equal(step, zero), "float32")
        div_res = nn._elementwise(
            "elementwise_add", div_res, eq
        )
        decay_var = nn._elementwise(
            "elementwise_mul", _const(decay_steps), div_res
        )
        frac = _div(step, decay_var)
    else:
        capped = nn._elementwise(
            "elementwise_min", step, _const(decay_steps)
        )
        frac = tensor.scale(capped, scale=1.0 / decay_steps)
    base = tensor.scale(frac, scale=-1.0, bias=1.0)
    return tensor.scale(
        _pow(base, power),
        scale=float(learning_rate) - float(end_learning_rate),
        bias=float(end_learning_rate),
    )


def piecewise_decay(boundaries, values):
    """Piecewise-constant schedule via Switch
    (reference: learning_rate_scheduler.py piecewise_decay)."""
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    main = default_main_program().global_block()
    lr_name = unique_name("learning_rate")
    lr = main.create_var(
        name=lr_name, shape=[1], dtype="float32", persistable=True,
        stop_gradient=True,
    )
    startup = default_startup_program().global_block()
    sv = startup.create_var(
        name=lr_name, shape=[1], dtype="float32", persistable=True
    )
    ConstantInitializer(float(values[0]))(sv, startup)

    step = _decay_step_counter()
    with control_flow.Switch() as switch:
        for i, bound in enumerate(boundaries):
            with switch.case(control_flow.less_than(step, _const(bound))):
                tensor.assign(_const(values[i]), lr)
        with switch.default():
            tensor.assign(_const(values[-1]), lr)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    """lr = 0.5 * base * (1 + cos(pi * epoch / epochs))."""
    step = _decay_step_counter()
    epoch = act_ops.floor(tensor.scale(step, scale=1.0 / step_each_epoch))
    inner = tensor.scale(epoch, scale=math.pi / epochs)
    return tensor.scale(
        act_ops.cos(inner), scale=0.5 * float(learning_rate), bias=1.0,
        bias_after_scale=False,
    )


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Linear ramp from start_lr to end_lr over warmup_steps, then the wrapped
    schedule (reference: learning_rate_scheduler.py linear_lr_warmup)."""
    main = default_main_program().global_block()
    lr_name = unique_name("learning_rate_warmup")
    lr = main.create_var(
        name=lr_name, shape=[1], dtype="float32", persistable=True,
        stop_gradient=True,
    )
    startup = default_startup_program().global_block()
    sv = startup.create_var(
        name=lr_name, shape=[1], dtype="float32", persistable=True
    )
    ConstantInitializer(float(start_lr))(sv, startup)

    step = _decay_step_counter()
    with control_flow.Switch() as switch:
        with switch.case(control_flow.less_than(step, _const(warmup_steps))):
            ramp = tensor.scale(
                step, scale=(float(end_lr) - float(start_lr)) / warmup_steps,
                bias=float(start_lr),
            )
            tensor.assign(ramp, lr)
        with switch.default():
            if hasattr(learning_rate, "name"):
                tensor.assign(learning_rate, lr)
            else:
                tensor.assign(_const(learning_rate), lr)
    return lr


def append_LARS(params_grads, learning_rate, weight_decay):
    """LARS scaling of the LR per layer (reference:
    learning_rate_scheduler.py append_LARS).  Kept for API parity; prefer
    LarsMomentumOptimizer."""
    from . import nn

    def _balanced_weight(param_norm, grad_norm):
        if weight_decay == 1.0:
            return grad_norm + param_norm
        return nn._elementwise(
            "elementwise_add",
            grad_norm,
            tensor.scale(param_norm, scale=float(weight_decay)),
        )

    outs = []
    for param, grad in params_grads:
        param_lr = param.optimize_attr.get("learning_rate", 1.0) \
            if hasattr(param, "optimize_attr") else 1.0
        param_norm = act_ops.sqrt(tensor.reduce_sum(act_ops.square(param)))
        grad_norm = act_ops.sqrt(tensor.reduce_sum(act_ops.square(grad)))
        decayed = _balanced_weight(param_norm, grad_norm)
        scaled = _div(
            tensor.scale(param_norm, scale=float(param_lr)), decayed
        )
        outs.append(nn._elementwise("elementwise_mul", learning_rate, scaled))
    return outs
