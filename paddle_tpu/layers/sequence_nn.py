"""Sequence + recurrent layer functions (fluid.layers parity).

Reference: python/paddle/fluid/layers/nn.py — dynamic_lstm :443,
dynamic_lstmp :577, dynamic_gru :727, gru_unit :830, sequence_conv :1799,
sequence_pool :1983, sequence_first/last_step :2061/2084, sequence_softmax,
sequence_expand(_as), sequence_reshape, sequence_slice, sequence_pad/unpad,
sequence_mask, sequence_concat, sequence_enumerate, sequence_reverse,
sequence_scatter, im2sequence, row_conv, lod_reset, lstm_unit (nets).
Each builds the same op graph as the reference; kernels are the
paddle_tpu.ops.sequence_ops / rnn_ops lowerings.
"""

from __future__ import annotations

from typing import Optional

from ..core.framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "dynamic_lstm", "dynamic_lstmp", "dynamic_gru", "gru_unit", "lstm_unit",
    "lstm",
    "sequence_conv", "sequence_pool", "sequence_first_step",
    "sequence_last_step", "sequence_softmax", "sequence_expand",
    "sequence_expand_as", "sequence_reshape", "sequence_slice",
    "sequence_pad", "sequence_unpad", "sequence_mask", "sequence_concat",
    "sequence_enumerate", "sequence_reverse", "sequence_scatter",
    "sequence_erase", "im2sequence", "row_conv", "lod_reset",
]


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """LSTM over a variable-length sequence batch (reference: layers/nn.py
    dynamic_lstm).  `input` must already be the 4H projection (use fc)."""
    helper = LayerHelper("lstm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden = size // 4
    weight = helper.create_parameter(helper.param_attr, shape=[hidden, size], dtype=dtype)
    bias_size = [1, 7 * hidden if use_peepholes else 4 * hidden]
    bias = helper.create_parameter(helper.bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    hidden_out = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    batch_gate = helper.create_variable_for_type_inference(dtype)
    batch_cell_pre_act = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(
        type="lstm", inputs=inputs,
        outputs={"Hidden": [hidden_out], "Cell": [cell],
                 "BatchGate": [batch_gate], "BatchCellPreAct": [batch_cell_pre_act]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation},
    )
    return hidden_out, cell


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """LSTM with recurrent projection (reference: layers/nn.py dynamic_lstmp)."""
    helper = LayerHelper("lstmp", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden = size // 4
    weight = helper.create_parameter(helper.param_attr, shape=[proj_size, size], dtype=dtype)
    proj_weight = helper.create_parameter(helper.param_attr, shape=[hidden, proj_size], dtype=dtype)
    bias_size = [1, 7 * hidden if use_peepholes else 4 * hidden]
    bias = helper.create_parameter(helper.bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    projection = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    batch_gate = helper.create_variable_for_type_inference(dtype)
    batch_cell_pre_act = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="lstmp",
        inputs={"Input": [input], "Weight": [weight],
                "ProjWeight": [proj_weight], "Bias": [bias]},
        outputs={"Projection": [projection], "Cell": [cell],
                 "BatchGate": [batch_gate], "BatchCellPreAct": [batch_cell_pre_act]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation},
    )
    return projection, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, name=None):
    """GRU over a variable-length sequence batch (reference: layers/nn.py
    dynamic_gru).  `input` must be the 3H projection."""
    helper = LayerHelper("gru", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = helper.input_dtype()
    weight = helper.create_parameter(helper.param_attr, shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(helper.bias_attr, shape=[1, 3 * size],
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    batch_gate = helper.create_variable_for_type_inference(dtype)
    batch_reset = helper.create_variable_for_type_inference(dtype)
    batch_hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(
        type="gru", inputs=inputs,
        outputs={"Hidden": [hidden], "BatchGate": [batch_gate],
                 "BatchResetHiddenPrev": [batch_reset], "BatchHidden": [batch_hidden]},
        attrs={"is_reverse": is_reverse, "gate_activation": gate_activation,
               "activation": candidate_activation},
    )
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", name=None):
    """Single GRU step (reference: layers/nn.py gru_unit)."""
    helper = LayerHelper("gru_unit", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = helper.input_dtype()
    size = size // 3
    weight = helper.create_parameter(helper.param_attr, shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(helper.bias_attr, shape=[1, 3 * size],
                                   dtype=dtype, is_bias=True)
    gate = helper.create_variable_for_type_inference(dtype)
    reset_hidden_prev = helper.create_variable_for_type_inference(dtype)
    updated_hidden = helper.create_variable_for_type_inference(dtype)
    acts = {"identity": 0, "sigmoid": 1, "tanh": 2, "relu": 3}
    helper.append_op(
        type="gru_unit",
        inputs={"Input": [input], "HiddenPrev": [hidden],
                "Weight": [weight], "Bias": [bias]},
        outputs={"Gate": [gate], "ResetHiddenPrev": [reset_hidden_prev],
                 "Hidden": [updated_hidden]},
        attrs={"activation": acts[activation], "gate_activation": acts[gate_activation]},
    )
    return updated_hidden, reset_hidden_prev, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One fc + lstm_unit step (reference: layers/nn.py lstm_unit)."""
    from .nn import fc
    from .tensor import concat

    size = cell_t_prev.shape[-1]
    concat_in = concat([x_t, hidden_t_prev], axis=-1)
    fc_out = fc(input=concat_in, size=4 * size, param_attr=param_attr,
                bias_attr=bias_attr)
    helper = LayerHelper("lstm_unit", input=x_t, name=name)
    dtype = x_t.dtype
    c = helper.create_variable_for_type_inference(dtype)
    h = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [fc_out], "C_prev": [cell_t_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": forget_bias},
    )
    return h, c


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Fused multi-layer LSTM over dense [T, N, D] input — the cuDNN-path
    layer (reference: layers/nn.py lstm -> cudnn_lstm op)."""
    helper = LayerHelper("cudnn_lstm", input=input, name=name)
    dtype = input.dtype
    in_size = input.shape[-1]
    ndir = 2 if is_bidirec else 1
    weight_size = 0
    d = in_size
    for _ in range(num_layers):
        for _ in range(ndir):
            weight_size += d * 4 * hidden_size + hidden_size * 4 * hidden_size + 4 * hidden_size
        d = hidden_size * ndir
    weight = helper.create_parameter(helper.param_attr, shape=[weight_size], dtype=dtype,
                                     default_initializer=default_initializer)
    out = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="cudnn_lstm",
        inputs={"Input": [input], "InitH": [init_h], "InitC": [init_c], "W": [weight]},
        outputs={"Out": [out], "last_h": [last_h], "last_c": [last_c]},
        attrs={"max_len": max_len, "hidden_size": hidden_size,
               "num_layers": num_layers, "is_bidirec": is_bidirec,
               "dropout_prob": dropout_prob, "is_test": is_test, "seed": seed},
    )
    return out, last_h, last_c


# -- sequence layers ---------------------------------------------------------
def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None,
                  name=None):
    helper = LayerHelper("sequence_conv", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    filter_shape = [filter_size * input.shape[-1], num_filters]
    filter_param = helper.create_parameter(helper.param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="sequence_conv",
        inputs={"X": [input], "Filter": [filter_param]},
        outputs={"Out": [pre_bias]},
        attrs={"contextStride": filter_stride,
               "contextStart": -int(filter_size // 2),
               "contextLength": filter_size},
    )
    pre_act = helper.append_bias_op(pre_bias)
    return helper.append_activation(pre_act)


def _pool(input, pool_type, is_test=False):
    helper = LayerHelper("sequence_pool", input=input)
    dtype = helper.input_dtype()
    pool_out = helper.create_variable_for_type_inference(dtype)
    max_index = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="sequence_pool",
        inputs={"X": [input]},
        outputs={"Out": [pool_out], "MaxIndex": [max_index]},
        attrs={"pooltype": pool_type.upper(), "is_test": is_test},
    )
    return pool_out


def sequence_pool(input, pool_type, is_test=False):
    return _pool(input, pool_type, is_test)


def sequence_first_step(input):
    return _pool(input, "FIRST")


def sequence_last_step(input):
    return _pool(input, "LAST")


def _simple_seq_op(op_type, input, attrs=None, extra_inputs=None, dtype=None):
    helper = LayerHelper(op_type, input=input)
    out = helper.create_variable_for_type_inference(dtype or helper.input_dtype())
    inputs = {"X": [input]}
    if extra_inputs:
        inputs.update(extra_inputs)
    helper.append_op(type=op_type, inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs or {})
    return out


def sequence_softmax(input, use_cudnn=False, name=None):
    return _simple_seq_op("sequence_softmax", input)


def sequence_expand(x, y, ref_level=-1, name=None):
    return _simple_seq_op("sequence_expand", x, attrs={"ref_level": ref_level},
                          extra_inputs={"Y": [y]})


def sequence_expand_as(x, y, name=None):
    return _simple_seq_op("sequence_expand_as", x, extra_inputs={"Y": [y]})


def sequence_reshape(input, new_dim):
    return _simple_seq_op("sequence_reshape", input, attrs={"new_dim": new_dim})


def sequence_slice(input, offset, length, name=None):
    return _simple_seq_op("sequence_slice", input,
                          extra_inputs={"Offset": [offset], "Length": [length]})


def sequence_reverse(x, name=None):
    helper = LayerHelper("sequence_reverse", input=x)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(type="sequence_reverse", inputs={"X": [x]},
                     outputs={"Y": [out]})
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", input=input)
    inputs = helper.multiple_input()
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(type="sequence_concat", inputs={"X": inputs},
                     outputs={"Out": [out]})
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    return _simple_seq_op("sequence_enumerate", input,
                          attrs={"win_size": win_size, "pad_value": pad_value})


def sequence_erase(input, tokens, name=None):
    return _simple_seq_op("sequence_erase", input, attrs={"tokens": tokens})


def sequence_scatter(input, index, updates, name=None):
    return _simple_seq_op("sequence_scatter", input,
                          extra_inputs={"Ids": [index], "Updates": [updates]})


def sequence_pad(x, pad_value, maxlen=None, name=None):
    helper = LayerHelper("sequence_pad", input=x)
    dtype = helper.input_dtype()
    out = helper.create_variable_for_type_inference(dtype)
    length = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="sequence_pad",
        inputs={"X": [x], "PadValue": [pad_value]},
        outputs={"Out": [out], "Length": [length]},
        attrs={"padded_length": -1 if maxlen is None else maxlen},
    )
    return out, length


def sequence_unpad(x, length, name=None):
    return _simple_seq_op("sequence_unpad", x, extra_inputs={"Length": [length]})


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ..core.proto import DataType, numpy_to_dtype
    import numpy as np

    helper = LayerHelper("sequence_mask", input=x)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="sequence_mask", inputs={"X": [x]}, outputs={"Y": [out]},
        attrs={"maxlen": -1 if maxlen is None else maxlen,
               "out_dtype": int(numpy_to_dtype(np.dtype(dtype)))},
    )
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    from .nn import _pair

    helper = LayerHelper("im2sequence", input=input)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    pads = padding if isinstance(padding, (list, tuple)) and len(padding) == 4 \
        else list(_pair(padding)) * 2
    helper.append_op(
        type="im2sequence", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"kernels": list(_pair(filter_size)),
               "strides": list(_pair(stride)), "paddings": list(pads)},
    )
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", input=input, param_attr=param_attr, act=act)
    dtype = helper.input_dtype()
    filter_shape = [future_context_size + 1, input.shape[-1]]
    filter_param = helper.create_parameter(helper.param_attr, shape=filter_shape, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="row_conv",
                     inputs={"X": [input], "Filter": [filter_param]},
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def lod_reset(x, y=None, target_lod=None):
    helper = LayerHelper("lod_reset", input=x)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    inputs = {"X": [x]}
    if y is not None:
        inputs["Y"] = [y]
    helper.append_op(type="lod_reset", inputs=inputs, outputs={"Out": [out]},
                     attrs={"target_lod": target_lod or []})
    return out
