"""py_reader: async host input pipeline
(reference: python/paddle/fluid/layers/io.py:485 py_reader over
operators/reader/create_py_reader_op.cc + LoDTensorBlockingQueue).

A background thread converts reader batches into ready feed dicts and
pushes them into a bounded queue; `exe.run(feed=None)` pops the next batch.
Double-buffering (the reference's separate decorator) is subsumed by JAX's
async dispatch — the host thread stays ahead of the device by `capacity`
batches.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..core.framework import default_main_program, unique_name
from ..core.lod import create_lod_tensor
from ..core.proto import EOFException, convert_dtype, dtype_to_numpy

__all__ = ["py_reader", "read_file", "double_buffer", "EOFException"]


class PyReader:
    """Runtime half of a py_reader variable."""

    def __init__(self, names, shapes, dtypes, lod_levels, capacity):
        self._names = list(names)
        self._shapes = [list(s) for s in shapes]
        self._np_dtypes = [dtype_to_numpy(convert_dtype(d)) for d in dtypes]
        self._lod_levels = list(lod_levels)
        self._capacity = capacity
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._creator: Optional[Callable] = None
        self._tensor_provider = False
        self._end = object()
        self._stop_event: Optional[threading.Event] = None

    # -- decoration (reference: py_reader decorate_* methods) ---------------
    def decorate_paddle_reader(self, reader_creator: Callable):
        """reader yields per-sample tuples batched by paddle.batch."""
        self._creator = reader_creator
        self._tensor_provider = False

    def decorate_tensor_provider(self, provider: Callable):
        """provider yields ready per-slot arrays (one list per batch)."""
        self._creator = provider
        self._tensor_provider = True

    decorate_sample_list_generator = decorate_paddle_reader
    decorate_batch_generator = decorate_tensor_provider

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._creator is None:
            raise RuntimeError(
                "py_reader has no data source; call decorate_paddle_reader first"
            )
        self._queue = queue.Queue(maxsize=self._capacity)
        self._stop_event = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, args=(self._queue, self._stop_event),
            daemon=True,
        )
        self._thread.start()

    def reset(self):
        """Stop the worker and drop the queue so the next start() begins a
        fresh pass.  Signals the thread and drains its queue so a mid-pass
        reset doesn't leave a worker blocked on the abandoned bounded queue,
        silently consuming samples from a shared/stateful reader."""
        thread, q, stop = self._thread, self._queue, self._stop_event
        self._queue = None
        self._thread = None
        self._stop_event = None
        if stop is not None:
            stop.set()
        if thread is not None and thread.is_alive():
            # unblock a worker stuck in q.put(...) on the full queue; bound
            # the wait — a creator blocked inside next() (e.g. a network
            # source) can't observe the stop event until it yields, and
            # reset() must not hang on it (the daemon thread exits at its
            # next yield without pushing the item)
            deadline = time.monotonic() + 2.0
            while thread.is_alive() and time.monotonic() < deadline:
                try:
                    q.get_nowait()
                except queue.Empty:
                    thread.join(timeout=0.05)

    def _convert_batch(self, batch) -> dict:
        from ..data_feeder import dense_batch, lod_batch

        if self._tensor_provider:
            return dict(zip(self._names, batch))
        out = {}
        slots = list(zip(*batch))  # per-slot sample lists
        for name, shape, np_dtype, lod, slot in zip(
            self._names, self._shapes, self._np_dtypes, self._lod_levels, slots
        ):
            if lod > 0:
                out[name] = lod_batch(slot, np_dtype)
            else:
                out[name] = dense_batch(slot, shape, np_dtype)
        return out

    def _worker(self, q, stop):
        try:
            for batch in self._creator():
                if stop.is_set():
                    return
                item = self._convert_batch(batch)
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            q.put(self._end)
        except BaseException as e:  # surface reader errors to the consumer
            if not stop.is_set():
                q.put(e)

    def _next_batch(self) -> dict:
        if self._queue is None:
            raise RuntimeError("py_reader not started; call reader.start()")
        item = self._queue.get()
        if item is self._end:
            raise EOFException("py_reader pass finished; call reader.reset()")
        if isinstance(item, BaseException):
            raise item
        return item


def py_reader(
    capacity: int,
    shapes: Sequence[Sequence[int]],
    dtypes: Sequence,
    lod_levels: Optional[Sequence[int]] = None,
    name: Optional[str] = None,
    use_double_buffer: bool = True,
):
    """Create an async reader (reference: layers/io.py:485).  Returns a
    reader handle; call read_file(reader) for the data Variables."""
    lod_levels = list(lod_levels or [0] * len(shapes))
    program = default_main_program()
    block = program.global_block()

    data_names = [unique_name(f"{name or 'py_reader'}_slot{i}")
                  for i in range(len(shapes))]
    data_vars = []
    for dname, shape, dtype, lod in zip(data_names, shapes, dtypes, lod_levels):
        v = block.create_var(
            name=dname, shape=list(shape), dtype=dtype, lod_level=lod,
            stop_gradient=True,
        )
        data_vars.append(v)

    reader = PyReader(data_names, shapes, dtypes, lod_levels, capacity)
    reader._data_vars = data_vars
    reader.name = name or unique_name("py_reader")
    if not hasattr(program, "_py_readers"):
        program._py_readers = []
    program._py_readers.append(reader)
    return reader


def read_file(reader) -> List:
    """Data Variables of a py_reader (reference: layers/io.py read_file)."""
    return list(reader._data_vars)


def double_buffer(reader, place=None, name=None):
    """reference: layers/io.py double_buffer.  JAX's async dispatch already
    overlaps host feed with device compute, so this is the identity."""
    return reader
