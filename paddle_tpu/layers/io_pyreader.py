"""py_reader: async host input pipeline
(reference: python/paddle/fluid/layers/io.py:485 py_reader over
operators/reader/create_py_reader_op.cc + LoDTensorBlockingQueue).

A background thread converts reader batches into ready feed dicts and
pushes them into a bounded queue; `exe.run(feed=None)` pops the next batch.
Double-buffering (the reference's separate decorator) is subsumed by JAX's
async dispatch — the host thread stays ahead of the device by `capacity`
batches.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..core.framework import default_main_program, unique_name
from ..core.lod import create_lod_tensor
from ..core.proto import EOFException, convert_dtype, dtype_to_numpy

__all__ = ["py_reader", "read_file", "double_buffer", "EOFException",
           "Preprocessor"]


class PyReader:
    """Runtime half of a py_reader variable."""

    def __init__(self, names, shapes, dtypes, lod_levels, capacity):
        self._names = list(names)
        self._shapes = [list(s) for s in shapes]
        self._np_dtypes = [dtype_to_numpy(convert_dtype(d)) for d in dtypes]
        self._lod_levels = list(lod_levels)
        self._capacity = capacity
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._creator: Optional[Callable] = None
        self._tensor_provider = False
        self._end = object()
        self._stop_event: Optional[threading.Event] = None

    # -- decoration (reference: py_reader decorate_* methods) ---------------
    def decorate_paddle_reader(self, reader_creator: Callable):
        """reader yields per-sample tuples batched by paddle.batch."""
        self._creator = reader_creator
        self._tensor_provider = False

    def decorate_tensor_provider(self, provider: Callable):
        """provider yields ready per-slot arrays (one list per batch)."""
        self._creator = provider
        self._tensor_provider = True

    decorate_sample_list_generator = decorate_paddle_reader
    decorate_batch_generator = decorate_tensor_provider

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._creator is None:
            raise RuntimeError(
                "py_reader has no data source; call decorate_paddle_reader first"
            )
        self._queue = queue.Queue(maxsize=self._capacity)
        self._stop_event = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, args=(self._queue, self._stop_event),
            daemon=True,
        )
        self._thread.start()

    def reset(self):
        """Stop the worker and drop the queue so the next start() begins a
        fresh pass.  Signals the thread and drains its queue so a mid-pass
        reset doesn't leave a worker blocked on the abandoned bounded queue,
        silently consuming samples from a shared/stateful reader."""
        thread, q, stop = self._thread, self._queue, self._stop_event
        self._queue = None
        self._thread = None
        self._stop_event = None
        if stop is not None:
            stop.set()
        if thread is not None and thread.is_alive():
            # unblock a worker stuck in q.put(...) on the full queue; bound
            # the wait — a creator blocked inside next() (e.g. a network
            # source) can't observe the stop event until it yields, and
            # reset() must not hang on it (the daemon thread exits at its
            # next yield without pushing the item)
            deadline = time.monotonic() + 2.0
            while thread.is_alive() and time.monotonic() < deadline:
                try:
                    q.get_nowait()
                except queue.Empty:
                    thread.join(timeout=0.05)

    def _convert_batch(self, batch) -> dict:
        from ..data_feeder import dense_batch, lod_batch

        if self._tensor_provider:
            return dict(zip(self._names, batch))
        out = {}
        slots = list(zip(*batch))  # per-slot sample lists
        for name, shape, np_dtype, lod, slot in zip(
            self._names, self._shapes, self._np_dtypes, self._lod_levels, slots
        ):
            if lod > 0:
                out[name] = lod_batch(slot, np_dtype)
            else:
                out[name] = dense_batch(slot, shape, np_dtype)
        return out

    def _worker(self, q, stop):
        try:
            for batch in self._creator():
                if stop.is_set():
                    return
                item = self._convert_batch(batch)
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            q.put(self._end)
        except BaseException as e:  # surface reader errors to the consumer
            if not stop.is_set():
                q.put(e)

    def _next_batch(self) -> dict:
        if self._queue is None:
            raise RuntimeError("py_reader not started; call reader.start()")
        item = self._queue.get()
        if item is self._end:
            raise EOFException("py_reader pass finished; call reader.reset()")
        if isinstance(item, BaseException):
            raise item
        return item


def py_reader(
    capacity: int,
    shapes: Sequence[Sequence[int]],
    dtypes: Sequence,
    lod_levels: Optional[Sequence[int]] = None,
    name: Optional[str] = None,
    use_double_buffer: bool = True,
):
    """Create an async reader (reference: layers/io.py:485).  Returns a
    reader handle; call read_file(reader) for the data Variables."""
    lod_levels = list(lod_levels or [0] * len(shapes))
    program = default_main_program()
    block = program.global_block()

    data_names = [unique_name(f"{name or 'py_reader'}_slot{i}")
                  for i in range(len(shapes))]
    data_vars = []
    for dname, shape, dtype, lod in zip(data_names, shapes, dtypes, lod_levels):
        v = block.create_var(
            name=dname, shape=list(shape), dtype=dtype, lod_level=lod,
            stop_gradient=True,
        )
        data_vars.append(v)

    reader = PyReader(data_names, shapes, dtypes, lod_levels, capacity)
    reader._data_vars = data_vars
    reader.name = name or unique_name("py_reader")
    if not hasattr(program, "_py_readers"):
        program._py_readers = []
    program._py_readers.append(reader)
    return reader


def read_file(reader) -> List:
    """Data Variables of a py_reader (reference: layers/io.py read_file)."""
    return list(reader._data_vars)


def double_buffer(reader, place=None, name=None):
    """reference: layers/io.py double_buffer.  JAX's async dispatch already
    overlaps host feed with device compute, so this is the identity."""
    return reader


class _PreprocessedReader(PyReader):
    """A PyReader decorated with a compiled per-batch transform
    (reference: operators/reader/create_custom_reader_op.cc CustomReader —
    its ReadNextImpl runs the sub-block through a CPU executor per batch;
    here the sub-block is jitted once and applied in the worker thread,
    overlapping with device compute)."""

    def __init__(self, underlying, names, shapes, dtypes, lod_levels,
                 transform):
        super().__init__(names, shapes, dtypes, lod_levels,
                         underlying._capacity)
        self._underlying = underlying
        self._transform = transform

    def start(self):
        # late-bind the data source: the user decorates the UNDERLYING
        # reader, possibly after the Preprocessor was built
        self._creator = self._underlying._creator
        self._tensor_provider = self._underlying._tensor_provider
        super().start()

    def decorate_paddle_reader(self, reader_creator):
        self._underlying.decorate_paddle_reader(reader_creator)

    def decorate_tensor_provider(self, provider):
        self._underlying.decorate_tensor_provider(provider)

    decorate_sample_list_generator = decorate_paddle_reader
    decorate_batch_generator = decorate_tensor_provider

    def _convert_batch(self, batch) -> dict:
        # batch the SOURCE slots with the underlying reader's metadata,
        # then run the compiled sub-block
        src = self._underlying._convert_batch(batch)
        return self._transform(src)


class Preprocessor:
    """In-pipeline data preprocessing block (reference: layers/io.py:1080
    Preprocessor over operators/reader/create_custom_reader_op.cc).

        preprocessor = fluid.layers.Preprocessor(reader=reader)
        with preprocessor.block():
            img, lbl = preprocessor.inputs()
            preprocessor.outputs(img / 2, lbl + 1)
        out_vars = fluid.layers.read_file(preprocessor())

    The reference interprets the sub-block per batch on a CPU executor
    inside the decorated reader; here the sub-block lowers ONCE to a
    jitted XLA fn the reader worker applies to every batch — identical
    dataflow, compiled execution."""

    BEFORE_SUB_BLOCK = 0
    IN_SUB_BLOCK = 1
    AFTER_SUB_BLOCK = 2

    def __init__(self, reader, name=None):
        self.underlying_reader = reader
        self.main_prog = default_main_program()
        self.sub_block = None
        self.source_var_names = None
        self.sink_var_names = None
        self._name = name
        self._map_fn = None  # legacy plain-python-reader mapping mode
        self.status = Preprocessor.BEFORE_SUB_BLOCK

    def _is_completed(self):
        return (self.sub_block is not None and self.source_var_names
                and self.sink_var_names)

    def block(self, fn=None):
        # legacy convenience: @p.block over a plain python reader maps
        # samples host-side (no program sub-block involved)
        if fn is not None and callable(fn):
            self._map_fn = fn
            return fn
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            self.status = Preprocessor.IN_SUB_BLOCK
            self.sub_block = self.main_prog._create_block()
            try:
                yield
            finally:
                # roll back even when the body raises — otherwise every
                # later layer call lands in the orphaned sub-block
                self.main_prog._rollback()
                self.status = Preprocessor.AFTER_SUB_BLOCK
            if not self._is_completed():
                raise RuntimeError(
                    "The definition of preprocessor is incomplete! Set "
                    "input and output variables via inputs()/outputs() "
                    "inside the sub-block.")

        return _ctx()

    def inputs(self):
        if self.status != Preprocessor.IN_SUB_BLOCK:
            raise RuntimeError(
                "Preprocessor.inputs() can only be invoked inside the "
                "sub-block.")
        u = self.underlying_reader
        self.source_var_names = [
            unique_name("preprocessor_source") for _ in u._names
        ]
        block = self.main_prog.current_block()
        src_vars = []
        for vname, shape, np_dtype, lod in zip(
            self.source_var_names, u._shapes, u._np_dtypes, u._lod_levels
        ):
            src_vars.append(block.create_var(
                name=vname, shape=list(shape), dtype=np.dtype(np_dtype).name,
                lod_level=lod, stop_gradient=True,
            ))
        return src_vars

    def outputs(self, *outs):
        if self.status != Preprocessor.IN_SUB_BLOCK:
            raise RuntimeError(
                "Preprocessor.outputs() can only be invoked inside the "
                "sub-block.")
        self.sink_var_names = [v.name for v in outs]
        self._sink_vars = list(outs)

    def __call__(self):
        if self._map_fn is not None:
            map_fn, rd = self._map_fn, self.underlying_reader

            def _mapped():
                for sample in rd():
                    out = map_fn(*sample)
                    yield out if isinstance(out, tuple) else (out,)

            return _mapped()
        if not self._is_completed():
            raise RuntimeError(
                "Preprocessor not complete: define the sub-block first.")
        from ..core.compiler import CompiledBlock

        compiled = CompiledBlock(
            self.main_prog, self.sub_block.idx,
            feed_names=self.source_var_names,
            fetch_names=self.sink_var_names,
            state_names=[], donate_states=False,
        )
        # the underlying reader batches under ITS slot names; the
        # sub-block's source vars correspond positionally
        slot_names = list(self.underlying_reader._names)
        seed_box = [0]

        def transform(src: dict) -> dict:
            import jax

            key = jax.random.PRNGKey(seed_box[0])
            seed_box[0] += 1
            vals = tuple(src[n] for n in slot_names)
            fetches, _, _ = compiled(vals, (), key)
            return dict(zip(out_names, fetches))

        u = self.underlying_reader
        out_names = [unique_name(f"{self._name or 'custom_reader'}_slot{i}")
                     for i in range(len(self._sink_vars))]
        block = self.main_prog.current_block()
        out_vars = []
        shapes, dtypes, lods = [], [], []
        for oname, sv in zip(out_names, self._sink_vars):
            out_vars.append(block.create_var(
                name=oname, shape=list(sv.shape), dtype=sv.dtype,
                lod_level=sv.lod_level, stop_gradient=True,
            ))
            shapes.append(list(sv.shape))
            dtypes.append(sv.dtype)
            lods.append(sv.lod_level)

        new_reader = _PreprocessedReader(
            u, out_names, shapes, dtypes, lods, transform)
        new_reader._data_vars = out_vars
        new_reader.name = self._name or unique_name("create_custom_reader")
        if not hasattr(self.main_prog, "_py_readers"):
            self.main_prog._py_readers = []
        # the decorated reader SUBSUMES the underlying one (reference
        # DecoratedReader semantics): only the outer reader feeds the
        # program
        if u in self.main_prog._py_readers:
            self.main_prog._py_readers.remove(u)
        self.main_prog._py_readers.append(new_reader)
        return new_reader
