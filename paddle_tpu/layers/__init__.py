"""fluid.layers equivalent: the public layer-function namespace."""

from . import (  # noqa: F401
    control_flow,
    detection,
    io,
    learning_rate_scheduler,
    nn,
    ops,
    sequence_nn,
    tensor,
)
from .control_flow import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .io import *  # noqa: F401,F403
from .learning_rate_scheduler import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .sequence_nn import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403

from ..core.framework import Variable
from ..layer_helper import LayerHelper


def mean(x, name=None):
    """Mean over all elements -> scalar [1] (reference: operators/mean_op)."""
    helper = LayerHelper("mean", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def elementwise_binary_dispatch(x, other, op_type):
    """Back Variable.__add__/__mul__/...: scalar operands use scale ops,
    Variable operands use elementwise ops."""
    if isinstance(other, Variable):
        return nn._elementwise(op_type, x, other)
    val = float(other)
    if op_type == "elementwise_add":
        return tensor.scale(x, scale=1.0, bias=val)
    if op_type == "elementwise_sub":
        return tensor.scale(x, scale=1.0, bias=-val)
    if op_type == "elementwise_mul":
        return tensor.scale(x, scale=val)
    if op_type == "elementwise_div":
        return tensor.scale(x, scale=1.0 / val)
    if op_type == "elementwise_pow":
        return nn.pow(x, factor=val)
    raise NotImplementedError(op_type)
