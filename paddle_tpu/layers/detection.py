"""Detection layers (reference: python/paddle/fluid/layers/detection.py)."""

from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper
from . import nn, tensor

__all__ = [
    "prior_box",
    "density_prior_box",
    "anchor_generator",
    "iou_similarity",
    "box_coder",
    "bipartite_match",
    "target_assign",
    "multiclass_nms",
    "detection_output",
    "ssd_loss",
    "roi_pool",
    "roi_align",
    "yolov3_loss",
    "box_clip",
    "grid_sampler",
    "affine_grid",
    "affine_channel",
    "generate_proposals",
    "rpn_target_assign",
    "generate_proposal_labels",
    "psroi_pool",
    "roi_perspective_transform",
    "polygon_box_transform",
    "detection_map",
    "multi_box_head",
]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", input=input, name=name)
    dtype = input.dtype
    boxes = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={
            "min_sizes": [float(m) for m in min_sizes],
            "max_sizes": [float(m) for m in (max_sizes or [])],
            "aspect_ratios": [float(a) for a in aspect_ratios],
            "variances": [float(v) for v in variance],
            "min_max_aspect_ratios_order": bool(min_max_aspect_ratios_order),
            "flip": flip, "clip": clip,
            "step_w": float(steps[0]), "step_h": float(steps[1]),
            "offset": offset,
        },
    )
    return boxes, var


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5, name=None):
    helper = LayerHelper("density_prior_box", input=input, name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={
            "densities": [int(d) for d in (densities or [1])],
            "fixed_sizes": [float(s) for s in (fixed_sizes or [])],
            "fixed_ratios": [float(r) for r in (fixed_ratios or [1.0])],
            "variances": [float(v) for v in variance],
            "clip": clip,
            "step_w": float(steps[0]), "step_h": float(steps[1]),
            "offset": offset,
        },
    )
    return boxes, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", input=input, name=name)
    anchors = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="anchor_generator",
        inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [var]},
        attrs={
            "anchor_sizes": [float(s) for s in (anchor_sizes or [64., 128., 256., 512.])],
            "aspect_ratios": [float(r) for r in (aspect_ratios or [0.5, 1.0, 2.0])],
            "variances": [float(v) for v in variance],
            "stride": [float(s) for s in (stride or [16.0, 16.0])],
            "offset": offset,
        },
    )
    return anchors, var


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="iou_similarity", inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
    )
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None,
              axis=0):
    helper = LayerHelper("box_coder", input=prior_box, name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None and hasattr(prior_box_var, "name"):
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="box_coder", inputs=inputs, outputs={"OutputBox": [out]},
        attrs={"code_type": code_type, "box_normalized": box_normalized,
               "axis": axis},
    )
    return out


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    helper = LayerHelper("bipartite_match", input=dist_matrix, name=name)
    match_indices = helper.create_variable_for_type_inference("int32")
    match_distance = helper.create_variable_for_type_inference(
        dist_matrix.dtype
    )
    helper.append_op(
        type="bipartite_match",
        inputs={"DistMat": [dist_matrix]},
        outputs={
            "ColToRowMatchIndices": [match_indices],
            "ColToRowMatchDist": [match_distance],
        },
        attrs={
            "match_type": match_type if match_type is not None else "bipartite",
            "dist_threshold": (
                dist_threshold if dist_threshold is not None else 0.5
            ),
        },
    )
    return match_indices, match_distance


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    helper = LayerHelper("target_assign", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="target_assign",
        inputs={"X": [input], "MatchIndices": [matched_indices]},
        outputs={"Out": [out], "OutWeight": [out_weight]},
        attrs={"mismatch_value": mismatch_value if mismatch_value is not None else 0},
    )
    return out, out_weight


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    helper = LayerHelper("multiclass_nms", input=bboxes, name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={
            "score_threshold": score_threshold,
            "nms_top_k": nms_top_k,
            "keep_top_k": keep_top_k,
            "nms_threshold": nms_threshold,
            "nms_eta": nms_eta,
            "background_label": background_label,
            "normalized": normalized,
        },
    )
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode SSD predictions then NMS
    (reference: layers/detection.py detection_output)."""
    decoded = box_coder(
        prior_box, prior_box_var, loc, code_type="decode_center_size"
    )
    return multiclass_nms(
        decoded, scores, score_threshold, nms_top_k, keep_top_k,
        nms_threshold=nms_threshold, nms_eta=nms_eta,
        background_label=background_label,
    )


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True,
             sample_size=None):
    """SSD matching + localisation/confidence loss
    (reference: layers/detection.py ssd_loss): per-prediction matching,
    box_coder-encoded localisation targets, and max_negative hard mining
    keeping neg_pos_ratio * num_pos negatives by confidence loss."""
    if mining_type != "max_negative":
        raise ValueError("only mining_type='max_negative' is supported")
    iou = iou_similarity(gt_box, prior_box)
    matched_indices, matched_dist = bipartite_match(
        iou, match_type, overlap_threshold
    )
    # per-prior matched gt boxes, encoded as regression offsets (axis=1:
    # row-aligned against each prior)
    matched_boxes, loc_w = target_assign(gt_box, matched_indices)
    loc_targets = box_coder(
        prior_box, prior_box_var, tensor.cast(matched_boxes, location.dtype),
        code_type="encode_center_size", axis=1,
    )
    lbl_targets, cls_w = target_assign(gt_label, matched_indices,
                                       mismatch_value=background_label)

    conf_loss_all = nn.softmax_with_cross_entropy(
        confidence, tensor.cast(lbl_targets, "int64")
    )  # [N, P, 1]
    helper = LayerHelper("mine_hard_examples")
    neg_mask = helper.create_variable_for_type_inference("float32")
    updated = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="mine_hard_examples",
        inputs={"ClsLoss": [conf_loss_all], "MatchIndices": [matched_indices],
                "MatchDist": [matched_dist]},
        outputs={"NegMask": [neg_mask], "UpdatedMatchIndices": [updated]},
        attrs={
            "neg_pos_ratio": float(neg_pos_ratio),
            "neg_dist_threshold": float(neg_overlap),
            "mining_type": mining_type,
            "sample_size": int(sample_size) if sample_size else 0,
        },
    )

    loc_loss = tensor.reduce_sum(
        nn.smooth_l1(
            location, loc_targets, inside_weight=loc_w, outside_weight=None
        )
    )
    conf_w = nn.elementwise_add(cls_w, neg_mask)
    conf_loss = tensor.reduce_sum(nn.elementwise_mul(conf_loss_all, conf_w))
    total = nn.elementwise_add(
        tensor.scale(loc_loss, scale=loc_loss_weight),
        tensor.scale(conf_loss, scale=conf_loss_weight),
    )
    if normalize:
        num_pos = tensor.reduce_sum(loc_w)
        total = nn.elementwise_div(
            total, tensor.scale(num_pos, scale=1.0, bias=1e-6)
        )
    return total


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0):
    helper = LayerHelper("roi_pool", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    argmax = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="roi_pool",
        inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out], "Argmax": [argmax]},
        attrs={
            "pooled_height": pooled_height,
            "pooled_width": pooled_width,
            "spatial_scale": spatial_scale,
        },
    )
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None):
    helper = LayerHelper("roi_align", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="roi_align",
        inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={
            "pooled_height": pooled_height,
            "pooled_width": pooled_width,
            "spatial_scale": spatial_scale,
            "sampling_ratio": sampling_ratio,
        },
    )
    return out


def yolov3_loss(x, gtbox, gtlabel, anchors, class_num, ignore_thresh,
                downsample_ratio=32, name=None):
    helper = LayerHelper("yolov3_loss", input=x, name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="yolov3_loss",
        inputs={"X": [x], "GTBox": [gtbox], "GTLabel": [gtlabel]},
        outputs={"Loss": [loss]},
        attrs={
            "anchors": [int(a) for a in anchors],
            "class_num": class_num,
            "ignore_thresh": ignore_thresh,
            "downsample_ratio": downsample_ratio,
        },
    )
    return loss


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="box_clip",
        inputs={"Input": [input], "ImInfo": [im_info]},
        outputs={"Output": [out]},
    )
    return out


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="grid_sampler", inputs={"X": [x], "Grid": [grid]},
        outputs={"Output": [out]},
    )
    return out


def affine_grid(theta, out_shape=None, name=None):
    helper = LayerHelper("affine_grid", input=theta, name=name)
    out = helper.create_variable_for_type_inference(theta.dtype)
    inputs = {"Theta": [theta]}
    attrs = {}
    if hasattr(out_shape, "name"):
        inputs["OutputShape"] = [out_shape]
    else:
        attrs["output_shape"] = [int(v) for v in (out_shape or [])]
    helper.append_op(
        type="affine_grid", inputs=inputs, outputs={"Output": [out]},
        attrs=attrs,
    )
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    helper = LayerHelper("affine_channel", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="affine_channel",
        inputs={"X": [x], "Scale": [scale], "Bias": [bias]},
        outputs={"Out": [out]},
        attrs={"data_layout": data_layout},
    )
    return out


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """RPN proposal generation (reference: layers/detection.py
    generate_proposals over detection/generate_proposals_op.cc).  Returns
    (rpn_rois, rpn_roi_probs), padded [N, post_nms_top_n, .] LoD values."""
    helper = LayerHelper("generate_proposals", input=scores, name=name)
    rpn_rois = helper.create_variable_for_type_inference(bbox_deltas.dtype)
    rpn_roi_probs = helper.create_variable_for_type_inference(scores.dtype)
    helper.append_op(
        type="generate_proposals",
        inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [variances]},
        outputs={"RpnRois": [rpn_rois], "RpnRoiProbs": [rpn_roi_probs]},
        attrs={"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
               "nms_thresh": nms_thresh, "min_size": min_size, "eta": eta},
    )
    rpn_rois.stop_gradient = True
    rpn_roi_probs.stop_gradient = True
    return rpn_rois, rpn_roi_probs


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """RPN training targets (reference: layers/detection.py
    rpn_target_assign).  Returns (predicted_cls_logits, predicted_bbox_pred,
    target_label, target_bbox, bbox_inside_weight); static
    rpn_batch_size_per_im rows per image, fg shortfalls zero-weighted."""
    from .tensor import gather as _gather, reshape as _reshape

    helper = LayerHelper("rpn_target_assign", input=anchor_box)
    loc_index = helper.create_variable_for_type_inference("int32")
    score_index = helper.create_variable_for_type_inference("int32")
    target_label = helper.create_variable_for_type_inference("int32")
    target_bbox = helper.create_variable_for_type_inference(anchor_box.dtype)
    bbox_inside_weight = helper.create_variable_for_type_inference(
        anchor_box.dtype)
    helper.append_op(
        type="rpn_target_assign",
        inputs={"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
                "IsCrowd": [is_crowd], "ImInfo": [im_info]},
        outputs={"LocationIndex": [loc_index], "ScoreIndex": [score_index],
                 "TargetLabel": [target_label], "TargetBBox": [target_bbox],
                 "BBoxInsideWeight": [bbox_inside_weight]},
        attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
               "rpn_straddle_thresh": rpn_straddle_thresh,
               "rpn_positive_overlap": rpn_positive_overlap,
               "rpn_negative_overlap": rpn_negative_overlap,
               "rpn_fg_fraction": rpn_fg_fraction,
               "use_random": use_random},
    )
    for v in (loc_index, score_index, target_label, target_bbox,
              bbox_inside_weight):
        v.stop_gradient = True
    cls_flat = _reshape(cls_logits, shape=(-1, 1))
    bbox_flat = _reshape(bbox_pred, shape=(-1, 4))
    predicted_cls_logits = _gather(cls_flat, score_index)
    predicted_bbox_pred = _gather(bbox_flat, loc_index)
    return (predicted_cls_logits, predicted_bbox_pred, target_label,
            target_bbox, bbox_inside_weight)


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True):
    """Fast-RCNN RoI sampling (reference: layers/detection.py
    generate_proposal_labels)."""
    helper = LayerHelper("generate_proposal_labels", input=rpn_rois)
    rois = helper.create_variable_for_type_inference(rpn_rois.dtype)
    labels_int32 = helper.create_variable_for_type_inference("int32")
    bbox_targets = helper.create_variable_for_type_inference(rpn_rois.dtype)
    bbox_inside_weights = helper.create_variable_for_type_inference(
        rpn_rois.dtype)
    bbox_outside_weights = helper.create_variable_for_type_inference(
        rpn_rois.dtype)
    helper.append_op(
        type="generate_proposal_labels",
        inputs={"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
                "IsCrowd": [is_crowd], "GtBoxes": [gt_boxes],
                "ImInfo": [im_info]},
        outputs={"Rois": [rois], "LabelsInt32": [labels_int32],
                 "BboxTargets": [bbox_targets],
                 "BboxInsideWeights": [bbox_inside_weights],
                 "BboxOutsideWeights": [bbox_outside_weights]},
        attrs={"batch_size_per_im": batch_size_per_im,
               "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
               "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
               "bbox_reg_weights": list(bbox_reg_weights),
               "class_nums": class_nums or 81, "use_random": use_random},
    )
    return (rois, labels_int32, bbox_targets, bbox_inside_weights,
            bbox_outside_weights)


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None):
    """Position-sensitive RoI pooling (reference: layers/nn.py psroi_pool
    over operators/psroi_pool_op.cc)."""
    helper = LayerHelper("psroi_pool", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="psroi_pool",
        inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"output_channels": output_channels,
               "spatial_scale": spatial_scale,
               "pooled_height": pooled_height,
               "pooled_width": pooled_width},
    )
    return out


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              name=None):
    """Perspective-warp quad RoIs (reference: layers/detection.py
    roi_perspective_transform)."""
    helper = LayerHelper("roi_perspective_transform", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="roi_perspective_transform",
        inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"transformed_height": transformed_height,
               "transformed_width": transformed_width,
               "spatial_scale": spatial_scale},
    )
    return out


def polygon_box_transform(input, name=None):
    """EAST geometry map transform (reference: layers/detection.py
    polygon_box_transform)."""
    helper = LayerHelper("polygon_box_transform", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="polygon_box_transform",
        inputs={"Input": [input]},
        outputs={"Output": [out]},
    )
    return out


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  ap_version="integral", name=None):
    """Batch mAP (reference: layers/detection.py detection_map over
    operators/detection_map_op.cc; streaming accumulation lives in the
    host-side evaluator here)."""
    helper = LayerHelper("detection_map", input=detect_res, name=name)
    m = helper.create_variable_for_type_inference("float32")
    accum_pos = helper.create_variable_for_type_inference("int32")
    accum_tp = helper.create_variable_for_type_inference("float32")
    accum_fp = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="detection_map",
        inputs={"DetectRes": [detect_res], "Label": [label]},
        outputs={"MAP": [m], "AccumPosCount": [accum_pos],
                 "AccumTruePos": [accum_tp], "AccumFalsePos": [accum_fp]},
        attrs={"overlap_threshold": overlap_threshold,
               "evaluate_difficult": evaluate_difficult,
               "ap_type": ap_version, "class_num": class_num,
               "background_label": background_label},
    )
    return m


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD multi-scale detection head (reference: layers/detection.py
    multi_box_head): per feature map, prior boxes plus 3x3/1x1 conv heads
    for box regression and class confidences; outputs concatenated
    (mbox_locs [N, P, 4], mbox_confs [N, P, C], boxes [P, 4], vars [P, 4])."""
    from . import nn as _nn
    from .tensor import concat as _concat, reshape as _reshape

    n_layer = len(inputs)
    if min_sizes is None:
        # reference ratio schedule: evenly spaced between min/max ratio
        min_sizes = []
        max_sizes = []
        if n_layer > 1:
            # reference schedule: ratios step evenly from min to max; with
            # only 2 layers the single interval spans the whole range
            step = (
                int((max_ratio - min_ratio) / (n_layer - 2))
                if n_layer > 2 else (max_ratio - min_ratio)
            )
            min_sizes = [base_size * 0.1]
            max_sizes = [base_size * 0.2]
            for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
                min_sizes.append(base_size * ratio / 100.0)
                max_sizes.append(base_size * (ratio + step) / 100.0)
            min_sizes = min_sizes[:n_layer]
            max_sizes = max_sizes[:n_layer]
        else:
            min_sizes = [base_size * 0.2]
            max_sizes = [base_size * 0.5]

    from .tensor import transpose as _transpose

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, inp in enumerate(inputs):
        min_s = min_sizes[i]
        max_s = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if isinstance(
            aspect_ratios[i], (list, tuple)) else [aspect_ratios[i]]
        sw = steps[i] if steps else (step_w[i] if step_w else 0.0)
        sh = steps[i] if steps else (step_h[i] if step_h else 0.0)
        min_list = list(min_s) if isinstance(min_s, (list, tuple)) else [min_s]
        max_list = (
            (list(max_s) if isinstance(max_s, (list, tuple)) else [max_s])
            if max_s is not None else []
        )
        box, var = prior_box(
            inp, image, min_sizes=min_list,
            max_sizes=max_list or None,
            aspect_ratios=ar, variance=list(variance), flip=flip,
            clip=clip, steps=[sw, sh], offset=offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order,
        )
        # priors per cell: the kernel's own expansion (shared helper, so
        # the conv-head channel count can never drift from the kernel)
        from ..ops.detection_ops import expand_aspect_ratios

        num_priors = (
            len(min_list) * len(expand_aspect_ratios(ar, flip))
            + len(max_list)
        )

        loc = _nn.conv2d(inp, num_priors * 4, kernel_size, padding=pad,
                         stride=stride)
        conf = _nn.conv2d(inp, num_priors * num_classes, kernel_size,
                          padding=pad, stride=stride)
        # [N, C', H, W] -> [N, H*W*priors, 4 or num_classes]
        loc = _reshape(_transpose(loc, perm=[0, 2, 3, 1]),
                       shape=(0, -1, 4))
        conf = _reshape(_transpose(conf, perm=[0, 2, 3, 1]),
                        shape=(0, -1, num_classes))
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(_reshape(box, shape=(-1, 4)))
        vars_all.append(_reshape(var, shape=(-1, 4)))

    mbox_locs = _concat(locs, axis=1)
    mbox_confs = _concat(confs, axis=1)
    boxes = _concat(boxes_all, axis=0)
    variances = _concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, boxes, variances
