"""IO layers (reference: python/paddle/fluid/layers/io.py — data, py_reader,
double_buffer...).  `data` declares a feed slot; reader layers live in
paddle_tpu.reader and are wired here in later form."""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..core.framework import Variable, default_main_program
from ..core.proto import VarType

from .io_pyreader import EOFException, double_buffer, py_reader, read_file  # noqa: F401

__all__ = ["data", "py_reader", "read_file", "double_buffer", "EOFException", "shuffle", "batch", "create_py_reader_by_data"]


def data(
    name: str,
    shape: Sequence[int],
    append_batch_size: bool = True,
    dtype="float32",
    lod_level: int = 0,
    type: VarType = VarType.LOD_TENSOR,
    stop_gradient: bool = True,
) -> Variable:
    """Declare an input variable (reference: layers/io.py data).  With
    append_batch_size a leading -1 batch dim is added, as in the reference."""
    helper_block = default_main_program().current_block()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper_block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        type=type,
        stop_gradient=stop_gradient,
    )


def shuffle(reader, buffer_size):
    """reference: layers/io.py shuffle — in this framework readers are
    python callables, so this delegates to the reader-decorator stack."""
    from ..reader import shuffle as _shuffle

    return _shuffle(reader, buffer_size)


def batch(reader, batch_size):
    """reference: layers/io.py batch (see shuffle)."""
    from ..reader import batch as _batch

    return _batch(reader, batch_size)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """py_reader bound to existing data vars (reference: layers/io.py
    create_py_reader_by_data) — same queue-fed reader as py_reader with
    shapes/dtypes taken from feed_list."""
    shapes = [list(v.shape) for v in feed_list]
    dtypes = [v.dtype for v in feed_list]
    lod_levels = [getattr(v, "lod_level", 0) or 0 for v in feed_list]
    return py_reader(
        capacity=capacity, shapes=shapes, dtypes=dtypes,
        lod_levels=lod_levels, name=name,
        use_double_buffer=use_double_buffer,
    )
