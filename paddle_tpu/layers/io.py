"""IO layers (reference: python/paddle/fluid/layers/io.py — data, py_reader,
double_buffer...).  `data` declares a feed slot; reader layers live in
paddle_tpu.reader and are wired here in later form."""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..core.framework import Variable, default_main_program
from ..core.proto import VarType

from .io_pyreader import EOFException, double_buffer, py_reader, read_file  # noqa: F401

__all__ = ["data", "py_reader", "read_file", "double_buffer", "EOFException"]


def data(
    name: str,
    shape: Sequence[int],
    append_batch_size: bool = True,
    dtype="float32",
    lod_level: int = 0,
    type: VarType = VarType.LOD_TENSOR,
    stop_gradient: bool = True,
) -> Variable:
    """Declare an input variable (reference: layers/io.py data).  With
    append_batch_size a leading -1 batch dim is added, as in the reference."""
    helper_block = default_main_program().current_block()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper_block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        type=type,
        stop_gradient=stop_gradient,
    )
