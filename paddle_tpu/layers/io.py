"""IO layers (reference: python/paddle/fluid/layers/io.py — data, py_reader,
double_buffer...).  `data` declares a feed slot; reader layers live in
paddle_tpu.reader and are wired here in later form."""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..core.framework import Variable, default_main_program
from ..core.proto import VarType

from .io_pyreader import (  # noqa: F401
    EOFException,
    Preprocessor,
    double_buffer,
    py_reader,
    read_file,
)

__all__ = ["data", "py_reader", "read_file", "double_buffer", "EOFException", "shuffle", "batch", "create_py_reader_by_data", "random_data_generator", "open_files", "Preprocessor"]


def data(
    name: str,
    shape: Sequence[int],
    append_batch_size: bool = True,
    dtype="float32",
    lod_level: int = 0,
    type: VarType = VarType.LOD_TENSOR,
    stop_gradient: bool = True,
) -> Variable:
    """Declare an input variable (reference: layers/io.py data).  With
    append_batch_size a leading -1 batch dim is added, as in the reference."""
    helper_block = default_main_program().current_block()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper_block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        type=type,
        stop_gradient=stop_gradient,
    )


def shuffle(reader, buffer_size):
    """reference: layers/io.py shuffle — in this framework readers are
    python callables, so this delegates to the reader-decorator stack."""
    from ..reader import shuffle as _shuffle

    return _shuffle(reader, buffer_size)


def batch(reader, batch_size):
    """reference: layers/io.py batch (see shuffle)."""
    from ..reader import batch as _batch

    return _batch(reader, batch_size)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """py_reader bound to existing data vars (reference: layers/io.py
    create_py_reader_by_data) — same queue-fed reader as py_reader with
    shapes/dtypes taken from feed_list."""
    shapes = [list(v.shape) for v in feed_list]
    dtypes = [v.dtype for v in feed_list]
    lod_levels = [getattr(v, "lod_level", 0) or 0 for v in feed_list]
    return py_reader(
        capacity=capacity, shapes=shapes, dtypes=dtypes,
        lod_levels=lod_levels, name=name,
        use_double_buffer=use_double_buffer,
    )


def random_data_generator(low, high, shapes, lod_levels=None, for_parallel=True):
    """Random data source for reader benchmarks (reference: layers/io.py
    random_data_generator over create_random_data_generator_op).  Returns a
    python reader yielding uniform tensors of the given shapes."""
    import numpy as np

    fixed = [[abs(d) for d in s] for s in shapes]

    def reader():
        rng = np.random.RandomState(0)
        while True:
            yield tuple(
                rng.uniform(low, high, s).astype("float32") for s in fixed
            )

    return reader


def open_files(filenames, shapes, lod_levels, dtypes, thread_num=1,
               buffer_size=None, pass_num=1, is_test=None):
    """Read recordio files as a python reader (reference: layers/io.py
    open_files over open_files_op; files are the recordio format written by
    paddle_tpu.recordio, records are np.savez archives of the slots).
    '<slot>__lodK__' sidecar entries (convert_reader_to_recordio_file's
    LoD encoding) fold back into LoDValues."""
    import io as _io
    import re as _re

    import numpy as np

    from ..core.lod import LoDValue
    from ..recordio import RecordIOScanner

    n_slots = len(shapes)
    _lod_key = _re.compile(r"^(.*)__lod(\d+)__$")

    def _fold(z, fn):
        # archive order == np.savez argument order; sorting would
        # scramble slots by key name
        base_keys = [k for k in z.files if not _lod_key.match(k)]
        if len(base_keys) != n_slots:
            raise ValueError(
                f"record in {fn!r} has {len(base_keys)} arrays but "
                f"{n_slots} slots declared"
            )
        out = []
        for k in base_keys:
            levels = sorted(
                (int(m.group(2)), z[name])
                for name in z.files
                for m in (_lod_key.match(name),)
                if m is not None and m.group(1) == k
            )
            if levels:
                lens = [v for _, v in levels]
                out.append(LoDValue(z[k], lens[0], tuple(lens[1:])))
            else:
                out.append(z[k])
        return tuple(out)

    def reader():
        for _ in range(pass_num):
            for fn in filenames:
                with RecordIOScanner(fn) as sc:
                    for rec in sc:
                        with np.load(_io.BytesIO(rec),
                                     allow_pickle=False) as z:
                            yield _fold(z, fn)

    return reader


