"""Auto-generated activation layer fns (reference:
python/paddle/fluid/layers/ops.py via layer_function_generator — one layer fn
per registered activation op)."""

from __future__ import annotations

from ..layer_helper import LayerHelper

_ACTIVATIONS = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "softshrink",
    "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin", "round",
    "reciprocal", "log", "square", "softplus", "softsign", "hard_shrink",
    "gelu", "erf", "sign", "tan", "acos", "asin", "atan", "sinh", "cosh",
]

__all__ = list(_ACTIVATIONS) + ["uniform_random", "gaussian_random",
                                "gaussian_random_batch_size_like",
                                "uniform_random_batch_size_like"]


def _make_act(op_type):
    def layer_fn(x, name=None):
        helper = LayerHelper(op_type, input=x, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]})
        return out

    layer_fn.__name__ = op_type
    layer_fn.__doc__ = f"{op_type} activation (op-generated layer fn)"
    return layer_fn


for _op in _ACTIVATIONS:
    globals()[_op] = _make_act(_op)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    from ..core.proto import convert_dtype

    helper = LayerHelper("uniform_random")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="uniform_random", outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": int(dtype), "min": float(min),
               "max": float(max), "seed": seed},
    )
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    from ..core.proto import convert_dtype

    helper = LayerHelper("gaussian_random")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gaussian_random", outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": int(dtype), "mean": float(mean),
               "std": float(std), "seed": seed},
    )
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32", min=-1.0,
                                   max=1.0, seed=0, input_dim_idx=0,
                                   output_dim_idx=0):
    from ..core.proto import convert_dtype

    helper = LayerHelper("uniform_random_batch_size_like", input=input)
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="uniform_random_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": int(dtype), "min": float(min),
               "max": float(max), "seed": seed,
               "input_dim_idx": input_dim_idx, "output_dim_idx": output_dim_idx},
    )
    return out


def gaussian_random_batch_size_like(input, shape, mean=0.0, std=1.0, seed=0,
                                    dtype="float32", input_dim_idx=0,
                                    output_dim_idx=0):
    # lowers through uniform's batch-size-like path with gaussian sampling
    from ..core.proto import convert_dtype

    helper = LayerHelper("gaussian_random_batch_size_like", input=input)
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gaussian_random_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": int(dtype), "mean": float(mean),
               "std": float(std), "seed": seed,
               "input_dim_idx": input_dim_idx, "output_dim_idx": output_dim_idx},
    )
    return out
