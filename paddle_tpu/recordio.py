"""RecordIO reader/writer (reference: recordio/ C++ lib +
python/paddle/fluid/recordio_writer.py).

Backed by the native C++ library (paddle_tpu/native/recordio.cc, built on
first use); a pure-Python codec of the same on-disk format serves as
fallback and as the cross-check in tests.

NOTE: the on-disk format is a NEW design (magic 0x0CDB0CDB, header
num_records:u32 + payload_len:u64) and is NOT wire-compatible with the
reference's recordio files (kMagicNumber 0x01020304, per-record
checksum/compressor/len framing).  Files written by the upstream framework
cannot be read here; convert via the upstream reader if needed.
"""

from __future__ import annotations

import ctypes
import struct
import zlib
from typing import Iterator, List, Optional

from . import native

__all__ = ["RecordIOWriter", "RecordIOScanner", "write_recordio",
           "read_recordio", "convert_reader_to_recordio_file",
           "convert_reader_to_recordio_files"]

_MAGIC = 0x0CDB0CDB


def _lib():
    lib = native.load("recordio")
    if lib is not None and not getattr(lib, "_rio_ready", False):
        lib.rio_writer_open.restype = ctypes.c_void_p
        lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.rio_writer_write.restype = ctypes.c_int
        lib.rio_writer_write.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64
        ]
        lib.rio_writer_close.restype = ctypes.c_int
        lib.rio_writer_close.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_open.restype = ctypes.c_void_p
        lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.rio_scanner_next.restype = ctypes.c_int64
        lib.rio_scanner_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
        ]
        lib.rio_scanner_close.restype = None
        lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
        lib._rio_ready = True
    return lib


class RecordIOWriter:
    """reference: recordio/writer.h Writer + recordio_writer.py."""

    def __init__(self, path: str, max_chunk_records: int = 1000,
                 force_python: bool = False):
        self._path = path
        self._max = max_chunk_records
        self._lib = None if force_python else _lib()
        if self._lib is not None:
            self._h = self._lib.rio_writer_open(
                path.encode(), max_chunk_records
            )
            if not self._h:
                raise IOError(f"cannot open {path} for writing")
        else:
            self._f = open(path, "wb")
            self._payload: List[bytes] = []

    def write(self, record: bytes) -> None:
        if isinstance(record, str):
            record = record.encode()
        if self._lib is not None:
            rc = self._lib.rio_writer_write(self._h, record, len(record))
            if rc != 0:
                raise IOError("recordio write failed")
            return
        self._payload.append(record)
        if len(self._payload) >= self._max:
            self._flush_py()

    def _flush_py(self):
        if not self._payload:
            return
        payload = b"".join(
            struct.pack("<I", len(r)) + r for r in self._payload
        )
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._f.write(
            struct.pack("<IIIIQ", _MAGIC, crc, 0, len(self._payload),
                        len(payload))
        )
        self._f.write(payload)
        self._payload = []

    def close(self) -> None:
        if self._lib is not None:
            if self._lib.rio_writer_close(self._h) != 0:
                raise IOError("recordio close failed")
            self._h = None
        else:
            self._flush_py()
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecordIOScanner:
    """reference: recordio/scanner.h Scanner."""

    def __init__(self, path: str, force_python: bool = False):
        self._path = path
        self._lib = None if force_python else _lib()
        if self._lib is not None:
            self._h = self._lib.rio_scanner_open(path.encode())
            if not self._h:
                raise IOError(f"cannot open {path}")
        else:
            self._f = open(path, "rb")
            self._pending: List[bytes] = []

    def __iter__(self) -> Iterator[bytes]:
        if self._lib is not None:
            out = ctypes.POINTER(ctypes.c_uint8)()
            while True:
                n = self._lib.rio_scanner_next(self._h, ctypes.byref(out))
                if n == -1:
                    return
                if n == -2:
                    raise IOError(f"corrupt recordio chunk in {self._path}")
                yield ctypes.string_at(out, n)
        else:
            while True:
                if self._pending:
                    yield self._pending.pop(0)
                    continue
                head = self._f.read(24)
                if len(head) < 24:
                    return
                magic, crc, _comp, num, plen = struct.unpack("<IIIIQ", head)
                if magic != _MAGIC:
                    raise IOError("bad recordio magic")
                payload = self._f.read(plen)
                if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    raise IOError("recordio crc mismatch")
                pos = 0
                for _ in range(num):
                    (rlen,) = struct.unpack_from("<I", payload, pos)
                    pos += 4
                    self._pending.append(payload[pos : pos + rlen])
                    pos += rlen

    def close(self) -> None:
        if self._lib is not None and self._h:
            self._lib.rio_scanner_close(self._h)
            self._h = None
        elif self._lib is None:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_recordio(path: str, records, max_chunk_records: int = 1000) -> int:
    n = 0
    with RecordIOWriter(path, max_chunk_records) as w:
        for r in records:
            w.write(r)
            n += 1
    return n


def read_recordio(path: str) -> Iterator[bytes]:
    with RecordIOScanner(path) as s:
        for r in s:
            yield r


def convert_reader_to_recordio_file(
    filename,
    reader_creator,
    feeder,
    compressor=None,
    max_num_records=1000,
    feed_order=None,
) -> int:
    """Serialize a python reader's batches into one recordio file
    (reference: recordio_writer.py convert_reader_to_recordio_file).  Each
    record is the np.savez archive layers.open_files reads back; a LoD
    slot appends one '<slot>__lodK__' entry per nesting level (lengths,
    then each sub_lengths grid), which open_files folds back into a
    LoDValue.  `compressor` is accepted for signature parity (this format
    stores raw npz; the chunk layer owns framing)."""
    import io as _io

    import numpy as np

    from .core.lod import LoDValue

    if feed_order is None:
        feed_order = feeder.feed_names
    counter = 0
    with RecordIOWriter(filename, max_chunk_records=max_num_records) as w:
        for batch in reader_creator():
            res = feeder.feed(batch)
            arrs = {}
            for name in feed_order:
                v = res[name]
                if isinstance(v, LoDValue):
                    arrs[name] = np.asarray(v.data)
                    for k, lens in enumerate(
                        (v.lengths,) + tuple(v.sub_lengths)
                    ):
                        arrs[f"{name}__lod{k}__"] = np.asarray(lens)
                else:
                    arrs[name] = np.asarray(v)
            buf = _io.BytesIO()
            np.savez(buf, **arrs)
            w.write(buf.getvalue())
            counter += 1
    return counter


def convert_reader_to_recordio_files(
    filename,
    batch_per_file,
    reader_creator,
    feeder,
    compressor=None,
    max_num_records=1000,
    feed_order=None,
) -> int:
    """Split the stream across many recordio files, batch_per_file records
    each (reference: recordio_writer.py convert_reader_to_recordio_files;
    file names get -00000 style suffixes)."""
    import itertools

    total = 0
    it = iter(reader_creator())
    for idx in itertools.count():
        chunk = list(itertools.islice(it, batch_per_file))
        if not chunk:
            break
        total += convert_reader_to_recordio_file(
            f"{filename}-{idx:05d}", lambda c=chunk: iter(c), feeder,
            compressor, max_num_records, feed_order,
        )
    return total
