"""Parameter attributes (reference: python/paddle/fluid/param_attr.py).

TPU-native addition: `sharding` — a per-dim tuple of mesh-axis names (or
None) consumed by ParallelExecutor/pjit for tensor-parallel layouts.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

__all__ = ["ParamAttr", "WeightNormParamAttr"]


class ParamAttr:
    def __init__(
        self,
        name: Optional[str] = None,
        initializer=None,
        learning_rate: float = 1.0,
        regularizer=None,
        trainable: bool = True,
        gradient_clip=None,
        do_model_average: bool = False,
        sharding: Optional[Sequence[Any]] = None,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average
        self.sharding = list(sharding) if sharding is not None else None

    @staticmethod
    def _to_attr(arg) -> Optional["ParamAttr"]:
        """Normalize user input: None/False/str/Initializer/ParamAttr
        (reference: param_attr.py ParamAttr._to_attr)."""
        if arg is None or arg is True:
            # reference: param_attr.py:148 — bool True selects the default
            # ParamAttr, False disables the parameter (e.g. bias_attr=False)
            return ParamAttr()
        if arg is False:
            return None
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, (list, tuple)) and all(isinstance(a, ParamAttr) for a in arg):
            return list(arg)
        # assume an Initializer instance
        return ParamAttr(initializer=arg)

    def _to_kwargs(self, with_initializer: bool = False):
        kwargs = {
            "name": self.name,
            "optimize_attr": {"learning_rate": self.learning_rate},
            "regularizer": self.regularizer,
            "trainable": self.trainable,
            "gradient_clip_attr": self.gradient_clip,
            "do_model_average": self.do_model_average,
        }
        if with_initializer:
            kwargs["initializer"] = self.initializer
        return kwargs


class WeightNormParamAttr(ParamAttr):
    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
