"""Evaluator classes (reference: python/paddle/fluid/evaluator.py —
deprecated in the reference in favor of fluid.metrics, kept for API parity).

Each evaluator owns in-graph state vars updated per batch plus an eval()
that reads them back from the scope."""

from __future__ import annotations

import numpy as np

from . import layers
from .core.framework import default_main_program, unique_name
from .core.scope import global_scope
from .initializer import ConstantInitializer

__all__ = ["Evaluator", "ChunkEvaluator", "EditDistance"]


class Evaluator:
    """reference: evaluator.py Evaluator."""

    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper_name = unique_name(name)

    def reset(self, executor, reset_program=None):
        scope = getattr(executor, "scope", None) or global_scope()
        for var in self.states:
            v = scope.find_var(var.name)
            if v is not None:
                scope.set_var(var.name, np.zeros_like(np.asarray(v)))

    def eval(self, executor, eval_program=None):
        raise NotImplementedError

    def _create_state(self, suffix, dtype, shape):
        from .core.framework import default_startup_program

        name = unique_name(f"{self.helper_name}_{suffix}")
        main = default_main_program().global_block()
        state = main.create_var(
            name=name, shape=list(shape), dtype=dtype, persistable=True
        )
        startup = default_startup_program().global_block()
        sv = startup.create_var(
            name=name, shape=list(shape), dtype=dtype, persistable=True
        )
        ConstantInitializer(0.0)(sv, startup)
        self.states.append(state)
        return state


class ChunkEvaluator(Evaluator):
    """Accumulating chunk F1 (reference: evaluator.py ChunkEvaluator)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super().__init__("chunk_eval")
        (precision, recall, f1, num_infer, num_label, num_correct) = (
            layers.chunk_eval(
                input=input, label=label, chunk_scheme=chunk_scheme,
                num_chunk_types=num_chunk_types,
                excluded_chunk_types=excluded_chunk_types,
            )
        )
        self.num_infer_chunks = self._create_state("num_infer", "int64", [1])
        self.num_label_chunks = self._create_state("num_label", "int64", [1])
        self.num_correct_chunks = self._create_state("num_correct", "int64", [1])
        layers.sums([self.num_infer_chunks, num_infer],
                    out=self.num_infer_chunks)
        layers.sums([self.num_label_chunks, num_label],
                    out=self.num_label_chunks)
        layers.sums([self.num_correct_chunks, num_correct],
                    out=self.num_correct_chunks)
        self.metrics = [precision, recall, f1]

    def eval(self, executor, eval_program=None):
        scope = getattr(executor, "scope", None) or global_scope()
        ni = float(np.ravel(np.asarray(scope.find_var(self.num_infer_chunks.name)))[0])
        nl = float(np.ravel(np.asarray(scope.find_var(self.num_label_chunks.name)))[0])
        nc = float(np.ravel(np.asarray(scope.find_var(self.num_correct_chunks.name)))[0])
        precision = nc / ni if ni else 0.0
        recall = nc / nl if nl else 0.0
        f1 = 2 * precision * recall / (precision + recall) if nc else 0.0
        return np.array(precision), np.array(recall), np.array(f1)


class EditDistance(Evaluator):
    """Accumulating edit distance (reference: evaluator.py EditDistance)."""

    def __init__(self, input, label, ignored_tokens=None):
        super().__init__("edit_distance")
        distances, seq_num = layers.edit_distance(
            input=input, label=label, ignored_tokens=ignored_tokens
        )
        self.total_distance = self._create_state("total", "float32", [1])
        self.seq_num = self._create_state("seq_num", "int64", [1])
        batch_total = layers.reduce_sum(distances)
        layers.sums([self.total_distance, batch_total],
                    out=self.total_distance)
        layers.sums([self.seq_num, seq_num], out=self.seq_num)
        self.metrics = [distances]

    def eval(self, executor, eval_program=None):
        scope = getattr(executor, "scope", None) or global_scope()
        total = float(np.ravel(np.asarray(scope.find_var(self.total_distance.name)))[0])
        n = float(np.ravel(np.asarray(scope.find_var(self.seq_num.name)))[0])
        return np.array(total / n if n else 0.0)
