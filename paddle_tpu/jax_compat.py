"""jax version compatibility shims.

The repo targets the modern jax surface (`jax.shard_map` as a top-level
function, `jax.export` eagerly importable).  Older jaxlib builds (<= 0.4.x)
ship the same functionality under different paths; alias them onto the
`jax` module at import time so every call site (and the tests, which import
`from jax import shard_map` directly) sees one spelling.
"""

from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):
    import functools
    import inspect

    from jax.experimental.shard_map import shard_map as _shard_map

    if "check_vma" in inspect.signature(_shard_map).parameters:
        jax.shard_map = _shard_map
    else:
        # the modern kwarg is check_vma; 0.4.x spells it check_rep
        @functools.wraps(_shard_map)
        def _shard_map_compat(*args, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _shard_map(*args, **kwargs)

        jax.shard_map = _shard_map_compat

# jax.export is a lazily-importable submodule on 0.4.x: attribute access on
# the bare `jax` module fails until someone imports it.  Do that once here
# so `jax.export.export(...)` works everywhere.
import jax.export  # noqa: E402,F401

# Lowered.as_text(debug_info=True) (location metadata in the printed
# module) postdates 0.4.x; emulate it via the MLIR module's own printer.
import inspect as _inspect  # noqa: E402

_low_as_text = jax.stages.Lowered.as_text
if "debug_info" not in _inspect.signature(_low_as_text).parameters:
    def _as_text_compat(self, dialect=None, *, debug_info=False):
        if debug_info:
            try:
                mod = self.compiler_ir(dialect or "stablehlo")
                return mod.operation.get_asm(enable_debug_info=True)
            except Exception:
                pass
        return _low_as_text(self, dialect)

    jax.stages.Lowered.as_text = _as_text_compat
