"""Gradient clipping (reference: python/paddle/fluid/clip.py —
ErrorClipByValue, GradientClipByValue, GradientClipByNorm,
GradientClipByGlobalNorm, set_gradient_clip)."""

from __future__ import annotations

from typing import List, Tuple

from .layer_helper import LayerHelper

__all__ = [
    "ErrorClipByValue",
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "set_gradient_clip",
    "append_gradient_clip_ops",
    "error_clip_callback",
]


class BaseErrorClipAttr:
    def append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        if min is None:
            min = -max
        self.max, self.min = float(max), float(min)

    def append_clip_op(self, block, grad_name):
        block.append_op(
            type="clip", inputs={"X": [grad_name]}, outputs={"Out": [grad_name]},
            attrs={"min": self.min, "max": self.max},
        )


def error_clip_callback(block, context):
    pass


class BaseGradientClipAttr:
    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        if min is None:
            min = -max
        self.max, self.min = float(max), float(min)

    def _create_operators(self, param, grad):
        helper = LayerHelper("clip_grad")
        new_grad = helper.create_variable_for_type_inference(grad.dtype)
        grad.block.append_op(
            type="clip", inputs={"X": [grad]}, outputs={"Out": [new_grad]},
            attrs={"min": self.min, "max": self.max},
        )
        return param, new_grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _create_operators(self, param, grad):
        helper = LayerHelper("clip_grad_by_norm")
        new_grad = helper.create_variable_for_type_inference(grad.dtype)
        grad.block.append_op(
            type="clip_by_norm", inputs={"X": [grad]}, outputs={"Out": [new_grad]},
            attrs={"max_norm": self.clip_norm},
        )
        return param, new_grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Scale all grads by clip_norm/max(global_norm, clip_norm)
    (reference: clip.py GradientClipByGlobalNorm builds the same op chain)."""

    def __init__(self, clip_norm, group_name: str = "default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
        context[self.group_name].append(_square_sum(grad))
        self.context = context

    def _create_operators(self, param, grad):
        from . import layers

        group_scale_name = self.group_name + "_scale"
        if group_scale_name not in self.context:
            group_norm_sq = layers.sums(self.context[self.group_name])
            group_norm = layers.ops.sqrt(group_norm_sq)
            clip_var = layers.fill_constant(shape=[1], dtype=group_norm.dtype,
                                            value=self.clip_norm)
            scale_var = layers.elementwise_div(
                x=clip_var,
                y=layers.elementwise_max(x=clip_var, y=group_norm),
            )
            self.context[group_scale_name] = scale_var
        new_grad = layers.elementwise_mul(x=grad, y=self.context[group_scale_name])
        return param, new_grad


def _square_sum(grad):
    from . import layers

    sq = layers.ops.square(grad)
    return layers.reduce_sum(sq)


def set_gradient_clip(clip, param_list=None, program=None):
    """Attach the clip strategy to parameters (reference: clip.py:304
    set_gradient_clip — param_list None means every parameter currently in
    the program; the attr lives ON the parameters, never in module state,
    so one program's clip cannot leak into the next)."""
    if not isinstance(clip, BaseGradientClipAttr):
        raise TypeError(
            "'clip' should be an instance of BaseGradientClipAttr's "
            "derived class")
    from .core.framework import default_main_program

    if program is None:
        program = default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    for p in param_list:
        if isinstance(p, str):
            p = program.global_block().var(p)
        p.gradient_clip_attr = clip


def append_gradient_clip_ops(param_grads: List[Tuple]):
    context = {}
    clips = []
    for p, g in param_grads:
        if g is None:
            clips.append((p, g))
            continue
        clip_attr = getattr(p, "gradient_clip_attr", None)
        if clip_attr is None:
            clips.append((p, g))
            continue
        clip_attr._process_context(context, p, g)
        clips.append((p, g, clip_attr))
    res = []
    for item in clips:
        if len(item) == 2:
            res.append(item)
        else:
            p, g, clip_attr = item
            res.append(clip_attr._create_operators(p, g))
    return res
