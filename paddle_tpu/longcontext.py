"""Long-context sequence/context parallelism: ring attention over a mesh
axis.

The reference (2018-era) bounds sequence length by one device's memory
(SURVEY.md section 5 "long-context: absent").  This module exceeds reference
capability: sequences shard over the mesh's `sp` axis, each device holds
S/P tokens, and attention runs as a P-step ring — queries stay put while
K/V blocks rotate via lax.ppermute over ICI, merged with the online-softmax
recurrence (Liu et al., Ring Attention; blockwise formulation as in the
scaling-book collective-matmul recipe).  Peak memory per chip is
O(S/P * D), and the K/V transfer overlaps the current block's compute under
XLA's async collectives.

Use inside shard_map (sequence_parallel_attention wraps this), composing
with data parallelism on other mesh axes.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "sequence_parallel_attention"]

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Attention over a sequence sharded on `axis_name`.

    q/k/v: LOCAL shards [B, H, S_local, D]; must be called under shard_map
    (or pmap) with `axis_name` bound.  Returns the local output shard.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    p = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, H, S, D = q.shape

    q_pos = my * S + jnp.arange(S)  # global positions of local queries

    def step(carry, i):
        acc, m, l, k_cur, v_cur = carry
        # k_cur currently holds the shard that started on device (my - i)
        src = (my - i) % p
        k_pos = src * S + jnp.arange(S)

        def attend(acc, m, l):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur) * scale
            mask = jnp.ones((S, S), dtype=bool)
            if causal:
                mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, NEG_INF)

            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            # guard all-masked rows (the partially-future diagonal block's
            # padded rows under causal)
            m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
            pexp = jnp.exp(s - m_safe)
            pexp = jnp.where(mask, pexp, 0.0)
            corr = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_safe))
            l_new = corr * l + jnp.sum(pexp, axis=-1, keepdims=True)
            acc_new = acc * corr + jnp.einsum(
                "bhqk,bhkd->bhqd", pexp.astype(v_cur.dtype), v_cur
            )
            return acc_new, m_new, l_new

        if causal:
            # an entirely-future K/V shard (src > my: every key position
            # exceeds every local query position) contributes nothing —
            # skip its matmuls instead of computing a fully-masked block.
            # lax.cond keeps this differentiable.  NOTE: with contiguous
            # sequence sharding this halves aggregate FLOPs/energy but
            # NOT wall-clock — the ring is lockstep and device p-1
            # attends at every step, so latency stays gated by the
            # busiest device.  A latency win needs load-balanced
            # (zigzag/striped) sharding; the rotation below still runs
            # every step so the ring stays in sync.
            acc, m, l = jax.lax.cond(
                src > my, lambda a, mm, ll: (a, mm, ll), attend, acc, m, l
            )
        else:
            acc, m, l = attend(acc, m, l)

        # rotate K/V shards around the ring (overlaps with next compute)
        perm = [(j, (j + 1) % p) for j in range(p)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc, m, l, k_nxt, v_nxt), None

    acc0 = jnp.zeros(q.shape, dtype=jnp.float32)
    m0 = jnp.full((B, H, S, 1), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((B, H, S, 1), dtype=jnp.float32)
    (acc, m, l, _, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(p)
    )
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def sequence_parallel_attention(mesh, q, k, v, axis: str = "sp",
                                causal: bool = False,
                                scale: Optional[float] = None,
                                batch_axis: Optional[str] = "dp"):
    """Global-view wrapper: q/k/v [B, H, S, D] with S sharded on `axis`
    (and optionally B on `batch_axis`); runs ring_attention via shard_map."""
    from jax import shard_map

    jmesh = getattr(mesh, "mesh", mesh)  # DeviceMesh or raw jax Mesh
    axis_names = jmesh.axis_names
    b = batch_axis if batch_axis in axis_names else None
    spec = P(b, None, axis, None)

    fn = functools.partial(
        ring_attention, axis_name=axis, causal=causal, scale=scale
    )
    return shard_map(
        fn, mesh=jmesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
