"""Long-context sequence/context parallelism: ring attention over a mesh
axis.

The reference (2018-era) bounds sequence length by one device's memory
(SURVEY.md section 5 "long-context: absent").  This module exceeds reference
capability: sequences shard over the mesh's `sp` axis, each device holds
S/P tokens, and attention runs as a P-step ring — queries stay put while
K/V blocks rotate via lax.ppermute over ICI, merged with the online-softmax
recurrence (Liu et al., Ring Attention; blockwise formulation as in the
scaling-book collective-matmul recipe).  Peak memory per chip is
O(S/P * D), and the K/V transfer overlaps the current block's compute under
XLA's async collectives.

Use inside shard_map (sequence_parallel_attention wraps this), composing
with data parallelism on other mesh axes.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "sequence_parallel_attention",
           "zigzag_permutation", "zigzag_ring_attention",
           "zigzag_sequence_parallel_attention",
           "ulysses_attention", "ulysses_sequence_parallel_attention"]

NEG_INF = -1e30


def _softmax_merge(state, s, vals, mask):
    """One online-softmax merge: fold score block `s` (masked by `mask`)
    and its values into the running (acc, m, l).  Shared by both ring
    variants — the NEG_INF/2 all-masked-row guard is numerically delicate
    and must stay in exactly one place."""
    acc, m, l = state
    s = jnp.where(mask, s, NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    pexp = jnp.where(mask, jnp.exp(s - m_safe), 0.0)
    corr = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_safe))
    l_new = corr * l + jnp.sum(pexp, axis=-1, keepdims=True)
    acc_new = acc * corr + jnp.einsum(
        "bhqk,bhkd->bhqd", pexp.astype(vals.dtype), vals
    )
    return acc_new, m_new, l_new


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Attention over a sequence sharded on `axis_name`.

    q/k/v: LOCAL shards [B, H, S_local, D]; must be called under shard_map
    (or pmap) with `axis_name` bound.  Returns the local output shard.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    p = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, H, S, D = q.shape

    q_pos = my * S + jnp.arange(S)  # global positions of local queries

    def step(carry, i):
        acc, m, l, k_cur, v_cur = carry
        # k_cur currently holds the shard that started on device (my - i)
        src = (my - i) % p
        k_pos = src * S + jnp.arange(S)

        def attend(acc, m, l):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur) * scale
            mask = jnp.ones((S, S), dtype=bool)
            if causal:
                mask = q_pos[:, None] >= k_pos[None, :]
            return _softmax_merge((acc, m, l), s, v_cur, mask)

        if causal:
            # an entirely-future K/V shard (src > my: every key position
            # exceeds every local query position) contributes nothing —
            # skip its matmuls instead of computing a fully-masked block.
            # lax.cond keeps this differentiable.  NOTE: with contiguous
            # sequence sharding this halves aggregate FLOPs/energy but
            # NOT wall-clock — the ring is lockstep and device p-1
            # attends at every step, so latency stays gated by the
            # busiest device.  A latency win needs load-balanced
            # (zigzag/striped) sharding; the rotation below still runs
            # every step so the ring stays in sync.
            acc, m, l = jax.lax.cond(
                src > my, lambda a, mm, ll: (a, mm, ll), attend, acc, m, l
            )
        else:
            acc, m, l = attend(acc, m, l)

        # rotate K/V shards around the ring (overlaps with next compute)
        perm = [(j, (j + 1) % p) for j in range(p)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc, m, l, k_nxt, v_nxt), None

    acc0 = jnp.zeros(q.shape, dtype=jnp.float32)
    m0 = jnp.full((B, H, S, 1), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((B, H, S, 1), dtype=jnp.float32)
    (acc, m, l, _, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(p)
    )
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def sequence_parallel_attention(mesh, q, k, v, axis: str = "sp",
                                causal: bool = False,
                                scale: Optional[float] = None,
                                batch_axis: Optional[str] = "dp"):
    """Global-view wrapper: q/k/v [B, H, S, D] with S sharded on `axis`
    (and optionally B on `batch_axis`); runs ring_attention via shard_map."""
    from jax import shard_map

    jmesh = getattr(mesh, "mesh", mesh)  # DeviceMesh or raw jax Mesh
    axis_names = jmesh.axis_names
    b = batch_axis if batch_axis in axis_names else None
    spec = P(b, None, axis, None)

    fn = functools.partial(
        ring_attention, axis_name=axis, causal=causal, scale=scale
    )
    return shard_map(
        fn, mesh=jmesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


# -- zigzag (load-balanced) causal context parallelism ----------------------
#
# With contiguous sharding, causal ring attention is imbalanced: device 0's
# queries see almost nothing, device P-1's see everything, and since the
# ring is lockstep, latency is gated by the busiest device (the plain
# ring_attention's skip only saves energy).  Zigzag sharding (as used by
# modern context-parallel trainers) splits the sequence into 2P chunks and
# gives device d the PAIR (d, 2P-1-d) — one early and one late chunk — so
# every device owns the same amount of visible causal work, and skipping
# hidden chunk-pairs turns the saved FLOPs into saved wall-clock.

def zigzag_permutation(seq_len: int, p: int):
    """(perm, inv) index arrays: `x[..., perm, :]` lays a [S] sequence out
    so P equal shards each hold chunks (d, 2P-1-d); `inv` undoes it."""
    import numpy as np

    if seq_len % (2 * p):
        raise ValueError(f"seq_len {seq_len} must divide into 2p={2*p} chunks")
    c = seq_len // (2 * p)
    chunks = np.arange(seq_len).reshape(2 * p, c)
    perm = np.concatenate(
        [np.concatenate([chunks[d], chunks[2 * p - 1 - d]]) for d in range(p)]
    )
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq_len)
    return perm, inv


def zigzag_ring_attention(q, k, v, axis_name: str,
                          scale: Optional[float] = None):
    """Causal attention over a ZIGZAG-sharded sequence (call under
    shard_map).  q/k/v: local shards [B, H, 2C, D] holding global chunks
    (my, 2P-1-my).  Per ring step the four local-q-chunk x incoming-k-chunk
    sub-blocks are computed only when visible (full or diagonal), which is
    balanced across devices by construction."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    p = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, H, S2, D = q.shape
    C = S2 // 2

    q_chunks = (q[:, :, :C], q[:, :, C:])
    q_chunk_ids = (my, 2 * p - 1 - my)
    pos = jnp.arange(C)

    def sub_block(state, qi, qc_id, k_half, v_half, kc_id):
        """Merge one C x C sub-block if visible: kc_id < qc_id -> full,
        == -> causal diagonal, > -> hidden (skip)."""
        qq = q_chunks[qi]

        def visible(st):
            s = jnp.einsum("bhqd,bhkd->bhqk", qq, k_half) * scale
            # full block when strictly earlier, diagonal when equal
            mask = (kc_id < qc_id) | (pos[:, None] >= pos[None, :])
            return _softmax_merge(st, s, v_half, mask)

        return jax.lax.cond(kc_id <= qc_id, visible, lambda st: st, state)

    def step(carry, i):
        st0, st1, k_cur, v_cur = carry
        src = (my - i) % p
        k_chunk_ids = (src, 2 * p - 1 - src)
        halves = ((k_cur[:, :, :C], v_cur[:, :, :C]),
                  (k_cur[:, :, C:], v_cur[:, :, C:]))
        for kh, (k_half, v_half) in enumerate(halves):
            st0 = sub_block(st0, 0, q_chunk_ids[0], k_half, v_half,
                            k_chunk_ids[kh])
            st1 = sub_block(st1, 1, q_chunk_ids[1], k_half, v_half,
                            k_chunk_ids[kh])
        perm = [(j, (j + 1) % p) for j in range(p)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (st0, st1, k_nxt, v_nxt), None

    def init():
        shape = (B, H, C, 1)
        return (jnp.zeros((B, H, C, D), jnp.float32),
                jnp.full(shape, NEG_INF, jnp.float32),
                jnp.zeros(shape, jnp.float32))

    (st0, st1, _, _), _ = jax.lax.scan(
        step, (init(), init(), k, v), jnp.arange(p)
    )

    def fin(st):
        acc, _, l = st
        return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

    return jnp.concatenate([fin(st0), fin(st1)], axis=2)


def zigzag_sequence_parallel_attention(mesh, q, k, v, axis: str = "sp",
                                       scale: Optional[float] = None,
                                       batch_axis: Optional[str] = "dp"):
    """Global-view causal attention with zigzag load balancing: permutes
    the sequence into zigzag layout, runs zigzag_ring_attention under
    shard_map over `axis`, and un-permutes the output."""
    from jax import shard_map

    jmesh = getattr(mesh, "mesh", mesh)
    p = jmesh.shape[axis]
    S = q.shape[2]
    perm, inv = zigzag_permutation(S, p)
    axis_names = jmesh.axis_names
    b = batch_axis if batch_axis in axis_names else None
    spec = P(b, None, axis, None)

    fn = functools.partial(zigzag_ring_attention, axis_name=axis, scale=scale)
    out = shard_map(
        fn, mesh=jmesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q[:, :, perm], k[:, :, perm], v[:, :, perm])
    return out[:, :, inv]


# -- all-to-all (Ulysses-style) sequence parallelism -------------------------
#
# The ring moves K/V around the mesh P times; the all-to-all variant moves
# the DATA LAYOUT instead: one all_to_all re-shards q/k/v from
# sequence-sharded [B, H, S/P, D] to head-sharded [B, H/P, S, D], each
# device runs full-sequence attention for its H/P heads BLOCKWISE over keys
# (online softmax, block_k keys at a time), and a second all_to_all restores
# sequence sharding.  Two collectives total (vs P ppermute hops), at the
# cost of requiring H % P == 0 and holding q/k/v/o for the full sequence:
# peak memory O(S * D * H/P + S * block_k * H/P) per chip vs the ring's
# O(S/P * D * H) — the S x S score matrix is never materialized.  Pick per
# workload: many-head models with moderate S favour all-to-all; extreme S
# (where even O(S * D * H/P) activations overflow) favours the ring.

def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None, block_k: int = 1024):
    """All-to-all sequence-parallel attention over `axis_name` (call under
    shard_map).  q/k/v: LOCAL sequence shards [B, H, S_local, D] with the
    GLOBAL head count H divisible by the axis size.  Returns the local
    output shard [B, H, S_local, D].  `block_k` bounds the score-matrix
    working set ([.., S, block_k] per step)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    p = jax.lax.psum(1, axis_name)  # static axis size under shard_map
    if q.shape[1] % p:
        raise ValueError(
            f"ulysses attention needs heads {q.shape[1]} divisible by the "
            f"'{axis_name}' axis size {p}; use ring_attention otherwise")

    def to_heads(x):
        # [B, H, S/P, D] -> [B, H/P, S, D]: split the head axis across the
        # mesh, concatenate the gathered sequence chunks.
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = _blockwise_attention(qh, kh, vh, scale, causal, block_k)
    return to_seq(out).astype(q.dtype)


def _blockwise_attention(q, k, v, scale, causal, block_k):
    """Single-device attention with the online-softmax merge applied over
    key blocks of size `block_k` — O(S * block_k) score working set instead
    of the dense S x S matrix.  Shapes [B, H, S, D] (full sequence)."""
    B, H, S, D = q.shape
    bk = max(1, min(block_k, S))
    nblocks = -(-S // bk)
    pad = nblocks * bk - S
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    q_pos = jnp.arange(S)

    def step(state, i):
        ks = jax.lax.dynamic_slice_in_dim(kp, i * bk, bk, axis=2)
        vs = jax.lax.dynamic_slice_in_dim(vp, i * bk, bk, axis=2)
        k_pos = i * bk + jnp.arange(bk)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, ks).astype(jnp.float32) * scale
        mask = k_pos[None, :] < S  # padded key slots never contribute
        if causal:
            mask = mask & (q_pos[:, None] >= k_pos[None, :])
        else:
            mask = jnp.broadcast_to(mask, (S, bk))
        return _softmax_merge(state, s, vs, mask), None

    acc0 = jnp.zeros(q.shape, dtype=jnp.float32)
    m0 = jnp.full((B, H, S, 1), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((B, H, S, 1), dtype=jnp.float32)
    (acc, _, l), _ = jax.lax.scan(step, (acc0, m0, l0), jnp.arange(nblocks))
    return acc / jnp.maximum(l, 1e-30)


def ulysses_sequence_parallel_attention(mesh, q, k, v, axis: str = "sp",
                                        causal: bool = False,
                                        scale: Optional[float] = None,
                                        batch_axis: Optional[str] = "dp",
                                        block_k: int = 1024):
    """Global-view wrapper: q/k/v [B, H, S, D] with S sharded on `axis`;
    re-shards to heads via all_to_all, computes full attention per head
    group, and restores sequence sharding.  Requires H % mesh[axis] == 0."""
    from jax import shard_map

    jmesh = getattr(mesh, "mesh", mesh)
    p = jmesh.shape[axis]
    if q.shape[1] % p:
        raise ValueError(
            f"ulysses attention needs heads {q.shape[1]} divisible by the "
            f"'{axis}' axis size {p}; use ring_attention otherwise")
    axis_names = jmesh.axis_names
    b = batch_axis if batch_axis in axis_names else None
    spec = P(b, None, axis, None)

    fn = functools.partial(ulysses_attention, axis_name=axis, causal=causal,
                           scale=scale, block_k=block_k)
    return shard_map(
        fn, mesh=jmesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
