"""Timeline export (reference: tools/timeline.py chrome-trace generation).

Round-trip coverage for the profiler/timeline export rebased onto the
observability span writer: the JSON loads, spans nest, durations are
non-negative, Perfetto rows are labeled (thread_name metadata events),
and per-thread tids are stable (main thread pinned to 0)."""

import json
import os
import tempfile
import threading
import time

import pytest

import paddle_tpu as fluid
from paddle_tpu import profiler, timeline


@pytest.fixture(autouse=True)
def _clean_tracer():
    """The observability tracer is process-global; a span another test
    left behind must not leak into the merged export counts."""
    from paddle_tpu import observability as obs

    obs.default_tracer().clear()
    yield
    obs.default_tracer().clear()


def _export(path):
    n = timeline.export_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)
    return n, doc


def _xs(doc):
    return [e for e in doc["traceEvents"] if e["ph"] == "X"]


def test_chrome_trace_export():
    profiler.reset_profiler()
    profiler.start_profiler("All")
    with profiler.record_event("step"):
        with profiler.record_event("forward"):
            time.sleep(0.002)
        with profiler.record_event("backward"):
            time.sleep(0.001)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "trace.json")
        n, doc = _export(path)
        assert n == 3
        xs = _xs(doc)
        names = {e["name"] for e in xs}
        assert names == {"step", "forward", "backward"}
        for e in xs:
            assert e["dur"] > 0
        # nesting: forward is contained within step
        by = {e["name"]: e for e in xs}
        assert by["step"]["ts"] <= by["forward"]["ts"]
        assert (by["forward"]["ts"] + by["forward"]["dur"]
                <= by["step"]["ts"] + by["step"]["dur"] + 1)
    profiler.stop_profiler()
    profiler.reset_profiler()


def test_chrome_trace_thread_names_and_stable_tids():
    """Satellite: thread_name metadata events + stable per-thread tids
    (the old export emitted insertion-order ints with no names, leaving
    Perfetto rows unlabeled)."""
    profiler.reset_profiler()
    profiler.start_profiler("All")

    def worker():
        with profiler.record_event("io"):
            time.sleep(0.002)

    with profiler.record_event("main_work"):
        t = threading.Thread(target=worker, name="reader-0")
        t.start()
        t.join()
    profiler.stop_profiler()
    with tempfile.TemporaryDirectory() as d:
        n, doc = _export(os.path.join(d, "t.json"))
        assert n == 2
        xs = {e["name"]: e for e in _xs(doc)}
        metas = {e["tid"]: e["args"]["name"]
                 for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        # main thread is pinned to tid 0 and both rows are labeled
        assert xs["main_work"]["tid"] == 0
        assert metas[0] == threading.main_thread().name
        assert metas[xs["io"]["tid"]] == "reader-0"
        assert xs["io"]["tid"] != 0
        # stable across exports: the same spans map to the same tids
        n2, doc2 = _export(os.path.join(d, "t2.json"))
        assert {e["name"]: e["tid"] for e in _xs(doc2)} == {
            e["name"]: e["tid"] for e in _xs(doc)}
    profiler.reset_profiler()


def test_chrome_trace_merges_observability_spans():
    """One merged trace per run: profiler record_event spans (cat host)
    and observability spans (cat obs) land in the same file."""
    from paddle_tpu import observability as obs

    profiler.reset_profiler()
    obs.default_tracer().clear()
    fluid.set_flags({"FLAGS_observability": True})
    try:
        profiler.start_profiler("All")
        with profiler.record_event("host_evt"):
            with obs.span("obs_evt"):
                pass
        profiler.stop_profiler()
        with tempfile.TemporaryDirectory() as d:
            n, doc = _export(os.path.join(d, "m.json"))
            assert n == 2
            by = {e["name"]: e for e in _xs(doc)}
            assert by["host_evt"]["cat"] == "host"
            assert by["obs_evt"]["cat"] == "obs"
            # same thread -> same row; obs span nested inside host event
            assert by["obs_evt"]["tid"] == by["host_evt"]["tid"]
            assert by["host_evt"]["ts"] <= by["obs_evt"]["ts"]
        # include_observability=False keeps the profiler-only view
        with tempfile.TemporaryDirectory() as d:
            n = timeline.export_chrome_trace(
                os.path.join(d, "p.json"), include_observability=False)
            assert n == 1
    finally:
        fluid.set_flags({"FLAGS_observability": False})
        obs.default_tracer().clear()
        profiler.reset_profiler()


def test_timeline_class_roundtrip():
    """Timeline(...).generate_chrome_trace_file round-trip: loads as
    JSON, every complete event has non-negative duration."""
    profiler.reset_profiler()
    profiler.start_profiler("All")
    for i in range(3):
        with profiler.record_event(f"evt_{i}"):
            pass
    profiler.stop_profiler()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "tl.json")
        n = timeline.Timeline(None).generate_chrome_trace_file(path)
        assert n == 3
        with open(path) as f:
            doc = json.load(f)
        assert doc["displayTimeUnit"] == "ms"
        for e in _xs(doc):
            assert e["dur"] >= 0 and e["ts"] >= 0
    profiler.reset_profiler()


def test_trace_not_collected_when_profiler_off():
    profiler.reset_profiler()
    with profiler.record_event("untraced"):
        pass
    assert len(profiler._trace) == 0
