"""Timeline export (reference: tools/timeline.py chrome-trace generation)."""

import json
import os
import tempfile
import time

import paddle_tpu as fluid
from paddle_tpu import profiler, timeline


def test_chrome_trace_export():
    profiler.reset_profiler()
    profiler.start_profiler("All")
    with profiler.record_event("step"):
        with profiler.record_event("forward"):
            time.sleep(0.002)
        with profiler.record_event("backward"):
            time.sleep(0.001)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "trace.json")
        n = timeline.export_chrome_trace(path)
        assert n == 3
        with open(path) as f:
            doc = json.load(f)
        names = {e["name"] for e in doc["traceEvents"]}
        assert names == {"step", "forward", "backward"}
        for e in doc["traceEvents"]:
            assert e["ph"] == "X" and e["dur"] > 0
        # nesting: forward is contained within step
        by = {e["name"]: e for e in doc["traceEvents"]}
        assert by["step"]["ts"] <= by["forward"]["ts"]
        assert (by["forward"]["ts"] + by["forward"]["dur"]
                <= by["step"]["ts"] + by["step"]["dur"] + 1)
    profiler.stop_profiler()
    profiler.reset_profiler()


def test_trace_not_collected_when_profiler_off():
    profiler.reset_profiler()
    with profiler.record_event("untraced"):
        pass
    assert len(profiler._trace) == 0
