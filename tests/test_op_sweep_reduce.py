"""Per-op sweep: reductions and ranking ops (reference: test_reduce_op.py,
test_cumsum_op.py, test_top_k_op.py, test_argsort_op.py over
operators/reduce_ops/ REGISTER_REDUCE_OP + cum_op + top_k_op)."""

import numpy as np
import pytest

from op_test import OpTest


def _rand(shape, seed=11, lo=0.5, hi=2.0):
    return np.random.RandomState(seed).uniform(lo, hi, shape).astype("float32")


REDUCE = {
    "reduce_sum": (np.sum, True),
    "reduce_mean": (np.mean, True),
    "reduce_max": (np.max, False),  # subgradient at ties
    "reduce_min": (np.min, False),
    "reduce_prod": (np.prod, True),
}


@pytest.mark.parametrize("op", sorted(REDUCE))
@pytest.mark.parametrize("dim,keep_dim", [([1], False), ([0], True), ([0, 2], False)])
def test_reduce(op, dim, keep_dim):
    ref, do_grad = REDUCE[op]
    x = _rand((2, 3, 4))

    class T(OpTest):
        op_type = op

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"dim": dim, "keep_dim": keep_dim}
    t.outputs = {"Out": ref(x.astype(np.float64), axis=tuple(dim),
                            keepdims=keep_dim).astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    if do_grad:
        t.check_grad(["X"], "Out", max_relative_error=0.01)


@pytest.mark.parametrize("op,ref", [("reduce_all", np.all), ("reduce_any", np.any)])
def test_reduce_bool(op, ref):
    x = np.random.RandomState(1).rand(2, 3, 4) > 0.4

    class T(OpTest):
        op_type = op

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"dim": [1], "keep_dim": False}
    t.outputs = {"Out": ref(x, axis=1)}
    t.check_output()


def test_reduce_all_dims_to_scalar():
    x = _rand((2, 3))

    class T(OpTest):
        op_type = "reduce_sum"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"dim": [], "reduce_all": True}
    t.outputs = {"Out": np.array([x.sum()], dtype="float32")}
    t.check_output(atol=2e-5, rtol=2e-5)


def test_cumsum():
    x = _rand((3, 5), lo=-1, hi=1)

    class T(OpTest):
        op_type = "cumsum"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"axis": 1}
    t.outputs = {"Out": np.cumsum(x.astype(np.float64), axis=1).astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X"], "Out", max_relative_error=0.01)


def test_cumsum_exclusive_reverse():
    x = _rand((3, 5), lo=-1, hi=1, seed=12)
    ref = np.cumsum(x[:, ::-1], axis=1)[:, ::-1] - x  # reverse exclusive

    class T(OpTest):
        op_type = "cumsum"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"axis": 1, "exclusive": True, "reverse": True}
    t.outputs = {"Out": ref.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)


def test_top_k():
    x = _rand((3, 10), lo=-5, hi=5, seed=13)
    k = 4
    idx = np.argsort(-x, axis=1, kind="stable")[:, :k]
    val = np.take_along_axis(x, idx, axis=1)

    class T(OpTest):
        op_type = "top_k"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"k": k}
    t.outputs = {"Out": val, "Indices": idx.astype("int64")}
    t.check_output()


def test_argsort():
    x = _rand((3, 6), lo=-5, hi=5, seed=14)
    idx = np.argsort(x, axis=1, kind="stable")
    val = np.take_along_axis(x, idx, axis=1)

    class T(OpTest):
        op_type = "argsort"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"axis": 1}
    t.outputs = {"Out": val, "Indices": idx.astype("int64")}
    t.check_output()


@pytest.mark.parametrize("op,ref", [("arg_max", np.argmax), ("arg_min", np.argmin)])
def test_arg_extreme(op, ref):
    x = _rand((4, 7), lo=-5, hi=5, seed=15)

    class T(OpTest):
        op_type = op

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"axis": 1}
    t.outputs = {"Out": ref(x, axis=1).astype("int64")}
    t.check_output()


def test_logsumexp_full():
    x = _rand((3, 4), lo=-2, hi=2, seed=16)

    class T(OpTest):
        op_type = "logsumexp"

    t = T()
    t.inputs = {"X": x}
    t.outputs = {"Out": np.array(
        np.log(np.sum(np.exp(x.astype(np.float64)))), dtype="float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
