"""Op correctness via the OpTest harness — numpy references + numeric
gradient checks (reference: ~250 test_*_op.py files; representative set)."""

import numpy as np
import pytest

from op_test import OpTest


class TestMulOp(OpTest):
    op_type = "mul"

    def setup(self):
        rng = np.random.RandomState(0)
        x = rng.uniform(-1, 1, (4, 5)).astype("float32")
        y = rng.uniform(-1, 1, (5, 3)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {}
        self.outputs = {"Out": x @ y}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Y"], "Out")


class TestSoftmaxOp(OpTest):
    op_type = "softmax"

    def setup(self):
        rng = np.random.RandomState(1)
        x = rng.uniform(-1, 1, (3, 7)).astype("float32")
        e = np.exp(x - x.max(axis=-1, keepdims=True))
        self.inputs = {"X": x}
        self.outputs = {"Out": e / e.sum(axis=-1, keepdims=True)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out", max_relative_error=0.01)


class TestElementwiseAddBroadcast(OpTest):
    op_type = "elementwise_add"

    def setup(self):
        rng = np.random.RandomState(2)
        x = rng.rand(2, 3, 4).astype("float32")
        y = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x + y}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Y"], "Out")


class TestLayerNormOp(OpTest):
    op_type = "layer_norm"

    def setup(self):
        rng = np.random.RandomState(3)
        N, D = 3, 8
        x = rng.rand(N, D).astype("float32")
        scale = rng.rand(D).astype("float32")
        bias = rng.rand(D).astype("float32")
        mu = x.mean(axis=1, keepdims=True)
        var = x.var(axis=1, keepdims=True)
        y = (x - mu) / np.sqrt(var + 1e-5) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"epsilon": 1e-5, "begin_norm_axis": 1}
        self.outputs = {
            "Y": y,
            "Mean": mu.reshape(N),
            "Variance": var.reshape(N),
        }

    def test_output(self):
        self.setup()
        self.check_output(atol=1e-4)


class TestTransposeOp(OpTest):
    op_type = "transpose"

    def setup(self):
        rng = np.random.RandomState(4)
        x = rng.rand(2, 3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"axis": [1, 0, 2]}
        self.outputs = {"Out": x.transpose(1, 0, 2)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out")


class TestConv2dOp(OpTest):
    op_type = "conv2d"

    def setup(self):
        rng = np.random.RandomState(5)
        x = rng.rand(1, 2, 5, 5).astype("float32")
        w = rng.rand(3, 2, 3, 3).astype("float32")
        out = np.zeros((1, 3, 3, 3), dtype="float32")
        for o in range(3):
            for i in range(3):
                for j in range(3):
                    out[0, o, i, j] = np.sum(
                        x[0, :, i : i + 3, j : j + 3] * w[o]
                    )
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {
            "strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
            "groups": 1,
        }
        self.outputs = {"Output": out}

    def test_output(self):
        self.setup()
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.setup()
        self.check_grad(
            ["Input", "Filter"], "Output", max_relative_error=0.02,
            numeric_grad_delta=5e-3,
        )


class TestSequencePoolSum(OpTest):
    op_type = "sequence_pool"

    def setup(self):
        rng = np.random.RandomState(6)
        flat = rng.rand(7, 3).astype("float32")
        lengths = [3, 4]
        self.inputs = {"X": (flat, lengths)}
        self.attrs = {"pooltype": "SUM"}
        self.outputs = {
            "Out": np.stack([flat[:3].sum(0), flat[3:].sum(0)])
        }

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out")


class TestSigmoidOp(OpTest):
    op_type = "sigmoid"

    def setup(self):
        rng = np.random.RandomState(7)
        x = rng.uniform(-2, 2, (4, 6)).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": 1.0 / (1.0 + np.exp(-x))}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out", max_relative_error=0.01)


class TestReduceMeanOp(OpTest):
    op_type = "reduce_mean"

    def setup(self):
        rng = np.random.RandomState(8)
        x = rng.rand(3, 4, 5).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dim": [1], "keep_dim": False, "reduce_all": False}
        self.outputs = {"Out": x.mean(axis=1)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out")


class TestConvBnAddActOp(OpTest):
    """conv_bn_add_act: numpy reference for outputs + finite-difference
    gradient check through the fused conv+BN+residual+relu backward
    (the reference's OpTest pattern for conv_fusion-class ops)."""

    op_type = "conv_bn_add_act"

    def setup(self, act="relu"):
        rng = np.random.RandomState(7)
        N, C, H, F, K = 2, 4, 6, 5, 3
        x = rng.uniform(-1, 1, (N, C, H, H)).astype("float32")
        w = (rng.uniform(-1, 1, (F, C, K, K)) * 0.4).astype("float32")
        scale = rng.uniform(0.6, 1.4, (F,)).astype("float32")
        bias = (rng.uniform(-0.2, 0.2, (F,))).astype("float32")
        # nonzero moving stats: an all-zero mean would let a wrong
        # momentum blend of the old mean pass undetected
        mean = rng.uniform(-0.5, 0.5, (F,)).astype("float32")
        var = rng.uniform(0.5, 1.5, (F,)).astype("float32")
        z = rng.uniform(-1, 1, (N, F, H, H)).astype("float32")
        eps, momentum = 1e-5, 0.9

        # numpy reference: NCHW conv (stride 1, pad 1) + batch stats BN
        # + residual + relu
        xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
        out = np.zeros((N, F, H, H), "float32")
        for kh in range(K):
            for kw in range(K):
                patch = xp[:, :, kh:kh + H, kw:kw + H]
                out += np.einsum("nchw,fc->nfhw", patch, w[:, :, kh, kw])
        bm = out.mean(axis=(0, 2, 3))
        bv = out.var(axis=(0, 2, 3))
        inv = 1.0 / np.sqrt(bv + eps)
        y = ((out - bm[None, :, None, None]) * inv[None, :, None, None]
             * scale[None, :, None, None] + bias[None, :, None, None])
        y = y + z
        if act == "relu":
            y = np.maximum(y, 0.0)

        self.inputs = {"X": x, "Filter": w, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var, "Z": z}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1],
                      "epsilon": eps, "momentum": momentum, "act": act}
        self.outputs = {
            "Y": y,
            "MeanOut": momentum * mean + (1 - momentum) * bm,
            "VarianceOut": momentum * var + (1 - momentum) * bv,
            "SavedMean": bm,
            "SavedVariance": inv,
        }

    def test_output(self):
        self.setup()
        self.check_output(atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("impl", ["reference", "pallas"])
    def test_grad(self, impl):
        # the smooth path (no relu kink): finite differences across the
        # activation's corner dominate the error otherwise.  impl=pallas
        # numerically validates the hand-written custom_vjp backward of
        # kernels/conv_epilogue.py (interpret mode on CPU), not just the
        # autodiff'd reference composition
        import paddle_tpu as fluid

        fluid.set_flags({"FLAGS_conv_epilogue": impl})
        try:
            self.setup(act="")
            self.check_grad(["X", "Filter", "Scale", "Bias", "Z"], "Y",
                            max_relative_error=0.02)
        finally:
            fluid.set_flags({"FLAGS_conv_epilogue": "reference"})
