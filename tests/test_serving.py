"""Serving tier (paddle_tpu/serving/): batching engine acceptance,
paged KV-cache decode parity, drain/timeout semantics, serve_bench gate.

Acceptance criteria pinned here (ISSUE 4):
(a) concurrent mixed-shape submit()s == sequential predict(), bit-exact;
(b) a bucket-ladder engine dispatches at most len(buckets) distinct
    batch shapes across 100 mixed-size requests (compile counters);
(c) continuous-batching decode of overlapping sequences through the
    paged KV cache == per-sequence full-recompute decode (fp32 tol),
    and retired sequences' pages return to the free pool;
(d) deadline-expired requests fail with the named timeout error while
    in-flight batches complete during drain.
Plus the decode-shaped ragged-attention contract the KV loop relies on:
flash_attention at Sq=1 with growing k_lengths == _reference_attention
token-for-token.

ISSUE 5 additions (pallas ragged paged attention + batched prefill):
(e) interpret-mode pallas paged decode == the reference gather path
    token-for-token over a multi-step simulated decode with ragged
    lengths, mixed page counts, and >=3 overlapping sequences — and the
    whole continuous-batching loop under paged_impl="interpret" matches
    full_decode;
(f) batched whole-prompt prefill: prefill_step == full_forward's last
    row per sequence (the batched-reference oracle), batched-vs-token
    loops produce token-identical generations, and prefill model-steps
    drop from O(prompt_len) to O(1) per admission group (step counters);
(g) envelope/flag selection: pallas_paged_viable encodes the Mosaic
    tiling envelope, explicit pallas outside it falls back to reference
    (same numbers, no compile bomb), FLAGS_serving_paged_impl validates
    its choices.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, serving
from paddle_tpu.core.framework import unique_name_guard
from paddle_tpu.inference import (
    load_compiled_inference_model,
    save_compiled_inference_model,
)
from paddle_tpu.kernels.flash_attention import (
    _reference_attention,
    flash_attention,
)
from paddle_tpu.kernels.paged_attention import (
    attention_bytes_per_step,
    gather_kv_pages,
    paged_decode_attention,
    pallas_paged_viable,
    resolve_paged_impl,
)
from paddle_tpu.resilience import PreemptionDrain
from paddle_tpu.serving import (
    ContinuousBatchingLoop,
    DecodeConfig,
    DecodeRequest,
    Engine,
    EngineClosedError,
    EngineConfig,
    KVCachePool,
    PagePoolExhausted,
    QueueFullError,
    RequestTimeoutError,
    full_decode,
    full_forward,
    init_decode_params,
    prefill_step,
)


def _export_small_cnn(dirname: str):
    """Conv->bn->pool->fc artifact in private programs/scope (reusable
    across tests regardless of the autouse fresh-program fixture)."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), unique_name_guard():
        img = layers.data("image", [1, 8, 8], dtype="float32")
        c = layers.conv2d(img, num_filters=4, filter_size=3, padding=1)
        b = layers.batch_norm(c, act="relu")
        p = layers.pool2d(b, pool_size=8, pool_type="avg")
        pred = layers.fc(p, size=3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        save_compiled_inference_model(
            dirname, ["image"], [pred], exe, main_program=main, scope=scope)
    return load_compiled_inference_model(dirname)


@pytest.fixture(scope="module")
def cnn_predict(tmp_path_factory):
    return _export_small_cnn(str(tmp_path_factory.mktemp("serving_cnn")))


def _wait_until(pred, timeout=5.0):
    t0 = time.perf_counter()
    while not pred():
        if time.perf_counter() - t0 > timeout:
            raise AssertionError("condition not reached in time")
        time.sleep(0.005)


class _GatedBackend:
    """Backend whose dispatch blocks until released — stages the
    in-flight-during-drain scenarios deterministically."""

    feed_names = ["x"]
    fetch_names = ["y"]
    meta: dict = {}

    def __init__(self):
        self.gate = threading.Event()
        self.calls = 0

    def __call__(self, feed):
        self.calls += 1
        assert self.gate.wait(10.0), "test gate never released"
        return [np.asarray(feed["x"]) * 2.0]


# -- (a) concurrent mixed shapes, bit-identical -------------------------

def test_concurrent_mixed_shapes_bit_identical(cnn_predict):
    eng = Engine.from_artifact(
        cnn_predict,
        config=EngineConfig(buckets=(1, 2, 4, 8), max_wait_s=0.002))
    rng = np.random.RandomState(7)
    feeds = [
        {"image": rng.rand(int(rng.randint(1, 5)), 1, 8, 8).astype(np.float32)}
        for _ in range(24)
    ]
    with ThreadPoolExecutor(max_workers=6) as tp:
        futs = list(tp.map(eng.submit, feeds))
    outs = [f.result(timeout=30) for f in futs]
    eng.close()
    for feed, got in zip(feeds, outs):
        (want,) = cnn_predict(feed)
        assert got[0].shape == want.shape
        np.testing.assert_array_equal(got[0], want)


# -- (b) bucket ladder bounds compiled shapes ---------------------------

def test_bucket_ladder_bounds_compiled_shapes(cnn_predict):
    buckets = (1, 2, 4, 8)
    eng = Engine.from_artifact(
        cnn_predict, config=EngineConfig(buckets=buckets, max_wait_s=0.001))
    rng = np.random.RandomState(3)
    futs = [
        eng.submit({"image": rng.rand(
            int(rng.randint(1, 9)), 1, 8, 8).astype(np.float32)})
        for _ in range(100)
    ]
    for f in futs:
        f.result(timeout=60)
    counters = eng.compile_counters()
    stats = eng.stats()
    eng.close()
    # 100 mixed-size requests, at most one first-seen shape per bucket
    assert counters["miss"] == counters["distinct_shapes"]
    assert counters["distinct_shapes"] <= len(buckets)
    assert counters["hit"] + counters["miss"] == stats["batches"]
    assert stats["rows"] == sum(int(f.result()[0].shape[0]) for f in futs)


def test_static_artifact_collapses_ladder(tmp_path, monkeypatch):
    """A static-batch artifact can only serve its exported size: the
    bucket planner collapses the ladder and records the export's
    symbolic_error as the reason."""
    import paddle_tpu.inference.aot  # noqa: F401 — jexport target below
    from jax import export as jexport

    real = jexport.export
    calls = {"n": 0}

    def flaky_export(fn, **kw):
        wrapped = real(fn, **kw)

        def call(*specs):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ValueError("synthetic: polymorphism unsupported")
            return wrapped(*specs)

        return call

    monkeypatch.setattr(jexport, "export", flaky_export)
    predict = _export_small_cnn(str(tmp_path))
    assert predict.meta["batch"] == "static"
    eng = Engine.from_artifact(
        predict, config=EngineConfig(buckets=(1, 2, 4), max_wait_s=0.0))
    assert eng.ladder.buckets == (1,)
    assert "synthetic" in eng.bucket_reason
    (out,) = eng.infer({"image": np.zeros((1, 1, 8, 8), np.float32)})
    assert out.shape == (1, 3)
    with pytest.raises(ValueError, match="max_batch"):
        eng.submit({"image": np.zeros((2, 1, 8, 8), np.float32)})
    eng.close()


def test_engine_rejects_bad_feeds(cnn_predict):
    eng = Engine.from_artifact(
        cnn_predict, config=EngineConfig(buckets=(1, 2)))
    with pytest.raises(KeyError, match="missing"):
        eng.submit({})
    with pytest.raises(KeyError, match="unknown"):
        eng.submit({"image": np.zeros((1, 1, 8, 8), np.float32),
                    "oops": np.zeros((1,), np.float32)})
    eng.close()


# -- (d) deadlines, drain, backpressure ---------------------------------

def test_deadline_timeout_and_drain_semantics():
    backend = _GatedBackend()
    eng = Engine(backend, config=EngineConfig(buckets=(1,), max_wait_s=0.0))
    f_inflight = eng.submit({"x": np.ones((1, 2), np.float32)})
    _wait_until(lambda: backend.calls == 1)  # A is in-flight, queue empty
    f_b = eng.submit({"x": np.full((1, 2), 3.0, np.float32)}, timeout=0.01)
    f_c = eng.submit({"x": np.full((1, 2), 4.0, np.float32)}, timeout=0.01)
    time.sleep(0.05)  # let both deadlines lapse while A blocks the engine
    eng.begin_drain()
    with pytest.raises(EngineClosedError):
        eng.submit({"x": np.ones((1, 2), np.float32)})
    backend.gate.set()
    assert eng.drain(timeout=10.0)
    # the in-flight batch completed during drain...
    np.testing.assert_array_equal(
        f_inflight.result(timeout=1.0)[0], np.full((1, 2), 2.0, np.float32))
    # ...and the expired queued requests failed with the NAMED error
    for f in (f_b, f_c):
        with pytest.raises(RequestTimeoutError, match="expired"):
            f.result(timeout=1.0)
    eng.close()


def test_deadline_fires_without_traffic():
    """An expired request fails promptly even when nothing else arrives
    to tickle the dispatcher: a 1-row request under a batch-fill window
    of 5s must NOT wait the window out — the dispatcher's sleep tracks
    the earliest deadline."""
    backend = _GatedBackend()
    backend.gate.set()
    eng = Engine(backend, config=EngineConfig(buckets=(2,), max_wait_s=5.0))
    t0 = time.perf_counter()
    f = eng.submit({"x": np.ones((1, 2), np.float32)}, timeout=0.05)
    with pytest.raises(RequestTimeoutError):
        f.result(timeout=2.0)
    assert time.perf_counter() - t0 < 2.0  # not the 5s fill window
    eng.close()


def test_queue_backpressure():
    backend = _GatedBackend()
    eng = Engine(backend, config=EngineConfig(
        buckets=(1,), max_wait_s=0.0, queue_depth=2))
    f_a = eng.submit({"x": np.ones((1, 2), np.float32)})
    _wait_until(lambda: backend.calls == 1)
    eng.submit({"x": np.ones((1, 2), np.float32)})
    eng.submit({"x": np.ones((1, 2), np.float32)})
    with pytest.raises(QueueFullError):
        eng.submit({"x": np.ones((1, 2), np.float32)})
    backend.gate.set()
    eng.close()
    assert f_a.result(timeout=1.0)


def test_preemption_drain_wiring():
    """SIGTERM-path: PreemptionDrain.request() stops admissions via the
    listener hook while admitted work completes."""
    backend = _GatedBackend()
    backend.gate.set()  # fast backend
    eng = Engine(backend, config=EngineConfig(buckets=(1,), max_wait_s=0.0))
    drain = PreemptionDrain()
    eng.attach_drain(drain)
    f = eng.submit({"x": np.ones((1, 2), np.float32)})
    drain.request()
    assert eng.draining
    np.testing.assert_array_equal(
        f.result(timeout=5.0)[0], np.full((1, 2), 2.0, np.float32))
    with pytest.raises(EngineClosedError):
        eng.submit({"x": np.ones((1, 2), np.float32)})
    eng.close()
    # a listener attached AFTER the notice fires immediately
    late = Engine(backend, config=EngineConfig(buckets=(1,)))
    late.attach_drain(drain)
    assert late.draining
    late.close()


def test_begin_drain_is_nonblocking_under_contention():
    """begin_drain runs from SIGNAL context on the main thread — it must
    never block on the engine lock (a SIGTERM landing while that thread
    is inside submit() would self-deadlock), and the drain must still
    proceed via the dispatcher's bounded park."""
    backend = _GatedBackend()
    backend.gate.set()
    eng = Engine(backend, config=EngineConfig(buckets=(1,), max_wait_s=0.0))
    with eng._cond:  # simulate the interrupted thread holding the lock
        t0 = time.perf_counter()
        eng.begin_drain()  # must return immediately, no notify possible
        assert time.perf_counter() - t0 < 0.1
    assert eng.draining
    assert eng.drain(timeout=2 * Engine._IDLE_PARK_S + 1.0)
    eng.close()


def test_close_timeout_fails_stranded_requests():
    """A close() whose drain times out must FAIL whatever is still
    queued — a stopped dispatcher leaving futures pending would hang
    every caller blocked in .result()."""
    backend = _GatedBackend()  # gate closed: first dispatch blocks
    eng = Engine(backend, config=EngineConfig(buckets=(1,), max_wait_s=0.0))
    f_inflight = eng.submit({"x": np.ones((1, 2), np.float32)})
    _wait_until(lambda: backend.calls == 1)
    f_queued = eng.submit({"x": np.ones((1, 2), np.float32)})
    eng.close(timeout=0.1)  # cannot drain: the backend is blocked
    with pytest.raises(EngineClosedError, match="drain timed out"):
        f_queued.result(timeout=1.0)
    backend.gate.set()  # release the in-flight batch: it still completes
    np.testing.assert_array_equal(
        f_inflight.result(timeout=5.0)[0], np.full((1, 2), 2.0, np.float32))


def test_done_callback_touching_engine_does_not_deadlock():
    """Future.set_exception runs done-callbacks synchronously on the
    dispatcher thread; a callback that calls back into the engine must
    not deadlock it (expired futures complete OUTSIDE the lock)."""
    backend = _GatedBackend()
    backend.gate.set()
    eng = Engine(backend, config=EngineConfig(buckets=(2,), max_wait_s=5.0))
    seen = []
    f = eng.submit({"x": np.ones((1, 2), np.float32)}, timeout=0.05)
    f.add_done_callback(lambda fut: seen.append(eng.queue_depth()))
    with pytest.raises(RequestTimeoutError):
        f.result(timeout=2.0)
    _wait_until(lambda: len(seen) == 1)
    # the dispatcher survived the reentrant callback: it still serves
    ok = eng.submit({"x": np.ones((2, 2), np.float32)})
    np.testing.assert_array_equal(
        ok.result(timeout=5.0)[0], np.full((2, 2), 2.0, np.float32))
    eng.close()


def test_trailing_shape_mismatch_rejected_at_submit(cnn_predict):
    """One client's mis-shaped request must fail at submit(), not poison
    the batch-mates it would have coalesced with."""
    eng = Engine.from_artifact(
        cnn_predict, config=EngineConfig(buckets=(1, 2, 4)))
    with pytest.raises(ValueError, match="trailing shape"):
        eng.submit({"image": np.zeros((1, 1, 32, 32), np.float32)})
    (out,) = eng.infer({"image": np.zeros((1, 1, 8, 8), np.float32)})
    assert out.shape == (1, 3)
    eng.close()


def test_abandoned_engine_is_collected():
    """An Engine dropped without close() must be garbage-collectable
    (the dispatcher holds it via weakref between cycles) — otherwise
    every forgotten Inferencer leaks a thread + executor forever."""
    import gc
    import weakref

    backend = _GatedBackend()
    backend.gate.set()
    eng = Engine(backend, config=EngineConfig(buckets=(1,), max_wait_s=0.0))
    eng.infer({"x": np.ones((1, 2), np.float32)})
    thread = eng._thread
    ref = weakref.ref(eng)
    del eng
    t0 = time.perf_counter()
    while ref() is not None and time.perf_counter() - t0 < 5.0:
        gc.collect()
        time.sleep(0.05)
    assert ref() is None
    thread.join(timeout=2 * Engine._IDLE_PARK_S + 1.0)
    assert not thread.is_alive()


def test_backend_failure_fails_the_batch():
    class Boom:
        feed_names = ["x"]
        fetch_names = ["y"]
        meta: dict = {}

        def __call__(self, feed):
            raise RuntimeError("backend exploded")

    eng = Engine(Boom(), config=EngineConfig(buckets=(1, 2), max_wait_s=0.0))
    f = eng.submit({"x": np.ones((1, 2), np.float32)})
    with pytest.raises(RuntimeError, match="exploded"):
        f.result(timeout=5.0)
    eng.close()


# -- Inferencer rides the engine ---------------------------------------

def test_inferencer_routes_through_engine(tmp_path):
    from paddle_tpu.contrib.inferencer import Inferencer

    def net():
        x = layers.data("x", [4], dtype="float32")
        return layers.fc(x, size=2,
                         param_attr=fluid.ParamAttr(name="infer_w"),
                         bias_attr=fluid.ParamAttr(name="infer_b"))

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), unique_name_guard():
        net()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_persistables(exe, str(tmp_path), main_program=main)

    inf = Inferencer(net, str(tmp_path), place=fluid.CPUPlace())
    x = np.ones((3, 4), np.float32)
    (out1,) = inf.infer({"x": x})
    (out2,) = inf.infer({"x": x})
    np.testing.assert_array_equal(out1, out2)
    assert out1.shape == (3, 2)
    # both calls went through ONE engine sharing one executor cache
    stats = inf._engine.stats()
    assert stats["batches"] == 2
    assert stats["distinct_shapes"] == 1  # same feed shape counts once
    # a new feed shape is a fresh executor trace — the counter says so
    inf.infer({"x": np.ones((5, 4), np.float32)})
    assert inf._engine.stats()["distinct_shapes"] == 2
    inf.close()


# -- KV-cache pool ------------------------------------------------------

def test_kvcache_alloc_append_free_accounting():
    pool = KVCachePool(num_pages=4, page_size=2, num_layers=1,
                       num_heads=1, head_dim=4)
    pool.allocate(0)
    for step in range(4):  # 4 tokens -> 2 pages
        pages, slots = pool.append_token([0])
        pool.write_kv(0, pages, slots,
                      np.full((1, 1, 4), step, np.float32),
                      np.full((1, 1, 4), -step, np.float32))
    assert pool.used_pages == 2 and pool.length(0) == 4
    tables, lengths = pool.page_table_batch([0])
    k = np.asarray(gather_kv_pages(pool.k_pages[0], tables))  # [1,H,S,D]
    np.testing.assert_array_equal(k[0, 0, :, 0], [0, 1, 2, 3])
    assert pool.free_seq(0) == 2
    assert pool.free_pages == pool.num_pages
    st = pool.stats()
    assert st["page_allocs"] == 2 and st["page_frees"] == 2
    assert st["used_pages_high_water"] == 2


def test_kvcache_exhaustion_is_atomic():
    pool = KVCachePool(num_pages=2, page_size=2, num_layers=1,
                       num_heads=1, head_dim=4)
    pool.allocate(0)
    pool.allocate(1)
    pool.append_token([0])
    pool.append_token([1])  # both pages claimed
    pool.append_token([0])  # slot 1 of page A, no fresh page needed
    with pytest.raises(PagePoolExhausted):
        # 0 needs a fresh page (full) and 1 has a slot: the claim must
        # fail BEFORE advancing either sequence
        pool.append_token([0, 1])
    assert pool.length(0) == 2 and pool.length(1) == 1


def test_kvcache_defrag_preserves_contents():
    pool = KVCachePool(num_pages=6, page_size=2, num_layers=1,
                       num_heads=1, head_dim=2)
    for s in range(3):
        pool.allocate(s)
    for step in range(4):
        pages, slots = pool.append_token([0, 1, 2])
        k = np.stack([np.full((1, 2), 100 * s + step, np.float32)
                      for s in range(3)])
        pool.write_kv(0, pages, slots, k, k)
    pool.free_seq(1)  # punch a hole mid-pool
    before_tables, lengths = pool.page_table_batch([0, 2])
    before = np.asarray(gather_kv_pages(pool.k_pages[0], before_tables))
    moves = pool.defrag()
    assert moves > 0
    after_tables, lengths2 = pool.page_table_batch([0, 2])
    after = np.asarray(gather_kv_pages(pool.k_pages[0], after_tables))
    np.testing.assert_array_equal(before, after)
    np.testing.assert_array_equal(lengths, lengths2)
    # compacted: live pages occupy the lowest indices
    assert int(np.asarray(after_tables).max()) == pool.used_pages - 1


# -- decode-shaped ragged attention (the KV-loop contract) --------------

def test_flash_decode_ragged_matches_reference_token_for_token():
    """Sq=1 queries against a fixed K/V buffer with growing k_lengths —
    exactly what the paged decode loop issues — must match dense
    reference attention over the true prefix at every step, through the
    REAL pallas kernel (interpret mode) and the jax path."""
    B, H, S, D = 2, 2, 32, 8
    rng = np.random.RandomState(11)
    q_all = rng.standard_normal((B, H, S, D)).astype(np.float32)
    k_buf = rng.standard_normal((B, H, S, D)).astype(np.float32)
    v_buf = rng.standard_normal((B, H, S, D)).astype(np.float32)
    scale = D ** -0.5
    for force in ("interpret", "jax"):
        for t in range(1, S + 1):
            q = q_all[:, :, t - 1:t, :]
            got = np.asarray(flash_attention(
                q, k_buf, v_buf, causal=False, scale=scale,
                k_lengths=np.full((B,), t, np.int32), force=force))
            want = np.asarray(_reference_attention(
                q, k_buf[:, :, :t], v_buf[:, :, :t], causal=False,
                scale=scale))
            np.testing.assert_allclose(
                got, want, rtol=2e-5, atol=2e-6,
                err_msg=f"step {t} force={force}")


# -- (e) pallas ragged paged attention: interpret-mode parity ----------

def test_paged_pallas_interpret_matches_reference_multistep():
    """The REAL pallas page-walk kernel (interpret mode) vs the
    reference gather, token-for-token over a simulated multi-step decode:
    >=3 overlapping sequences, ragged lengths, mixed page counts — the
    pool-level mirror of the flash Sq=1 contract test."""
    H, Dh, page_size = 2, 8, 3  # odd page size: deliberately unaligned
    pool = KVCachePool(num_pages=32, page_size=page_size, num_layers=1,
                       num_heads=H, head_dim=Dh)
    rng = np.random.RandomState(23)
    seq_ids = [0, 1, 2, 3]
    for s in seq_ids:
        pool.allocate(s)
    # stagger the prefixes so lengths (and page counts) stay ragged
    for s, prefix in zip(seq_ids, (5, 1, 9, 3)):
        for _ in range(prefix):
            pages, slots = pool.append_token([s])
            pool.write_kv(0, pages, slots,
                          rng.standard_normal((1, H, Dh)).astype(np.float32),
                          rng.standard_normal((1, H, Dh)).astype(np.float32))
    for step in range(12):
        pages, slots = pool.append_token(seq_ids)
        B = len(seq_ids)
        pool.write_kv(0, pages, slots,
                      rng.standard_normal((B, H, Dh)).astype(np.float32),
                      rng.standard_normal((B, H, Dh)).astype(np.float32))
        tables, lengths = pool.page_table_batch(seq_ids)
        assert len(set(tables.shape[1] - (lengths - 1) // page_size)) > 1, \
            "page counts must stay mixed for the test to bite"
        q = rng.standard_normal((B, H, 1, Dh)).astype(np.float32)
        want = np.asarray(paged_decode_attention(
            q, pool.k_pages[0], pool.v_pages[0], tables, lengths,
            impl="reference"))
        got = np.asarray(paged_decode_attention(
            q, pool.k_pages[0], pool.v_pages[0], tables, lengths,
            impl="interpret"))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6,
                                   err_msg=f"step {step}")


def test_paged_envelope_and_flag_selection():
    """pallas_paged_viable encodes the Mosaic tiling envelope; explicit
    pallas OUTSIDE it falls back to the reference gather (identical
    numbers, never a compile failure); the flag validates its choices."""
    # in-envelope: lane-multiple head_dim, sublane-multiple page size
    assert pallas_paged_viable(16, 128)
    assert pallas_paged_viable(8, 256)
    assert pallas_paged_viable(16, 128, "bfloat16")
    # out: unaligned page size / head_dim / dtype
    assert not pallas_paged_viable(3, 128)
    assert not pallas_paged_viable(16, 64)
    assert not pallas_paged_viable(8, 128, "bfloat16")  # bf16 sublane=16
    assert not pallas_paged_viable(16, 128, "float64")
    # resolution: auto on CPU -> reference; explicit pallas out of
    # envelope -> reference fallback; interpret passes through
    assert resolve_paged_impl(None, 16, 128) == "reference"
    assert resolve_paged_impl("pallas", 3, 8) == "reference"
    assert resolve_paged_impl("interpret", 3, 8) == "interpret"
    with pytest.raises(ValueError, match="impl"):
        resolve_paged_impl("mosaic", 16, 128)
    with pytest.raises(ValueError):
        fluid.set_flags({"FLAGS_serving_paged_impl": "gather"})
    # the loop resolves the impl it will actually run (and labels
    # metrics with it)
    cfg = DecodeConfig(vocab_size=17, d_model=16, n_head=2, n_layer=1,
                       d_inner=16, max_length=16)
    pool = KVCachePool(num_pages=4, page_size=4, num_layers=1,
                       num_heads=2, head_dim=8)
    loop = ContinuousBatchingLoop(init_decode_params(cfg, seed=0), cfg,
                                  pool, paged_impl="pallas")
    assert loop.paged_impl == "reference"  # head_dim 8: out of envelope
    with pytest.raises(ValueError, match="prefill"):
        ContinuousBatchingLoop(init_decode_params(cfg, seed=0), cfg,
                               pool, prefill="speculative")


def test_attention_bytes_per_step_model():
    """The metrics gauge's analytic model: reference moves 3x the KV
    bytes of the pallas stream (pages + contiguous copy written + copy
    read back), scaled by layers."""
    kw = dict(batch=4, max_pages=32, page_size=16, num_heads=8,
              head_dim=128, itemsize=4, num_layers=2)
    s_kv = 4 * 32 * 16 * 8 * 128 * 4
    assert attention_bytes_per_step("pallas", **kw) == 2 * s_kv * 2
    assert attention_bytes_per_step("interpret", **kw) == 2 * s_kv * 2
    assert attention_bytes_per_step("reference", **kw) == 6 * s_kv * 2


# -- (f) batched whole-prompt prefill ----------------------------------

def test_prefill_step_matches_full_forward_oracle():
    """ONE batched causal pass == the whole-sequence oracle: last-row
    logits per sequence at fp32 tolerance, the pool holding exactly the
    K/V token-by-token prefill would have written."""
    cfg = DecodeConfig(vocab_size=37, d_model=16, n_head=2, n_layer=2,
                       d_inner=32, max_length=32)
    params = init_decode_params(cfg, seed=9)
    rng = np.random.RandomState(9)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (6, 2, 4)]
    pool = KVCachePool(num_pages=16, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim)
    for s in range(len(prompts)):
        pool.allocate(s)
    logits = prefill_step(params, cfg, pool, list(range(len(prompts))),
                          prompts)
    for i, p in enumerate(prompts):
        want = full_forward(params, cfg, p)[-1]
        np.testing.assert_allclose(logits[i], want, rtol=1e-4, atol=1e-4,
                                   err_msg=f"sequence {i}")
        assert pool.length(i) == len(p)
    # the cached K/V is the same content token-by-token would have
    # produced: a decode step on top must match full_decode's next token
    tokens = [int(row.argmax()) for row in logits]
    from paddle_tpu.serving.generate import decode_step

    step_logits = decode_step(params, cfg, pool, list(range(len(prompts))),
                              tokens, [len(p) for p in prompts])
    for i, p in enumerate(prompts):
        want_tokens, want_logits = full_decode(params, cfg, p, 2)
        assert tokens[i] == want_tokens[0]
        np.testing.assert_allclose(step_logits[i], want_logits[1],
                                   rtol=1e-4, atol=1e-4)


def test_batched_prefill_token_identical_and_o1_steps():
    """prefill='batched' vs prefill='token': token-identical
    generations, logits at fp32 tolerance — and prefill model-steps are
    O(1) per admission group instead of O(prompt_len)."""
    cfg = DecodeConfig(vocab_size=53, d_model=16, n_head=2, n_layer=2,
                       d_inner=32, max_length=48)
    params = init_decode_params(cfg, seed=3)
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (7, 3, 5)]
    max_new = 5

    def run(prefill):
        pool = KVCachePool(num_pages=24, page_size=4,
                           num_layers=cfg.n_layer, num_heads=cfg.n_head,
                           head_dim=cfg.head_dim)
        loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3,
                                      prefill=prefill)
        return loop, loop.run(
            [DecodeRequest(p, max_new) for p in prompts])

    tok_loop, tok_res = run("token")
    bat_loop, bat_res = run("batched")
    for t, b in zip(tok_res, bat_res):
        assert t.tokens == b.tokens
        for lt, lb in zip(t.logits, b.logits):
            np.testing.assert_allclose(lb, lt, rtol=1e-4, atol=1e-4)
    # token-by-token burns one model step per prompt token; batched
    # prefill is ONE step for the whole co-admitted group
    assert tok_loop.prefill_steps == 0
    assert bat_loop.prefill_steps == 1  # all 3 admit together
    assert bat_loop.steps == 1 + bat_loop.decode_steps
    assert bat_loop.steps <= tok_loop.steps - (max(len(p) for p in prompts) - 1)
    # both loops retire cleanly
    assert tok_loop.pool.free_pages == tok_loop.pool.num_pages
    assert bat_loop.pool.free_pages == bat_loop.pool.num_pages


def test_continuous_batching_pallas_interpret_end_to_end():
    """The whole loop — batched prefill + pallas (interpret) paged
    decode — against the full-recompute oracle."""
    cfg = DecodeConfig(vocab_size=41, d_model=16, n_head=2, n_layer=2,
                       d_inner=32, max_length=32)
    params = init_decode_params(cfg, seed=7)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (4, 2, 3)]
    pool = KVCachePool(num_pages=18, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3,
                                  paged_impl="interpret")
    assert loop.paged_impl == "interpret"
    results = loop.run([DecodeRequest(p, 4) for p in prompts])
    for p, res in zip(prompts, results):
        want_tokens, want_logits = full_decode(params, cfg, p, 4)
        assert res.tokens == want_tokens
        for got, want in zip(res.logits, want_logits):
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    assert pool.free_pages == pool.num_pages


# -- (c) continuous-batching decode parity ------------------------------

def test_continuous_batching_decode_matches_full_recompute():
    cfg = DecodeConfig(vocab_size=61, d_model=16, n_head=2, n_layer=2,
                       d_inner=32, max_length=48)
    params = init_decode_params(cfg, seed=5)
    rng = np.random.RandomState(5)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (3, 5, 2, 4)]
    max_new = 6
    reqs = [DecodeRequest(p, max_new) for p in prompts]

    # pool sized for 3 concurrent worst-case sequences but not 4: the
    # 4th admits only when a retirement frees pages (admit-as-retire)
    page_size = 4
    per_seq = KVCachePool.pages_needed(max(len(p) for p in prompts) + max_new,
                                       page_size)
    pool = KVCachePool(num_pages=3 * per_seq, page_size=page_size,
                       num_layers=cfg.n_layer, num_heads=cfg.n_head,
                       head_dim=cfg.head_dim)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3)
    results = loop.run(reqs)

    # ≥3 sequences genuinely overlapped: strictly fewer steps than
    # serial execution, and mean occupancy shows real batching
    serial_steps = sum(len(p) + max_new - 1 for p in prompts)
    assert loop.steps < serial_steps
    assert loop.mean_occupancy() > 0.5

    for req, res in zip(reqs, results):
        want_tokens, want_logits = full_decode(
            params, cfg, req.prompt, req.max_new_tokens)
        assert res.tokens == want_tokens
        assert len(res.logits) == len(want_logits)
        for got, want in zip(res.logits, want_logits):
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
        assert res.ttft_s is not None

    # every retired sequence's pages are back in the free pool
    assert pool.free_pages == pool.num_pages
    assert pool.stats()["live_sequences"] == 0


def test_decode_pool_too_small_raises():
    cfg = DecodeConfig(vocab_size=31, d_model=16, n_head=2, n_layer=1,
                       d_inner=16, max_length=32)
    params = init_decode_params(cfg, seed=1)
    pool = KVCachePool(num_pages=1, page_size=2, num_layers=1,
                       num_heads=2, head_dim=8)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1)
    with pytest.raises(PagePoolExhausted):
        loop.run([DecodeRequest([1, 2, 3], 4)])


# -- observability wiring ----------------------------------------------

def test_serving_metrics_emitted_when_enabled(cnn_predict):
    from paddle_tpu import observability as obs

    obs.reset()
    fluid.set_flags({"FLAGS_observability": True})
    try:
        eng = Engine.from_artifact(
            cnn_predict, config=EngineConfig(buckets=(1, 2), max_wait_s=0.0))
        eng.infer({"image": np.zeros((1, 1, 8, 8), np.float32)})
        eng.close()

        cfg = DecodeConfig(vocab_size=17, d_model=8, n_head=2, n_layer=1,
                           d_inner=16, max_length=16)
        pool = KVCachePool(num_pages=4, page_size=4, num_layers=1,
                           num_heads=2, head_dim=4)
        ContinuousBatchingLoop(
            init_decode_params(cfg, seed=0), cfg, pool, max_batch=2,
        ).run([DecodeRequest([1, 2], 2)])

        snap = obs.default_registry().snapshot()["metrics"]
        names = {m["name"] for m in snap}
        for want in (
            "paddle_tpu_serving_queue_depth",
            "paddle_tpu_serving_requests",
            "paddle_tpu_serving_batches",
            "paddle_tpu_serving_batch_occupancy",
            "paddle_tpu_serving_request_latency_seconds",
            "paddle_tpu_serving_ttft_seconds",
            "paddle_tpu_serving_token_seconds",
            "paddle_tpu_serving_attention_bytes_per_step",
            "paddle_tpu_serving_page_pool_utilization",
            "paddle_tpu_serving_sequences",
        ):
            assert want in names, f"missing {want} in {sorted(names)}"
        # decode-step instruments are labeled with the active impl
        by_name = {m["name"]: m for m in snap}
        tok_labels = {s["labels"].get("impl")
                      for s in by_name["paddle_tpu_serving_token_seconds"]
                      ["series"]}
        assert tok_labels == {"reference"}  # CPU auto-resolves reference
        bytes_series = by_name[
            "paddle_tpu_serving_attention_bytes_per_step"]["series"]
        assert bytes_series and all(
            s["labels"]["impl"] == "reference" and s["value"] > 0
            for s in bytes_series)
    finally:
        fluid.set_flags({"FLAGS_observability": False})
        obs.reset()


def test_serving_metrics_silent_when_disabled(cnn_predict):
    from paddle_tpu import observability as obs

    obs.reset()
    assert not obs.enabled()
    eng = Engine.from_artifact(
        cnn_predict, config=EngineConfig(buckets=(1, 2), max_wait_s=0.0))
    eng.infer({"image": np.zeros((1, 1, 8, 8), np.float32)})
    eng.close()
    assert obs.default_registry().snapshot()["metrics"] == []


# -- serve_bench --------------------------------------------------------

def test_serve_bench_engine_smoke_and_gate(tmp_path, capsys):
    import json

    from tools.serve_bench import main as bench_main

    out = tmp_path / "bench.json"
    rc = bench_main([
        "--model", "mnist", "--requests", "8", "--rate", "400",
        "--buckets", "1,2,4", "--batch-range", "1,4",
        "--json", str(out),
    ])
    assert rc == 0
    result = json.loads(out.read_text())
    assert result["mode"] == "engine"
    assert result["distinct_shapes"] <= 3
    assert result["throughput_rps"] > 0
    # bank this run, re-gate against itself: must pass
    bank = tmp_path / "bank.json"
    bank.write_text(json.dumps(
        {"p99_ms": result["p99_ms"] * 10, "distinct_shapes": 3}))
    rc = bench_main([
        "--model", "mnist", "--requests", "8", "--rate", "400",
        "--buckets", "1,2,4", "--batch-range", "1,4",
        "--baseline", str(bank), "--tol", "0.5", "--gate",
    ])
    assert rc == 0
    # an impossible baseline must fail the gate with exit 3
    bank.write_text(json.dumps({"p99_ms": 1e-9}))
    rc = bench_main([
        "--model", "tiny", "--requests", "4", "--rate", "400",
        "--buckets", "1,2", "--batch-range", "1,2",
        "--baseline", str(bank), "--gate",
    ])
    assert rc == 3
    capsys.readouterr()  # swallow the report text


def test_serve_bench_decode_smoke(capsys):
    from tools.serve_bench import main as bench_main

    rc = bench_main([
        "--mode", "decode", "--sequences", "3", "--max-new", "4",
        "--d-model", "16", "--vocab", "31", "--max-len", "32",
        "--pages", "32", "--page-size", "4",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"pages_leaked": 0' in out


@pytest.mark.slow
def test_serve_bench_decode_transformer_scale(capsys):
    """Transformer-shaped decode config (d_model 128, 4 layers) through
    the paged loop — the load-generator run banked for trend tracking."""
    from tools.serve_bench import main as bench_main

    rc = bench_main([
        "--mode", "decode", "--sequences", "8", "--max-new", "16",
        "--d-model", "128", "--n-head", "8", "--n-layer", "4",
        "--vocab", "512", "--max-len", "96", "--max-batch", "4",
        "--pages", "128", "--page-size", "8",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"pages_leaked": 0' in out
