"""Detection/vision ops (reference: test_prior_box_op.py,
test_iou_similarity_op.py, test_box_coder_op.py, test_bipartite_match_op.py,
test_multiclass_nms_op.py, test_roi_pool_op.py, test_roi_align_op.py,
test_grid_sampler_op.py, test_yolov3_loss_op.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.lod import create_lod_tensor


def _run(feed, fetch_list, return_numpy=True):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed, fetch_list=fetch_list, return_numpy=return_numpy)


def test_prior_box_shapes_and_range():
    x = layers.data("feat", [8, 4, 4], dtype="float32")
    img = layers.data("img", [3, 32, 32], dtype="float32")
    boxes, var = layers.prior_box(
        x, img, min_sizes=[4.0], max_sizes=[8.0],
        aspect_ratios=[2.0], flip=True, clip=True,
    )
    got_b, got_v = _run(
        {
            "feat": np.zeros((1, 8, 4, 4), "float32"),
            "img": np.zeros((1, 3, 32, 32), "float32"),
        },
        [boxes, var],
    )
    got_b, got_v = np.asarray(got_b), np.asarray(got_v)
    # 1 min_size * (1 + 2 flip-expanded ratios) + 1 max_size = 4 priors
    assert got_b.shape == (4, 4, 4, 4)
    assert got_v.shape == got_b.shape
    assert got_b.min() >= 0.0 and got_b.max() <= 1.0
    np.testing.assert_allclose(got_v[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


def test_iou_similarity_exact():
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [4], dtype="float32")
    out = layers.iou_similarity(x, y)
    a = np.array([[0, 0, 2, 2]], dtype="float32")
    b = np.array([[1, 1, 3, 3], [0, 0, 2, 2], [4, 4, 5, 5]], dtype="float32")
    (got,) = _run({"x": a, "y": b}, [out])
    np.testing.assert_allclose(
        np.asarray(got)[0], [1 / 7, 1.0, 0.0], rtol=1e-6
    )


def test_box_coder_roundtrip():
    # encode then decode must reproduce the original boxes
    prior = np.array([[0, 0, 4, 4], [2, 2, 6, 8]], dtype="float32")
    pvar = np.ones((2, 4), dtype="float32")
    target = np.array([[1, 1, 3, 3]], dtype="float32")

    pb = layers.data("pb", [4], dtype="float32")
    pv = layers.data("pv", [4], dtype="float32")
    tb = layers.data("tb", [4], dtype="float32")
    enc = layers.box_coder(pb, pv, tb, code_type="encode_center_size")
    dec = layers.box_coder(pb, pv, enc, code_type="decode_center_size")
    got_enc, got_dec = _run(
        {"pb": prior, "pv": pvar, "tb": target}, [enc, dec]
    )
    got_dec = np.asarray(got_dec)  # [1, 2, 4]
    np.testing.assert_allclose(got_dec[0, 0], target[0], atol=1e-5)
    np.testing.assert_allclose(got_dec[0, 1], target[0], atol=1e-4)


def test_bipartite_match_greedy():
    dist = np.array(
        [[0.1, 0.9, 0.3], [0.8, 0.2, 0.7]], dtype="float32"
    )  # 2 rows (gt), 3 cols (priors)
    d = layers.data("d", [3], dtype="float32")
    idx, val = layers.bipartite_match(d)
    got_idx, got_val = _run({"d": dist}, [idx, val])
    got_idx = np.ravel(np.asarray(got_idx))
    # greedy: best is (0,1)=0.9 -> col1<-row0; next (1,0)=0.8 -> col0<-row1
    assert got_idx[1] == 0 and got_idx[0] == 1 and got_idx[2] == -1


def test_multiclass_nms_suppresses():
    # two heavily-overlapping boxes + one distant box, one foreground class
    boxes = np.array(
        [[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5], [20, 20, 30, 30]]],
        dtype="float32",
    )
    scores = np.zeros((1, 2, 3), dtype="float32")
    scores[0, 1] = [0.9, 0.8, 0.7]  # class 1 (class 0 = background)
    b = layers.data("b", [3, 4], dtype="float32")
    s = layers.data("s", [2, 3], dtype="float32")
    out = layers.multiclass_nms(
        b, s, score_threshold=0.1, nms_top_k=10, keep_top_k=5,
        nms_threshold=0.5,
    )
    (got,) = _run({"b": boxes, "s": scores}, [out], return_numpy=False)
    n_kept = int(np.asarray(got.lengths)[0])
    data = np.asarray(got.data)[0, :n_kept]
    assert n_kept == 2  # overlapping pair collapsed to one
    np.testing.assert_allclose(data[0, 1], 0.9, rtol=1e-6)
    np.testing.assert_allclose(data[0, 2:], [0, 0, 10, 10], rtol=1e-6)


def test_roi_align_uniform_feature():
    # constant feature map -> every pooled value equals the constant
    x = layers.data("x", [2, 8, 8], dtype="float32")
    rois = layers.data("rois", [4], dtype="float32", lod_level=1)
    out = layers.roi_align(x, rois, pooled_height=2, pooled_width=2,
                           spatial_scale=1.0)
    feat = np.full((1, 2, 8, 8), 3.5, dtype="float32")
    roi_val = create_lod_tensor([np.array([[1, 1, 6, 6]], dtype="float32")])
    (got,) = _run({"x": feat, "rois": roi_val}, [out])
    got = np.asarray(got)
    assert got.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(got, 3.5, rtol=1e-5)


def test_grid_sampler_identity():
    x = layers.data("x", [1, 4, 4], dtype="float32")
    g = layers.data("g", [4, 4, 2], dtype="float32")
    out = layers.grid_sampler(x, g)
    feat = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    ys, xs = np.meshgrid(
        np.linspace(-1, 1, 4), np.linspace(-1, 1, 4), indexing="ij"
    )
    grid = np.stack([xs, ys], axis=-1)[None].astype("float32")
    (got,) = _run({"x": feat, "g": grid}, [out])
    np.testing.assert_allclose(np.asarray(got), feat, atol=1e-5)


def test_affine_channel():
    x = layers.data("x", [3, 2, 2], dtype="float32")
    s = layers.data("s", [3], dtype="float32")
    b = layers.data("b", [3], dtype="float32")
    out = layers.affine_channel(x, s, b)
    xv = np.ones((1, 3, 2, 2), "float32")
    (got,) = _run(
        {"x": xv, "s": np.array([1, 2, 3], "float32"),
         "b": np.array([10, 20, 30], "float32")},
        [out],
    )
    got = np.asarray(got)
    np.testing.assert_allclose(got[0, 0], 11.0)
    np.testing.assert_allclose(got[0, 2], 33.0)


def test_yolov3_loss_trains():
    A, CLS, H = 3, 4, 4
    anchors = [10, 13, 16, 30, 33, 23]
    x = layers.data("x", [A * (5 + CLS), H, H], dtype="float32")
    gtb = layers.data("gtb", [2, 4], dtype="float32")
    gtl = layers.data("gtl", [2], dtype="int32")
    feat = layers.conv2d(x, num_filters=A * (5 + CLS), filter_size=1)
    loss_t = layers.yolov3_loss(
        feat, gtb, gtl, anchors=anchors, class_num=CLS, ignore_thresh=0.7,
        downsample_ratio=32,
    )
    loss = layers.mean(loss_t)
    fluid.optimizer.AdamOptimizer(learning_rate=0.01).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {
        "x": rng.randn(2, A * (5 + CLS), H, H).astype("float32"),
        "gtb": np.array(
            [[[0.3, 0.3, 0.2, 0.2], [0.7, 0.7, 0.3, 0.3]],
             [[0.5, 0.5, 0.4, 0.4], [0, 0, 0, 0]]], dtype="float32"
        ),
        "gtl": np.array([[1, 2], [3, 0]], dtype="int32"),
    }
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = [
        float(np.ravel(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0]))[0])
        for _ in range(10)
    ]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_ssd_loss_trains():
    P, C, B = 8, 3, 2  # priors, classes, gt boxes per image
    rng = np.random.RandomState(0)
    prior = np.sort(rng.rand(P, 4).astype("float32"), axis=1)
    pvar = np.full((P, 4), 0.1, dtype="float32")

    loc = layers.data("loc", [P, 4], dtype="float32")
    conf = layers.data("conf", [P, C], dtype="float32")
    gtb = layers.data("gtb", [4], dtype="float32", lod_level=1)
    gtl = layers.data("gtl", [1], dtype="int64", lod_level=1)
    pb = layers.data("pb", [4], append_batch_size=False, dtype="float32")
    pv = layers.data("pv", [4], append_batch_size=False, dtype="float32")

    feat_loc = layers.fc(loc, size=P * 4, num_flatten_dims=1)
    feat_loc = layers.reshape(feat_loc, [-1, P, 4])
    feat_conf = layers.fc(conf, size=P * C, num_flatten_dims=1)
    feat_conf = layers.reshape(feat_conf, [-1, P, C])
    loss = layers.mean(
        layers.ssd_loss(feat_loc, feat_conf, gtb, gtl, pb, pv)
    )
    fluid.optimizer.AdamOptimizer(learning_rate=0.02).minimize(loss)

    gt_boxes = [np.sort(rng.rand(B, 4).astype("float32"), axis=1)
                for _ in range(2)]
    gt_labels = [rng.randint(1, C, size=(B, 1)).astype("int64")
                 for _ in range(2)]
    feed = {
        "loc": rng.randn(2, P, 4).astype("float32"),
        "conf": rng.randn(2, P, C).astype("float32"),
        "gtb": create_lod_tensor(gt_boxes),
        "gtl": create_lod_tensor(gt_labels),
        "pb": prior,
        "pv": pvar,
    }
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = [
        float(np.ravel(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0]))[0])
        for _ in range(10)
    ]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
