"""Benchmark model zoo parity (reference: benchmark/fluid/models/ — mnist,
resnet, vgg, stacked_dynamic_lstm, machine_translation, se_resnext)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import models


def _train(spec, steps=3, lr=0.01):
    fluid.optimizer.AdamOptimizer(learning_rate=lr).minimize(spec.loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    batch = spec.synthetic_batch(4)
    losses = []
    for _ in range(steps):
        (lv,) = exe.run(feed=batch, fetch_list=[spec.loss])
        losses.append(float(np.ravel(np.asarray(lv))[0]))
    assert np.isfinite(losses).all()
    return losses


def test_machine_translation_trains():
    spec = models.machine_translation(
        dict_size=100, embedding_dim=16, encoder_size=16, decoder_size=16
    )
    losses = _train(spec, steps=6, lr=0.005)
    assert losses[-1] < losses[0]


def test_se_resnext_trains():
    spec = models.se_resnext(
        class_num=10, layers_cfg=(1, 1, 1, 1), cardinality=8,
        reduction_ratio=4, img_shape=(3, 32, 32),
    )
    losses = _train(spec, steps=3)


def test_debugger_prints_program():
    x = fluid.layers.data("x", [4], dtype="float32")
    y = fluid.layers.fc(x, size=2)
    text = fluid.debugger.pprint_program_codes(fluid.default_main_program())
    assert "mul(" in text and "var x" in text
    dot = fluid.debugger.draw_block_graphviz(
        fluid.default_main_program().global_block(), path="/tmp/g.dot"
    )
    assert "digraph" in dot


def test_chunk_evaluator_accumulates():
    from paddle_tpu.core.lod import create_lod_tensor

    inf = fluid.layers.data("inf", [1], dtype="int64", lod_level=1)
    lab = fluid.layers.data("lab", [1], dtype="int64", lod_level=1)
    ev = fluid.evaluator.ChunkEvaluator(
        inf, lab, chunk_scheme="IOB", num_chunk_types=1
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    seq = np.array([[0], [1], [2]], dtype="int64")
    for _ in range(3):
        exe.run(
            feed={"inf": create_lod_tensor([seq]),
                  "lab": create_lod_tensor([seq])},
            fetch_list=[ev.metrics[0]],
        )
    p, r, f1 = ev.eval(exe)
    assert float(p) == 1.0 and float(r) == 1.0 and float(f1) == 1.0


def test_vgg19_builds_and_infers():
    """VGG-19 (IntelOptimizedPaddle.md benchmark model): depth-19 block
    layout builds, and the for_test clone runs a forward pass."""
    spec = models.vgg19(class_num=10, img_shape=(3, 32, 32))
    # 19 = 16 convs + 3 fc; count conv2d ops in the program
    prog = fluid.default_main_program()
    n_convs = sum(1 for op in prog.global_block().ops if op.type == "conv2d")
    assert n_convs == 16
    test_prog = prog.clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    batch = spec.synthetic_batch(2)
    (pred,) = exe.run(program=test_prog, feed=batch,
                      fetch_list=[spec.extras["predict"]])
    pred = np.asarray(pred)
    assert pred.shape == (2, 10)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, atol=1e-4)


def test_alexnet_googlenet_forward():
    """AlexNet + GoogLeNet (benchmark/paddle/image/{alexnet,googlenet}.py
    configs) build at benchmark shapes and produce valid softmax output."""
    for builder in (models.alexnet, models.googlenet):
        fluid.reset_default_env()
        spec = builder(class_num=10)
        test_prog = fluid.default_main_program().clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        batch = spec.synthetic_batch(2)
        (pred,) = exe.run(program=test_prog, feed=batch,
                          fetch_list=[spec.extras["predict"]])
        pred = np.asarray(pred)
        assert pred.shape == (2, 10), spec.name
        np.testing.assert_allclose(pred.sum(axis=1), 1.0, atol=1e-4,
                                   err_msg=spec.name)


def test_bench_survives_single_model_failure(monkeypatch, capsys):
    """One model crashing (e.g. a kernel lowering error, as the r5 chip
    window's transformer pallas failure did) must not abort the other
    models' measurements: bench records the error per model and still
    prints a primary result line with rc=0 semantics."""
    import json as _json

    import bench

    def fake_run_model(model, steps, peak_flops, amp="1", layout="NCHW",
                       profile_logdir=None):
        if model == "transformer":
            raise ValueError("pallas lowering rejected block shape")
        return {"metric": f"{model}_train_examples_per_sec_per_chip",
                "value": 100.0, "unit": "examples/sec",
                "vs_baseline": None}

    monkeypatch.setattr(bench, "run_model", fake_run_model)
    monkeypatch.setenv("BENCH_MODELS", "lenet,transformer,deepfm")
    monkeypatch.setenv("BENCH_TUNE", "0")
    monkeypatch.setenv("BENCH_SMOKE", "1")
    monkeypatch.setenv("BENCH_DEADLINE_S", "0")
    bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = _json.loads(line)
    assert rec["metric"] == "lenet_train_examples_per_sec_per_chip"
    assert len(rec["extra_metrics"]) == 1
    assert rec["model_errors"][0]["model"] == "transformer"
    assert "block shape" in rec["model_errors"][0]["detail"]


def test_bench_all_models_failing_exits_2(monkeypatch, capsys):
    import bench

    def fake_run_model(model, steps, peak_flops, amp="1", layout="NCHW",
                       profile_logdir=None):
        raise ValueError("boom")

    monkeypatch.setattr(bench, "run_model", fake_run_model)
    monkeypatch.setenv("BENCH_MODELS", "lenet,deepfm")
    monkeypatch.setenv("BENCH_TUNE", "0")
    monkeypatch.setenv("BENCH_SMOKE", "1")
    monkeypatch.setenv("BENCH_DEADLINE_S", "0")
    try:
        bench.main()
        raised = False
    except SystemExit as e:
        raised = e.code == 2
    assert raised
    rec = __import__("json").loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "error"
