"""Enforce layer, net_drawer, diff_api tooling
(reference: platform/enforce.h EnforceNotMet semantics, net_drawer.py,
tools/diff_api.py)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.enforce import (
    EnforceNotMet,
    enforce,
    enforce_eq,
    enforce_ge,
    enforce_gt,
    enforce_not_none,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_enforce_helpers():
    enforce(True)
    enforce_eq(3, 3)
    enforce_gt(4, 3)
    enforce_ge(3, 3)
    assert enforce_not_none(5) == 5
    with pytest.raises(EnforceNotMet, match="must be positive"):
        enforce(False, "dim {d} must be positive", d=-1)
    with pytest.raises(EnforceNotMet, match="== "):
        enforce_eq(1, 2, "shape mismatch")
    with pytest.raises(EnforceNotMet):
        enforce_not_none(None)


def test_lowering_error_carries_op_context():
    """A broken op body surfaces as EnforceNotMet naming the op (the
    reference wraps kernel errors with the op DebugString,
    operator.cc:704)."""
    fluid.reset_default_env()
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [6], dtype="float32")
    # elementwise_add with incompatible shapes survives graph build (both
    # rank-1 descs) but fails at lowering time inside jax
    out = layers.elementwise_add(x, y)
    loss = layers.reduce_mean(out)
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(EnforceNotMet) as ei:
        exe.run(feed={"x": np.zeros((2, 4), np.float32),
                      "y": np.zeros((2, 6), np.float32)},
                fetch_list=[loss])
    msg = str(ei.value)
    assert "elementwise_add" in msg and "[context]" in msg


def test_net_drawer_emits_dot(tmp_path):
    fluid.reset_default_env()
    x = layers.data("x", [4], dtype="float32")
    h = layers.fc(x, 8, act="relu")
    layers.reduce_mean(h)
    path = str(tmp_path / "g.dot")
    dot = fluid.net_drawer.draw_graph(path=path)
    assert dot.startswith("digraph")
    assert '"mul"' in dot and '"relu"' in dot
    assert os.path.exists(path)
    # params get the param style fill
    assert dot.count("#c8f7c5") >= 1


def test_diff_api_tool_matches():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "diff_api.py")],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stdout + out.stderr


def test_op_names_reach_hlo_metadata():
    """Lowered programs carry fluid op types (and name_scope annotations)
    as jax named_scopes, so profiler traces map back to program ops (the
    reference's per-op RecordEvent/SetCurAnnotation linkage, profiler.h +
    device_tracer.h:102)."""
    import jax
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.core.compiler import CompiledBlock
    from paddle_tpu.core.executor import _RunPlan

    x = layers.data("x", [2], dtype="float32")
    with fluid.name_scope("enc"):
        h = layers.fc(x, size=2, act="relu")
    loss = layers.mean(h)

    prog = fluid.default_main_program()
    plan = _RunPlan(prog, ["x"], [loss.name])
    cb = CompiledBlock(prog, 0, plan.feed_names, plan.fetch_names,
                       plan.state_names, donate_states=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    blk = prog.desc.block(0)
    txt = jax.jit(cb.raw_fn).lower(
        plan.feed_values({"x": np.ones((2, 2), "float32")}, blk),
        plan.state_values(fluid.global_scope(), blk),
        jax.random.PRNGKey(0),
    ).as_text(debug_info=True)
    assert "enc/mul" in txt or "enc/relu" in txt


def test_op_census_only_by_design_missing():
    """tools/op_census.py: every reference REGISTER_OPERATOR name has a
    lowering except the documented MIGRATION.md by-design rows."""
    import json
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir("/root/reference/paddle/fluid/operators"):
        import pytest
        pytest.skip("reference tree not present")
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "op_census.py")],
        capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": root, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(r.stdout)
    assert data["undocumented_missing"] == []
    assert data["registered_lowerings"] >= 300
