"""Model zoo: each benchmark config builds, trains a few steps, and the loss
drops on a memorizable synthetic batch (reference analogue: tests/book/*,
benchmark/fluid smoke runs)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import models


def _train(spec, steps=3, bs=4, lr=0.01):
    fluid.optimizer.Adam(learning_rate=lr).minimize(spec.loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    batch = spec.synthetic_batch(bs)
    losses = []
    for _ in range(steps):
        (lv,) = exe.run(feed=batch, fetch_list=[spec.loss])
        losses.append(float(np.ravel(lv)[0]))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    return losses


def test_lenet5_trains():
    _train(models.lenet5(), lr=0.001)


def test_resnet_cifar10_trains():
    _train(models.resnet_cifar10(depth=8))


def test_resnet_imagenet_builds_and_trains_small():
    spec = models.resnet_imagenet(depth=18, class_num=10, img_shape=(3, 32, 32))
    _train(spec, bs=2)


def test_vgg16_trains():
    _train(models.vgg16(), bs=2)


def test_transformer_trains():
    spec = models.transformer(models.TransformerConfig(
        src_vocab_size=64, trg_vocab_size=64, max_length=16,
        n_layer=2, n_head=4, d_model=32, d_inner=64,
    ))
    _train(spec, lr=0.003)


def test_transformer_decoder_is_causal():
    """Perturbing a FUTURE target token must not change logits at earlier
    decoder positions (guards the causal mask; a broken mask trains fine on
    a memorizable batch, so loss-based tests cannot catch it)."""
    spec = models.transformer(models.TransformerConfig(
        src_vocab_size=32, trg_vocab_size=32, max_length=8,
        n_layer=1, n_head=2, d_model=16, d_inner=32, dropout=0.0,
    ))
    logits = spec.extras["logits"]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    batch = spec.synthetic_batch(2)
    (base,) = exe.run(feed=batch, fetch_list=[logits])
    batch2 = {k: v.copy() for k, v in batch.items()}
    batch2["trg_word"][:, 5] = (batch2["trg_word"][:, 5] % 30) + 1
    (pert,) = exe.run(feed=batch2, fetch_list=[logits])
    # positions 0..4 see only tokens < 5: must be bit-identical
    np.testing.assert_array_equal(base[:, :5, :], pert[:, :5, :])
    # position >= 5 must actually change (mask isn't just blocking everything)
    assert np.abs(base[:, 5:, :] - pert[:, 5:, :]).max() > 0


def test_transformer_fuse_qkv_parity():
    """fuse_qkv=True (one [d,3d] qkv matmul / [d,2d] kv matmul) must be
    numerically identical to the three separate projections: build both,
    stitch the unfused weights into the fused layout, compare logits."""
    kw = dict(src_vocab_size=32, trg_vocab_size=32, max_length=8,
              n_layer=1, n_head=2, d_model=16, d_inner=32, dropout=0.0)
    exe = fluid.Executor(fluid.CPUPlace())

    spec_u = models.transformer(models.TransformerConfig(fuse_qkv=False, **kw))
    exe.run(fluid.default_startup_program())
    batch = spec_u.synthetic_batch(2)
    (base,) = exe.run(feed=batch, fetch_list=[spec_u.extras["logits"]])
    scope_u = fluid.global_scope()

    main, startup = fluid.Program(), fluid.Program()
    scope_f = fluid.Scope()
    with fluid.scope_guard(scope_f), fluid.program_guard(main, startup):
        spec_f = models.transformer(models.TransformerConfig(fuse_qkv=True, **kw))
        exe.run(startup)
        # copy shared-name params; stitch q/k/v -> qkv and k/v -> kv
        for name in scope_f.local_var_names():
            if scope_u.has_var(name) and scope_u.find_var(name) is not None:
                scope_f.set_var(name, np.asarray(scope_u.find_var(name)))
        for name in list(scope_f.local_var_names()):
            for fused, parts in (("_qkv", "qkv"), ("_kv", "kv")):
                if name.endswith(f"{fused}_w"):
                    stem = name[: -len(f"{fused}_w")]
                    scope_f.set_var(name, np.concatenate(
                        [np.asarray(scope_u.find_var(f"{stem}_{p}_w"))
                         for p in parts], axis=1))
                elif name.endswith(f"{fused}_b"):
                    stem = name[: -len(f"{fused}_b")]
                    scope_f.set_var(name, np.concatenate(
                        [np.asarray(scope_u.find_var(f"{stem}_{p}_b"))
                         for p in parts], axis=0))
        (fused,) = exe.run(program=main, feed=batch,
                           fetch_list=[spec_f.extras["logits"]])
    np.testing.assert_allclose(base, fused, rtol=1e-5, atol=1e-5)


def test_transformer_masks_ignore_pad():
    """Loss is averaged over non-pad tokens only: doubling padding must not
    change a zero-dropout model's loss scale wildly (sanity on masking)."""
    spec = models.transformer(models.TransformerConfig(
        src_vocab_size=32, trg_vocab_size=32, max_length=8,
        n_layer=1, n_head=2, d_model=16, d_inner=32, dropout=0.0,
    ))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    batch = spec.synthetic_batch(4)
    (tc,) = exe.run(feed=batch, fetch_list=[spec.metrics["token_count"]])
    lbl = batch["lbl_word"]
    assert int(np.ravel(tc)[0]) == int((lbl != 0).sum())


def test_transformer_fused_smooth_ce_parity():
    """fuse_smooth_ce=True (smoothing folded into softmax_with_cross_entropy,
    no [B,S,V] label tensors) must match the reference-shaped one_hot ->
    label_smooth -> soft-label CE chain: same loss and same gradients,
    checked over a short SGD trajectory with identical seeds."""
    kw = dict(src_vocab_size=48, trg_vocab_size=48, max_length=8,
              n_layer=1, n_head=2, d_model=16, d_inner=32, dropout=0.0,
              label_smooth_eps=0.1)

    def run(fused):
        fluid.reset_default_env()
        fluid.default_main_program().random_seed = 7
        fluid.default_startup_program().random_seed = 7
        spec = models.transformer(
            models.TransformerConfig(fuse_smooth_ce=fused, **kw))
        fluid.optimizer.SGDOptimizer(learning_rate=0.01).minimize(spec.loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        batch = spec.synthetic_batch(2, seed=3)
        return [
            float(np.ravel(np.asarray(exe.run(
                feed=batch, fetch_list=[spec.loss])[0]))[0])
            for _ in range(3)
        ]

    ref, fused = run(False), run(True)
    np.testing.assert_allclose(ref, fused, rtol=1e-5, atol=1e-6)
