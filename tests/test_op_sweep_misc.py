"""Per-op sweep: misc family (reference: test_cos_sim_op.py, test_selu_op.py,
test_modified_huber_loss_op.py, test_add_position_encoding_op.py,
test_conv_shift_op.py, test_similarity_focus_op.py, test_random_crop_op.py,
test_hash_op.py, test_minus_op.py, test_fill_op.py over the matching
operators/*.cc)."""

import numpy as np

import paddle_tpu as fluid
from op_test import OpTest


def _rand(shape, seed=0, lo=-2.0, hi=2.0):
    return np.random.RandomState(seed).uniform(lo, hi, shape).astype("float32")


def test_cos_sim():
    x = _rand((5, 8), seed=1)
    y = _rand((5, 8), seed=2)
    xd, yd = x.astype(np.float64), y.astype(np.float64)
    xn = np.sqrt((xd * xd).sum(1, keepdims=True))
    yn = np.sqrt((yd * yd).sum(1, keepdims=True))
    want = (xd * yd).sum(1, keepdims=True) / (xn * yn)

    class T(OpTest):
        op_type = "cos_sim"

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"Out": want.astype("float32"), "XNorm": xn.astype("float32"),
                 "YNorm": yn.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X", "Y"], "Out", max_relative_error=0.02)


def test_cos_sim_broadcast_y():
    x = _rand((6, 4), seed=3)
    y = _rand((1, 4), seed=4)
    xd, yd = x.astype(np.float64), y.astype(np.float64)
    xn = np.sqrt((xd * xd).sum(1, keepdims=True))
    yn = np.sqrt((yd * yd).sum(1, keepdims=True))
    want = (xd * yd).sum(1, keepdims=True) / (xn * yn)

    class T(OpTest):
        op_type = "cos_sim"

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"Out": want.astype("float32"), "XNorm": xn.astype("float32"),
                 "YNorm": yn.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)


def test_minus():
    x, y = _rand((3, 4), seed=5), _rand((3, 4), seed=6)

    class T(OpTest):
        op_type = "minus"

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"Out": x - y}
    t.check_output()
    t.check_grad(["X", "Y"], "Out")


def test_fill():
    vals = list(range(6))

    class T(OpTest):
        op_type = "fill"

    t = T()
    t.inputs = {}
    t.attrs = {"shape": [2, 3], "value": [float(v) for v in vals],
               "dtype": int(fluid.core.DataType.INT32)}
    t.outputs = {"Out": np.arange(6, dtype="int32").reshape(2, 3)}
    t.check_output()


def test_selu():
    x = _rand((4, 5), seed=7)
    x = np.where(np.abs(x) < 0.05, 0.5, x).astype("float32")  # avoid the kink
    scale, alpha = 1.0507009873554805, 1.6732632423543772
    xd = x.astype(np.float64)
    want = scale * np.where(xd > 0, xd, alpha * (np.exp(xd) - 1.0))

    class T(OpTest):
        op_type = "selu"

    t = T()
    t.inputs = {"X": x}
    t.outputs = {"Out": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X"], "Out", max_relative_error=0.01)


def test_modified_huber_loss():
    x = _rand((8, 1), seed=8)
    y = np.random.RandomState(9).randint(0, 2, (8, 1)).astype("float32")
    inter = (2.0 * y - 1.0) * x
    want = np.where(inter < -1.0, -4.0 * inter,
                    np.where(inter < 1.0, (1.0 - inter) ** 2, 0.0))

    class T(OpTest):
        op_type = "modified_huber_loss"

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"IntermediateVal": inter, "Out": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_add_position_encoding():
    n, l, d = 2, 5, 8
    x = _rand((n, l, d), seed=10)
    alpha, beta = 0.7, 1.3
    half = d // 2
    pos = np.arange(l, dtype=np.float64)[:, None]
    k = np.arange(half, dtype=np.float64)[None, :]
    val = pos / np.power(10000.0, k / (half - 1))
    enc = np.concatenate([np.sin(val), np.cos(val)], axis=-1)
    want = alpha * x.astype(np.float64) + beta * enc[None]

    class T(OpTest):
        op_type = "add_position_encoding"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"alpha": alpha, "beta": beta}
    t.outputs = {"Out": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X"], "Out", max_relative_error=0.01)


def test_conv_shift():
    b, m, n = 3, 7, 3
    x = _rand((b, m), seed=11)
    y = _rand((b, n), seed=12)
    half = (n - 1) // 2
    want = np.zeros((b, m), dtype=np.float64)
    for i in range(b):
        for j in range(m):
            for k in range(n):
                want[i, j] += x[i, (j + k - half) % m] * y[i, k]

    class T(OpTest):
        op_type = "conv_shift"

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"Out": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X", "Y"], "Out", max_relative_error=0.02)


def _similarity_focus_ref(x, axis, indexes):
    """Direct port of the reference greedy algorithm (similarity_focus_op.h)."""
    out = np.zeros_like(x)
    b, d1, d2, d3 = x.shape
    for i in range(b):
        for index in indexes:
            if axis == 1:
                sl = x[i, index]  # [d2, d3]
                order = np.argsort(-sl.ravel(), kind="stable")
                tag2 = np.zeros(d2, bool)
                tag3 = np.zeros(d3, bool)
                cnt = 0
                for flat in order:
                    r, c = flat // d3, flat % d3
                    if tag2[r] or tag3[c]:
                        continue
                    tag2[r] = tag3[c] = True
                    out[i, :, r, c] = 1
                    cnt += 1
                    if cnt == min(d2, d3):
                        break
    return out


def test_similarity_focus():
    x = _rand((2, 3, 4, 5), seed=13, lo=0.0, hi=1.0)
    want = _similarity_focus_ref(x, 1, [0, 2])

    class T(OpTest):
        op_type = "similarity_focus"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"axis": 1, "indexes": [0, 2]}
    t.outputs = {"Out": want}
    t.check_output()


def test_random_crop():
    x = _rand((4, 3, 10, 10), seed=14)
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.layers.data(name="x", shape=[3, 10, 10], dtype="float32")
        out = fluid.layers.random_crop(xv, shape=[3, 6, 6])
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(program=prog, feed={"x": x}, fetch_list=[out])
    assert got.shape == (4, 3, 6, 6)
    # every cropped instance must be a contiguous window of the input
    for i in range(4):
        found = False
        for oy in range(5):
            for ox in range(5):
                if np.array_equal(got[i], x[i, :, oy:oy + 6, ox:ox + 6]):
                    found = True
        assert found, f"instance {i} is not a window of the input"


def test_hash():
    ids = np.random.RandomState(15).randint(0, 100, (6, 2)).astype("int64")

    class T(OpTest):
        op_type = "hash"

    t = T()
    t.inputs = {"X": ids}
    t.attrs = {"num_hash": 4, "mod_by": 10000}
    t.outputs = {"Out": np.zeros((6, 4, 1), dtype="int64")}  # shape only
    prog, startup, feed, _, out_names = t._build()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.program_guard(prog, startup):
        (got,) = exe.run(program=prog, feed=feed,
                         fetch_list=[out_names["Out"][0]])
    assert got.shape == (6, 4, 1)
    assert got.min() >= 0 and got.max() < 10000
    # deterministic
    with fluid.program_guard(prog, startup):
        (again,) = exe.run(program=prog, feed=feed,
                           fetch_list=[out_names["Out"][0]])
    np.testing.assert_array_equal(got, again)
    # equal rows hash equal, different rows (whp) differ
    ids2 = ids.copy()
    ids2[0] = ids[1]
    feed2 = dict(feed)
    feed2[list(feed)[0]] = ids2
    with fluid.program_guard(prog, startup):
        (got2,) = exe.run(program=prog, feed=feed2,
                          fetch_list=[out_names["Out"][0]])
    np.testing.assert_array_equal(got2[0], got2[1])
