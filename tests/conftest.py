"""Test configuration: force an 8-device virtual CPU platform BEFORE jax
imports, so sharding tests exercise a multi-chip mesh without TPU hardware
(mirrors the reference's strategy of testing multi-device graphs on CPU
places, e.g. broadcast_op_handle_test.cc)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

# A sitecustomize module may have registered an accelerator plugin before
# this conftest ran (so the env var alone is too late); pin the platform
# through jax.config, which wins as long as no backend is initialized yet.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'` (ROADMAP.md): heavy multiprocess chaos /
    # long-soak tests opt out with `slow`; `chaos` tags the
    # fault-injection resilience suite so it can be run alone
    # (`-m chaos`).  `timeout` is pytest-timeout's marker when that
    # plugin is present; registering it here keeps the suite
    # warning-clean when it isn't.
    config.addinivalue_line(
        "markers",
        "slow: heavy multiprocess/long tests, excluded from tier-1 "
        "(-m 'not slow')")
    config.addinivalue_line(
        "markers", "chaos: fault-injection resilience tests")
    config.addinivalue_line(
        "markers", "timeout(seconds): per-test timeout (pytest-timeout)")


@pytest.fixture
def host_devices():
    """Factory fixture for chip-less SPMD tests: ``host_devices(n)``
    returns `n` virtual CPU devices for a device mesh.

    ``--xla_force_host_platform_device_count`` only takes effect BEFORE
    the jax backend initializes, so this conftest already forces 8
    devices at import time (above).  The fixture configures the flag
    itself in the one window where that is still possible (jax not yet
    imported — e.g. a test subprocess importing this conftest fresh)
    and otherwise validates the initialized platform, SKIPPING when it
    came up with fewer devices than the test needs (a real accelerator
    platform pinned first, or a host that overrode XLA_FLAGS) — a mesh
    test must never hard-fail an environment it cannot reconfigure."""
    import sys

    def _get(n):
        if "jax" not in sys.modules:  # pragma: no cover — conftest
            flags = os.environ.get("XLA_FLAGS", "")  # imports jax above
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags
                    + f" --xla_force_host_platform_device_count={n}")
        import jax as _jax

        devs = _jax.devices()
        if len(devs) < n:
            pytest.skip(
                f"needs {n} devices but the platform already "
                f"initialized with {len(devs)} — "
                "xla_force_host_platform_device_count cannot be "
                "re-applied after backend init")
        return devs[:n]

    return _get


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Give every test a fresh default main/startup program and scope."""
    import paddle_tpu as fluid
    from paddle_tpu.core import framework
    from paddle_tpu.core import scope as scope_mod

    prev_main = framework.switch_main_program(fluid.Program())
    prev_startup = framework.switch_startup_program(fluid.Program())
    prev_scope = scope_mod._current_scope
    scope_mod._current_scope = scope_mod.Scope()
    # fresh name counters too: generated names (fc_0.w_0, ...) must not
    # depend on how many layers earlier tests built — string-sorted name
    # lookups go wrong once a counter crosses 10 (fc_10 < fc_9)
    with framework.unique_name_guard():
        yield
    framework.switch_main_program(prev_main)
    framework.switch_startup_program(prev_startup)
    scope_mod._current_scope = prev_scope
