"""Serving-tier fault isolation (ISSUE 6): dispatcher supervision,
circuit breaker, per-sequence quarantine, KV-pool integrity watchdog,
deadline-aware shedding, and the FAULT_SERVE_* chaos suite.

Acceptance pinned here:
(a) a dispatch raise fails ONLY that batch's futures (typed
    EngineInternalError naming the cause) while the dispatcher survives:
    the chaos run's pass count is the fault-free count minus the
    poisoned batch;
(b) a dispatcher thread that dies outside the protected region is
    restarted by the supervisor with the queue preserved;
(c) breaker_threshold consecutive internal errors open the circuit
    breaker (submit fails fast with EngineUnhealthyError) until a
    cool-down probe succeeds;
(d) FAULT_SERVE_NAN_SEQ evicts exactly the poisoned sequence
    (NonFiniteSequenceError, pages freed) while survivors stay
    token-identical to the full_decode oracle — and the per-step finite
    check is ONE fused jit call per step, never per sequence;
(e) any exception out of a prefill/decode step frees the stepping
    sequences' pages before propagating (zero net page delta);
(f) FAULT_SERVE_LEAK_PAGES is detected by check_invariants() and
    repaired by reclaim_orphans() via the loop's check_every watchdog;
(g) a queue saturated with slow requests sheds a tight-deadline submit
    immediately (no queue wait) and accepts it again once drained;
(h) close() surfaces a dispatcher that outlived its join as
    stats()["close_timed_out"] instead of returning silently.
"""

import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.resilience import faultinject
from paddle_tpu.serving import (
    ContinuousBatchingLoop,
    DecodeConfig,
    DecodeRequest,
    Engine,
    EngineConfig,
    EngineInternalError,
    EngineUnhealthyError,
    KVCachePool,
    NonFiniteSequenceError,
    RequestTimeoutError,
    full_decode,
    init_decode_params,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every test starts and ends with no armed serving faults."""
    faultinject.reset()
    yield
    for k in ("FAULT_SERVE_DISPATCH_RAISE", "FAULT_SERVE_NAN_SEQ",
              "FAULT_SERVE_LEAK_PAGES", "FAULT_SERVE_SLOW_STEP_MS",
              "FAULT_SERVE_PREFIX_CORRUPT", "FAULT_SERVE_SPILL_CORRUPT",
              "FAULT_SERVE_SPILL_DROP"):
        os.environ.pop(k, None)
    faultinject.reset()


def _wait_until(pred, timeout=5.0):
    t0 = time.perf_counter()
    while not pred():
        if time.perf_counter() - t0 > timeout:
            raise AssertionError("condition not reached in time")
        time.sleep(0.005)


class _EchoBackend:
    """Fast backend: y = 2x, optional per-call delay/failure toggle."""

    feed_names = ["x"]
    fetch_names = ["y"]
    meta: dict = {}

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s
        self.fail = False
        self.calls = 0

    def __call__(self, feed):
        self.calls += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise RuntimeError("backend exploded")
        return [np.asarray(feed["x"]) * 2.0]


class _GatedBackend:
    """Backend whose dispatch blocks until released."""

    feed_names = ["x"]
    fetch_names = ["y"]
    meta: dict = {}

    def __init__(self):
        self.gate = threading.Event()
        self.calls = 0

    def __call__(self, feed):
        self.calls += 1
        assert self.gate.wait(10.0), "test gate never released"
        return [np.asarray(feed["x"]) * 2.0]


def _feed(v=1.0, rows=1):
    return {"x": np.full((rows, 2), v, np.float32)}


# -- (a) dispatch raise: batch-level blast radius -----------------------

def test_dispatch_raise_fails_only_poisoned_batch():
    def run_workload():
        eng = Engine(_EchoBackend(),
                     config=EngineConfig(buckets=(1,), max_wait_s=0.0))
        futs = [eng.submit(_feed(i)) for i in range(8)]
        passed, errors = 0, []
        for f in futs:
            try:
                f.result(timeout=10)
                passed += 1
            except Exception as e:  # noqa: BLE001
                errors.append(e)
        stats = eng.stats()
        alive = eng._thread.is_alive()
        eng.close()
        return passed, errors, stats, alive

    fault_free, errors, _, _ = run_workload()
    assert fault_free == 8 and not errors

    faultinject.reset()
    os.environ["FAULT_SERVE_DISPATCH_RAISE"] = "1"
    passed, errors, stats, alive = run_workload()
    # pass count == fault-free minus ONLY the poisoned batch (1-bucket
    # ladder: one batch = one request)
    assert passed == fault_free - 1
    assert len(errors) == 1
    assert isinstance(errors[0], EngineInternalError)
    assert "dispatch raise" in str(errors[0])  # names the cause
    assert isinstance(errors[0].cause, RuntimeError)
    assert stats["internal_errors"] == 1
    assert stats["breaker_trips"] == 0  # one error: below the threshold
    assert alive  # the dispatcher survived the poisoned batch


# -- (b) dispatcher thread death: supervisor restart --------------------

def test_dispatcher_death_restarts_with_queue_preserved():
    os.environ["FAULT_SERVE_DISPATCH_RAISE"] = "thread"
    eng = Engine(_EchoBackend(),
                 config=EngineConfig(buckets=(1,), max_wait_s=0.0))
    futs = [eng.submit(_feed(i)) for i in range(4)]
    # the thread died at some cycle boundary; the supervisor restarted
    # it and every queued request still completes (generous timeout: it
    # only guards against deadlock, and a loaded CI box can starve the
    # restarted dispatcher for seconds)
    for i, f in enumerate(futs):
        np.testing.assert_array_equal(
            f.result(timeout=30)[0], np.full((1, 2), 2.0 * i, np.float32))
    stats = eng.stats()
    assert stats["dispatcher_restarts"] == 1
    assert eng._thread.is_alive()
    assert eng.health()["dispatcher_alive"]
    eng.close()


# -- (c) circuit breaker ------------------------------------------------

def test_circuit_breaker_trips_fast_fails_and_recovers():
    backend = _EchoBackend()
    backend.fail = True
    eng = Engine(backend, config=EngineConfig(
        buckets=(1,), max_wait_s=0.0,
        breaker_threshold=2, breaker_cooldown_s=0.25))
    f1 = eng.submit(_feed())
    f2 = eng.submit(_feed())
    for f in (f1, f2):
        with pytest.raises(EngineInternalError, match="exploded"):
            f.result(timeout=10)
    # 2 consecutive failures == threshold: the breaker is OPEN
    h = eng.health()
    assert h["state"] == "BROKEN"
    assert h["breaker"]["state"] == "open"
    assert h["breaker"]["last_error"] and "exploded" in h["breaker"]["last_error"]
    with pytest.raises(EngineUnhealthyError, match="breaker"):
        eng.submit(_feed())
    # cool-down: half-open, a probe is admitted; a healthy backend
    # closes the breaker
    time.sleep(0.3)
    assert eng.health()["breaker"]["state"] == "half_open"
    backend.fail = False
    out = eng.infer(_feed(3.0), timeout=None)
    np.testing.assert_array_equal(out[0], np.full((1, 2), 6.0, np.float32))
    h = eng.health()
    assert h["state"] == "SERVING"
    assert h["breaker"]["state"] == "closed"
    assert h["breaker"]["consecutive_errors"] == 0
    assert eng.stats()["breaker_trips"] == 1
    assert h["last_dispatch_age_s"] is not None
    eng.close()


def test_breaker_reprobe_failure_retrips():
    backend = _EchoBackend()
    backend.fail = True
    eng = Engine(backend, config=EngineConfig(
        buckets=(1,), max_wait_s=0.0,
        breaker_threshold=1, breaker_cooldown_s=0.2))
    with pytest.raises(EngineInternalError):
        eng.infer(_feed())
    with pytest.raises(EngineUnhealthyError):
        eng.submit(_feed())
    time.sleep(0.25)  # half-open; the probe fails -> re-trip
    with pytest.raises(EngineInternalError):
        eng.infer(_feed())
    with pytest.raises(EngineUnhealthyError):
        eng.submit(_feed())
    assert eng.stats()["breaker_trips"] == 2
    eng.close()


# -- health() -----------------------------------------------------------

def test_health_states_and_snapshot():
    backend = _GatedBackend()
    eng = Engine(backend, config=EngineConfig(
        buckets=(1,), max_wait_s=0.0, queue_depth=5))
    h = eng.health()
    assert h["state"] == "SERVING"
    assert h["queue_depth"] == 0 and h["queue_capacity"] == 5
    assert h["dispatcher_alive"] and not h["close_timed_out"]
    assert h["pool"] is None
    # saturate the queue to >= 80%: DEGRADED (still admitting)
    eng.submit(_feed())
    _wait_until(lambda: backend.calls == 1)  # in-flight, queue empty
    for _ in range(4):
        eng.submit(_feed())
    assert eng.health()["state"] == "DEGRADED"
    backend.gate.set()
    assert eng.drain(timeout=10.0)
    assert eng.health()["state"] == "DRAINING"
    eng.close()

    # a pool attached for utilization reporting
    pool = KVCachePool(num_pages=4, page_size=2, num_layers=1,
                       num_heads=1, head_dim=4)
    pool.allocate(0)
    pool.append_token([0])
    eng2 = Engine(_EchoBackend(), config=EngineConfig(buckets=(1,)))
    eng2.attach_pool(pool)
    assert eng2.health()["pool"]["used_pages"] == 1
    assert eng2.health()["pool"]["utilization"] == 0.25
    eng2.close()


def test_health_exported_through_observability_gauges():
    from paddle_tpu import observability as obs

    obs.reset()
    fluid.set_flags({"FLAGS_observability": True})
    try:
        eng = Engine(_EchoBackend(), config=EngineConfig(buckets=(1,)))
        eng.infer(_feed())
        assert eng.health()["state"] == "SERVING"
        eng.close()
        snap = obs.default_registry().snapshot()["metrics"]
        by_name = {m["name"]: m for m in snap}
        assert "paddle_tpu_serving_health_state" in by_name
        assert by_name["paddle_tpu_serving_health_state"]["series"][0][
            "value"] == 0  # SERVING
        assert "paddle_tpu_serving_breaker_open" in by_name
    finally:
        fluid.set_flags({"FLAGS_observability": False})
        obs.reset()


# -- (g) deadline-aware shedding (satellite) ----------------------------

def test_deadline_shedding_rejects_immediately_then_readmits():
    backend = _EchoBackend(delay_s=0.05)
    eng = Engine(backend, config=EngineConfig(buckets=(1,), max_wait_s=0.0))
    eng.infer(_feed())  # warm: one observed batch latency (~50ms)
    # saturate: 6 slow requests ahead -> ~0.3s of queued work
    futs = [eng.submit(_feed()) for _ in range(6)]
    t0 = time.perf_counter()
    with pytest.raises(RequestTimeoutError, match="shed"):
        eng.submit(_feed(), timeout=0.01)
    elapsed = time.perf_counter() - t0
    assert elapsed < 0.04, f"shed decision took {elapsed:.3f}s (queue wait?)"
    assert eng.stats()["shed"] == 1
    for f in futs:
        f.result(timeout=30)
    # drained: the same tight-ish deadline is admitted again
    _wait_until(lambda: eng.queue_depth() == 0)
    out = eng.infer(_feed(5.0), timeout=5.0)
    np.testing.assert_array_equal(out[0], np.full((1, 2), 10.0, np.float32))
    assert eng.stats()["shed"] == 1  # no new shed
    eng.close()


def test_no_shedding_without_deadline_or_evidence():
    backend = _EchoBackend(delay_s=0.02)
    eng = Engine(backend, config=EngineConfig(buckets=(1,), max_wait_s=0.0))
    # no latency observed yet: even a tight deadline is admitted (it
    # may expire in queue, but it is never shed on a guess)
    f = eng.submit(_feed(), timeout=5.0)
    f.result(timeout=10)
    # deadline-less requests are never shed no matter the queue
    futs = [eng.submit(_feed()) for _ in range(5)]
    for f in futs:
        f.result(timeout=30)
    assert eng.stats()["shed"] == 0
    eng.close()


# -- (h) close timeout surfaces (satellite) -----------------------------

def test_close_timed_out_flag(monkeypatch):
    monkeypatch.setattr(Engine, "_JOIN_TIMEOUT_S", 0.2)
    backend = _GatedBackend()  # never released before close
    eng = Engine(backend, config=EngineConfig(buckets=(1,), max_wait_s=0.0))
    f = eng.submit(_feed())
    _wait_until(lambda: backend.calls == 1)
    eng.close(timeout=0.05)  # drain cannot finish: backend is stuck
    assert eng.stats()["close_timed_out"] is True
    assert eng.health()["close_timed_out"] is True
    backend.gate.set()  # release: the stuck batch still completes
    np.testing.assert_array_equal(
        f.result(timeout=5.0)[0], np.full((1, 2), 2.0, np.float32))
    eng._thread.join(timeout=5.0)
    assert not eng._thread.is_alive()


# -- decode: per-sequence quarantine ------------------------------------

def _decode_setup(seed=7, n_layer=2):
    cfg = DecodeConfig(vocab_size=41, d_model=16, n_head=2,
                       n_layer=n_layer, d_inner=32, max_length=32)
    params = init_decode_params(cfg, seed=seed)
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (4, 2, 3)]
    pool = KVCachePool(num_pages=24, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim)
    return cfg, params, prompts, pool


def test_nan_seq_quarantine_evicts_one_survivors_match_oracle():
    cfg, params, prompts, pool = _decode_setup()
    oracles = [full_decode(params, cfg, p, 4)[0] for p in prompts]
    os.environ["FAULT_SERVE_NAN_SEQ"] = "1@1"  # seq 1, first decode step
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3)
    results = loop.run([DecodeRequest(p, 4) for p in prompts])
    assert loop.quarantined == 1
    assert isinstance(results[1].error, NonFiniteSequenceError)
    assert results[1].error.seq_id == 1 and results[1].error.step == 1
    # survivors are token-identical to the per-sequence oracle
    for i in (0, 2):
        assert results[i].error is None
        assert results[i].tokens == oracles[i]
    # the evicted sequence's pages returned to the pool
    assert pool.free_pages == pool.num_pages
    assert pool.check_invariants()["ok"]


def test_prefix_corrupt_quarantined_evicted_batchmates_survive():
    """FAULT_SERVE_PREFIX_CORRUPT (ISSUE 11): a cached prefix page goes
    bad at reuse — the sequence served the poisoned prefix quarantines
    (NonFiniteSequenceError), the poisoned chain is INVALIDATED so it
    can never be served again, batch-mates decode on oracle-identical,
    and a later same-prefix request re-prefills clean."""
    from paddle_tpu.serving import PrefixCache

    cfg = DecodeConfig(vocab_size=41, d_model=16, n_head=2, n_layer=2,
                       d_inner=32, max_length=48)
    params = init_decode_params(cfg, seed=21)
    rng = np.random.RandomState(21)
    shared = rng.randint(1, cfg.vocab_size, size=12).tolist()
    owner = shared + rng.randint(1, cfg.vocab_size, size=2).tolist()
    victim = shared + rng.randint(1, cfg.vocab_size, size=3).tolist()
    # bystander: 5 prompt + 3 new = exactly 2 pages, all claimed at its
    # prefill — it never allocates after the quarantine frees pages
    bystander = rng.randint(1, cfg.vocab_size, size=5).tolist()
    pool = KVCachePool(num_pages=48, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim)
    cache = PrefixCache(pool)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=2,
                                  prefix_cache=cache, check_every=1)
    # warm the cache
    r0 = loop.run([DecodeRequest(owner, 3)])
    assert r0[0].error is None
    # arm: the victim's attach poisons the first matched page
    os.environ["FAULT_SERVE_PREFIX_CORRUPT"] = "1"
    res = loop.run([DecodeRequest(victim, 3),
                    DecodeRequest(bystander, 3)])
    assert loop.quarantined == 1
    assert isinstance(res[0].error, NonFiniteSequenceError)
    want_b, _ = full_decode(params, cfg, bystander, 3)
    assert res[1].error is None and res[1].tokens == want_b
    # the poisoned chain was evicted from the cache...
    assert cache.stats()["invalidations"] >= 1
    # ...so a fresh same-prefix request MISSES and re-prefills clean,
    # matching the oracle (the corruption is gone, not resident)
    hits_before = loop.prefix_hits
    res3 = loop.run([DecodeRequest(list(victim), 3)])
    assert loop.prefix_hits == hits_before
    want_v, _ = full_decode(params, cfg, victim, 3)
    assert res3[0].error is None and res3[0].tokens == want_v
    # zero leaked pages, refcount invariants green
    cache.clear()
    assert pool.used_pages == 0
    assert pool.check_invariants()["ok"]
    assert loop.invariant_violations == 0


def test_nan_at_prefill_quarantines_only_offender():
    cfg, params, prompts, pool = _decode_setup(seed=3)
    oracles = [full_decode(params, cfg, p, 3)[0] for p in prompts]
    os.environ["FAULT_SERVE_NAN_SEQ"] = "0@0"  # seq 0 at the prefill pass
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3)
    results = loop.run([DecodeRequest(p, 3) for p in prompts])
    assert loop.quarantined == 1
    assert isinstance(results[0].error, NonFiniteSequenceError)
    assert results[0].tokens == []  # evicted before its first token
    for i in (1, 2):
        assert results[i].error is None
        assert results[i].tokens == oracles[i]
    assert pool.free_pages == pool.num_pages


def test_finite_check_is_one_fused_call_per_step():
    """The quarantine scan must be ONE batched rows_finite call per loop
    step ([B, V] in, [B] bool out) — never a per-sequence check.  The
    scan lives in the shared prefill scheduler (prefill_sched) since the
    fleet's prefill replica runs the same blast radius."""
    import paddle_tpu.serving.prefill_sched as psched

    cfg, params, prompts, pool = _decode_setup(seed=5)
    calls = []
    real = psched.rows_finite

    def counting(x):
        calls.append(np.asarray(x).shape)
        return real(x)

    psched.rows_finite, orig = counting, psched.rows_finite
    try:
        loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3)
        loop.run([DecodeRequest(p, 3) for p in prompts])
    finally:
        psched.rows_finite = orig
    assert len(calls) == loop.steps  # exactly one scan per step
    assert all(len(s) == 2 and s[1] == cfg.vocab_size for s in calls), \
        "scan must see the whole [B, V] logits batch at once"


# -- decode: exception-safe page release (satellite) --------------------

def test_decode_step_exception_frees_pages_before_propagating():
    import paddle_tpu.serving.generate as gen

    cfg, params, prompts, pool = _decode_setup(seed=11)
    real = gen.decode_step
    calls = [0]

    def flaky(*a, **k):
        calls[0] += 1
        if calls[0] == 2:
            raise RuntimeError("decode step exploded")
        return real(*a, **k)

    gen.decode_step, orig = flaky, gen.decode_step
    try:
        loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3)
        with pytest.raises(RuntimeError, match="decode step exploded"):
            loop.run([DecodeRequest(p, 4) for p in prompts])
    finally:
        gen.decode_step = orig
    # zero net page delta: everything claimed before the raise was freed
    assert pool.used_pages == 0
    assert pool.check_invariants()["ok"]


def test_mid_prefill_raise_zero_net_page_delta(monkeypatch):
    """The acknowledged hazard: a raise inside the admission/prefill
    window (pages already claimed by append_tokens) must free them."""
    cfg, params, prompts, pool = _decode_setup(seed=13)
    real = pool.write_kv
    calls = [0]

    def flaky(layer, pages, slots, k, v):
        calls[0] += 1
        if calls[0] == 2:  # layer 1 of the first prefill pass
            raise RuntimeError("mid-prefill write failed")
        return real(layer, pages, slots, k, v)

    monkeypatch.setattr(pool, "write_kv", flaky)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3)
    assert pool.used_pages == 0
    with pytest.raises(RuntimeError, match="mid-prefill"):
        loop.run([DecodeRequest(p, 4) for p in prompts])
    assert pool.used_pages == 0  # zero net delta
    assert pool.check_invariants()["ok"]


# -- KV-pool integrity watchdog -----------------------------------------

def test_check_invariants_clean_and_orphan_detection():
    pool = KVCachePool(num_pages=6, page_size=2, num_layers=1,
                       num_heads=1, head_dim=4)
    assert pool.check_invariants()["ok"]
    pool.allocate(0)
    pool.append_token([0])
    assert pool.check_invariants()["ok"]
    # orphan a page: not free, owned by nobody
    leaked = pool._free.pop()
    rep = pool.check_invariants()
    assert not rep["ok"]
    assert rep["orphaned_pages"] == [leaked]
    assert pool.reclaim_orphans() == 1
    assert pool.check_invariants()["ok"]
    assert pool.stats()["orphans_reclaimed"] == 1
    # reclaim is idempotent
    assert pool.reclaim_orphans() == 0
    pool.free_seq(0)
    assert pool.free_pages == pool.num_pages


def test_check_invariants_detects_double_owned_and_mismatch():
    pool = KVCachePool(num_pages=6, page_size=2, num_layers=1,
                       num_heads=1, head_dim=4)
    pool.allocate(0)
    pool.allocate(1)
    pool.append_token([0])
    pool.append_token([1])
    shared = pool._tables[0].pages[0]
    pool._tables[1].pages.append(shared)  # corruption: two owners
    rep = pool.check_invariants()
    assert not rep["ok"]
    assert shared in rep["double_owned_pages"]
    assert 1 in rep["length_mismatches"]  # seq 1: a whole spare page
    pool._tables[1].pages.pop()
    pool._tables[0].length = 99  # length beyond capacity
    rep = pool.check_invariants()
    assert 0 in rep["length_mismatches"]


def test_leak_pages_detected_and_repaired_by_watchdog():
    cfg, params, prompts, pool = _decode_setup(seed=17)
    oracles = [full_decode(params, cfg, p, 4)[0] for p in prompts]
    os.environ["FAULT_SERVE_LEAK_PAGES"] = "2"
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3,
                                  check_every=1)
    results = loop.run([DecodeRequest(p, 4) for p in prompts])
    assert loop.invariant_violations == 1
    assert loop.reclaimed_pages == 2
    # the leak cost nothing: all sequences completed, oracle-identical,
    # and the run ends with a clean pool and zero orphans
    for r, want in zip(results, oracles):
        assert r.error is None and r.tokens == want
    rep = pool.check_invariants()
    assert rep["ok"] and rep["orphaned_pages"] == []
    assert pool.used_pages == 0
    assert pool.stats()["orphans_reclaimed"] == 2


def test_watchdog_off_by_default_leak_stays_visible():
    cfg, params, prompts, pool = _decode_setup(seed=19)
    os.environ["FAULT_SERVE_LEAK_PAGES"] = "2"
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3)
    loop.run([DecodeRequest(p, 3) for p in prompts])
    # no watchdog: the leak persists and check_invariants names it
    rep = pool.check_invariants()
    assert not rep["ok"] and len(rep["orphaned_pages"]) == 2
    assert pool.used_pages == 2  # the leak, visible in accounting
    assert pool.reclaim_orphans() == 2
    assert pool.used_pages == 0


# -- observability wiring ----------------------------------------------

def test_fault_isolation_metrics_emitted_when_enabled():
    from paddle_tpu import observability as obs

    obs.reset()
    fluid.set_flags({"FLAGS_observability": True})
    try:
        backend = _EchoBackend()
        backend.fail = True
        eng = Engine(backend, config=EngineConfig(
            buckets=(1,), max_wait_s=0.0,
            breaker_threshold=1, breaker_cooldown_s=5.0))
        with pytest.raises(EngineInternalError):
            eng.infer(_feed())
        with pytest.raises(EngineUnhealthyError):
            eng.submit(_feed())
        eng.health()
        eng.close()

        cfg, params, prompts, pool = _decode_setup(seed=23)
        os.environ["FAULT_SERVE_NAN_SEQ"] = "1@1"
        os.environ["FAULT_SERVE_LEAK_PAGES"] = "1"
        ContinuousBatchingLoop(params, cfg, pool, max_batch=3,
                               check_every=1).run(
            [DecodeRequest(p, 3) for p in prompts])

        snap = obs.default_registry().snapshot()["metrics"]
        by_name = {m["name"]: m for m in snap}
        assert "paddle_tpu_serving_breaker_trips" in by_name
        assert "paddle_tpu_serving_health_state" in by_name
        assert "paddle_tpu_serving_pool_orphans_reclaimed" in by_name
        outcomes = {s["labels"].get("outcome")
                    for s in by_name["paddle_tpu_serving_requests"]["series"]}
        assert "rejected_breaker_open" in outcomes
        events = {s["labels"].get("event")
                  for s in by_name["paddle_tpu_serving_sequences"]["series"]}
        assert "quarantined" in events
    finally:
        fluid.set_flags({"FLAGS_observability": False})
        obs.reset()


# -- serve_bench --chaos ------------------------------------------------

def test_serve_bench_chaos_decode_gate(tmp_path, capsys):
    import json

    from tools.serve_bench import main as bench_main

    out = tmp_path / "chaos.json"
    rc = bench_main([
        "--mode", "decode", "--chaos", "--sequences", "5", "--max-new", "4",
        "--d-model", "16", "--vocab", "31", "--max-len", "32",
        "--pages", "32", "--page-size", "4", "--json", str(out),
    ])
    assert rc == 0
    result = json.loads(out.read_text())
    assert result["quarantined"] == 1
    assert result["reclaimed_pages"] == 2
    assert result["pages_leaked"] == 0
    assert result["invariants_ok"] == 1
    # the CI contract: chaos runs gate on zero leaked pages
    bank = tmp_path / "bank.json"
    bank.write_text(json.dumps({"pages_leaked": 0, "invariants_ok": 1}))
    rc = bench_main([
        "--mode", "decode", "--chaos", "--sequences", "5", "--max-new", "4",
        "--d-model", "16", "--vocab", "31", "--max-len", "32",
        "--pages", "32", "--page-size", "4",
        "--baseline", str(bank), "--gate",
    ])
    assert rc == 0
    capsys.readouterr()


def test_serve_bench_chaos_engine_smoke(tmp_path, capsys):
    import json

    from paddle_tpu import observability as obs
    from tools.serve_bench import main as bench_main

    rc = bench_main([
        "--model", "tiny", "--requests", "18", "--rate", "400",
        "--buckets", "1,2", "--batch-range", "1,2", "--chaos",
        "--obs-dir", str(tmp_path / "obs"),
    ])
    assert rc == 0
    result = json.loads(capsys.readouterr().out)
    # breaker_threshold consecutive batches were poisoned — enough to
    # TRIP the breaker (ISSUE 8: the flight recorder's dump trigger)
    assert result["internal_errors"] == 3
    assert result["breaker_trips"] == 1
    assert 3 <= result["poisoned_requests"] <= 6
    assert result["recovered_requests"] >= 1
    assert (result["recovered_requests"] + result["poisoned_requests"]
            + result["timeout_requests"] + result["shed_requests"]
            + result["breaker_rejected_requests"]
            == result["requests"])
    assert result["dispatcher_restarts"] == 0
    # the induced trip left a black box, and it holds the transition
    assert result["flight_dumps"] >= 1
    dump = result["artifacts"]["flight_dumps"][0]
    with open(dump) as f:
        events = [json.loads(ln) for ln in f][1:]
    assert "breaker_open" in {e["kind"] for e in events}
    # banking {"flight_dumps": 1} gates future chaos runs on the
    # artifact existing (same 0/2/3 contract as pages_leaked)
    bank = tmp_path / "bank.json"
    bank.write_text(json.dumps({"flight_dumps": 1,
                                "dispatcher_restarts": 0}))
    rc = bench_main([
        "--model", "tiny", "--requests", "18", "--rate", "400",
        "--buckets", "1,2", "--batch-range", "1,2", "--chaos",
        "--baseline", str(bank), "--gate",
    ])
    capsys.readouterr()
    assert rc == 0
    # serve_bench restored the observability flag it flipped on
    assert not obs.enabled()
    obs.reset()


# -- host KV tier chaos (ISSUE 18) ---------------------------------------

def _tiered_two_turns(fault=None, arm_before_turn=None):
    """One session, two turns, spilled to host between them.  `fault`
    is armed before turn `arm_before_turn` (1 = before the spill's
    park, 2 = before the resume's fetch).  Returns (outputs, oracle
    outputs, manager) with the manager already closed and leak-audited."""
    from paddle_tpu.serving import TieredSessionManager

    cfg = DecodeConfig(vocab_size=61, d_model=16, n_head=2, n_layer=2,
                       d_inner=32, max_length=64)
    params = init_decode_params(cfg, seed=12)
    pool = KVCachePool(num_pages=32, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim)
    mgr = TieredSessionManager(pool, host_bytes=1 << 26)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  session_manager=mgr)
    s = mgr.open_session()
    p1 = [5, 1, 2, 3, 4, 5, 6, 7, 8]
    outs, want = [], []
    for turn, extra in enumerate(([], [9, 10, 11]), start=1):
        if fault and arm_before_turn == turn:
            os.environ[fault] = "1"
            faultinject.reset()
        p = p1 if turn == 1 else p1 + outs[0] + extra
        (r,) = loop.run([DecodeRequest(prompt=list(p), max_new_tokens=4,
                                       session=s)])
        assert r.error is None, r.error
        outs.append(r.tokens)
        want.append(full_decode(params, cfg, p, 4)[0])
        if turn == 1:
            assert mgr.spill(s, wait=True) and s.state == "parked"
    st = mgr.stats()
    mgr.close()
    assert pool.used_pages == 0
    assert pool.check_invariants()["ok"]
    assert len(mgr.tier) == 0
    return outs, want, st


def test_spill_corrupt_rejected_session_reprefills_correctly():
    """FAULT_SERVE_SPILL_CORRUPT: the parked payload rots in host RAM.
    The resume's CRC verify rejects it (never imports garbage), the
    session re-prefills, and turn 2 is still token-identical."""
    outs, want, st = _tiered_two_turns(
        fault="FAULT_SERVE_SPILL_CORRUPT", arm_before_turn=1)
    assert outs == want
    assert st["re_prefills"] == 1
    assert st["tier"]["corrupt_rejected"] == 1
    assert st["resumed_host"] == 0  # the one resume fell back


def test_spill_drop_lost_payload_session_reprefills_correctly():
    """FAULT_SERVE_SPILL_DROP: the parked payload vanishes before the
    resume fetches it — typed SpillMissingError fallback, counted,
    and turn 2 still matches the oracle."""
    outs, want, st = _tiered_two_turns(
        fault="FAULT_SERVE_SPILL_DROP", arm_before_turn=2)
    assert outs == want
    assert st["re_prefills"] == 1
    assert st["tier"]["lost"] == 1
    assert st["resumed_host"] == 0


def test_tiered_turns_clean_baseline_no_reprefill():
    """The same scenario unarmed: the resume comes back from host with
    no fallback — the teeth arms above fail without their knobs."""
    outs, want, st = _tiered_two_turns()
    assert outs == want
    assert st["re_prefills"] == 0 and st["resumed_host"] == 1
    assert st["tier"]["corrupt_rejected"] == 0 and st["tier"]["lost"] == 0
