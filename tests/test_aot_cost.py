"""Chip-less TPU cost accounting (core/aot_tpu.py): AOT-compile against a
v5e topology with no TPU attached and read the TPU compiler's own cost
model.  This is the instrument behind the conv-epilogue bytes/step
acceptance: the fused kernel pair must cut HBM traffic >= 25% vs the
unfused XLA chain on ResNet-50 block shapes, verified WITHOUT a chip."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.core.aot_tpu import compile_tpu, tpu_cost_analysis


def _skip_if_no_topology():
    try:
        from paddle_tpu.core.aot_tpu import tpu_topology

        tpu_topology()
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(f"no chip-less TPU topology available: {e}")


def test_tpu_topology_cost_analysis_basic():
    """A trivial matmul compiles for v5e on the CPU host and reports the
    TPU cost model's keys."""
    _skip_if_no_topology()
    x = jax.ShapeDtypeStruct((512, 512), jnp.float32)
    ca = tpu_cost_analysis(lambda a: jnp.sum(a @ a.T), x)
    assert ca.get("bytes accessed", 0) > 0
    assert ca.get("flops", 0) >= 2 * 512 * 512 * 512


def test_conv_epilogue_bytes_reduction_on_resnet_block_shapes():
    """The acceptance number: fused conv-epilogue kernels (pallas fwd +
    analytic bwd) vs the unfused conv->bn->add->relu XLA chain, fwd+bwd
    at ResNet-50 block shapes (56x56, C=F=64, 3x3), two chained residual
    blocks so inter-block effects count.  TPU compiler cost model must
    show >= 25% fewer bytes accessed for the fused lowering."""
    _skip_if_no_topology()
    from paddle_tpu.kernels.conv_epilogue import make_conv_bn_act

    N, H, C, NBLK = 4, 56, 64, 2
    x = jax.ShapeDtypeStruct((N, H, H, C), jnp.float32)
    w = jax.ShapeDtypeStruct((3, 3, C, C), jnp.float32)
    g = jax.ShapeDtypeStruct((C,), jnp.float32)

    def chain_fused(x, ws, gs, bs):
        f = make_conv_bn_act(has_residual=True, stride=1, padding=1)
        h = x
        for i in range(NBLK):
            h, _, _ = f(h, ws[i], gs[i], bs[i], h)
        return jnp.sum(h)

    def chain_unfused(x, ws, gs, bs):
        h = x
        for i in range(NBLK):
            out = jax.lax.conv_general_dilated(
                h, ws[i], window_strides=(1, 1), padding=[(1, 1), (1, 1)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            mean = jnp.mean(out, axis=(0, 1, 2))
            var = jnp.mean(out * out, axis=(0, 1, 2)) - mean * mean
            inv = jax.lax.rsqrt(var + 1e-5)
            h = jax.nn.relu((out - mean) * inv * gs[i] + bs[i] + h)
        return jnp.sum(h)

    def bytes_of(fn):
        grad = jax.grad(fn, argnums=(0, 1, 2, 3))
        ca = tpu_cost_analysis(grad, x, [w] * NBLK, [g] * NBLK, [g] * NBLK)
        return ca["bytes accessed"]

    unfused = bytes_of(chain_unfused)
    fused = bytes_of(chain_fused)
    assert fused <= 0.75 * unfused, (
        f"fused conv epilogue bytes/step regressed: {fused:.3e} vs "
        f"unfused {unfused:.3e} (ratio {fused / unfused:.3f} > 0.75)")


def test_executor_cost_analysis_platform_tpu():
    """Executor.cost_analysis(platform='tpu') returns the chip program's
    bytes/step on a CPU host (TPU trace scope forced: NHWC/keep-bf16
    auto-resolution included)."""
    _skip_if_no_topology()
    import paddle_tpu as fluid
    from paddle_tpu import layers

    fluid.reset_default_env()
    x = layers.data("x", [16, 16, 16], dtype="float32")
    h = layers.fc(layers.pool2d(x, pool_size=16, pool_type="avg"), size=4)
    loss = layers.mean(h)
    fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xa = np.zeros((2, 16, 16, 16), "float32")
    ca = exe.cost_analysis(feed={"x": xa}, fetch_list=[loss],
                           platform="tpu")
    assert ca.get("bytes accessed", 0) > 0


def test_paged_attention_pallas_kills_gather_bytes():
    """ISSUE 5 acceptance: at transformer decode shapes the pallas
    ragged paged-attention path must eliminate the reference gather's
    O(B*S*D) bytes/step.  Both arms AOT-compile for v5e through the REAL
    TPU pipeline (so Mosaic must accept the page-walk kernel, not just
    the interpreter) and are priced by the TPU compiler's cost model.
    The pallas kernel's page-stream DMAs are driven by the SMEM page
    table and invisible to the XLA-level cost model, so the honest A/B
    charges the kernel its full analytic streaming traffic
    (attention_bytes_per_step) ON TOP of the measured custom-call bytes
    — and still must clear the floor.  The measured table is banked as
    AOT_COST_PAGED.json."""
    _skip_if_no_topology()
    import json
    import os

    from paddle_tpu.kernels.paged_attention import (
        attention_bytes_per_step,
        paged_decode_attention,
        pallas_paged_viable,
    )

    B, H, D, ps, maxp = 4, 8, 128, 16, 32  # 512 cached tokens/sequence
    assert pallas_paged_viable(ps, D)
    P = B * maxp
    q = jax.ShapeDtypeStruct((B, H, 1, D), jnp.float32)
    kp = jax.ShapeDtypeStruct((H, P, ps, D), jnp.float32)
    tb = jax.ShapeDtypeStruct((B, maxp), jnp.int32)
    ln = jax.ShapeDtypeStruct((B,), jnp.int32)

    def arm(impl):
        return tpu_cost_analysis(
            lambda q, kp, vp, tb, ln: paged_decode_attention(
                q, kp, vp, tb, ln, impl=impl),
            q, kp, kp, tb, ln)["bytes accessed"]

    ref = arm("reference")
    pal = arm("pallas")
    stream = attention_bytes_per_step("pallas", B, maxp, ps, H, D)
    # the contiguous [B, H, S, D] gather copy is gone from the XLA
    # program entirely: the paged custom call's XLA-visible traffic is
    # q/tables/output noise, not O(B*S*D)
    assert pal <= 0.05 * ref, (
        f"pallas paged XLA-visible bytes did not collapse: {pal:.3e} vs "
        f"reference {ref:.3e}")
    # charging the kernel's FULL analytic page-stream traffic on top,
    # the paged path still clears a >=2.5x bytes/step win
    assert pal + stream <= 0.4 * ref, (
        f"paged path bytes/step floor missed: {pal + stream:.3e} vs "
        f"reference {ref:.3e} (ratio {(pal + stream) / ref:.3f} > 0.4)")
    # the banked artifact stays consistent with what this tier measures
    banked_path = os.path.join(os.path.dirname(__file__), os.pardir,
                               "AOT_COST_PAGED.json")
    with open(banked_path) as f:
        banked = json.load(f)
    ab = banked["decode_shape_ab"]
    assert ab["floor"] == 0.4
    assert ab["ratio_with_analytic_stream"] <= ab["floor"]


def test_compile_tpu_full_pipeline_catches_more_than_export():
    """compile_tpu runs the whole XLA TPU pipeline (layout, fusion,
    memory budgeting) — the pallas conv kernel must survive it inside
    its advertised envelope (pallas_viable), not just the jax.export
    lowering gate.  This tier caught two real bugs export missed:
    Mosaic's 'non-native tiling' on unaligned tap windows, and
    interpret-mode pallas silently compiled into AOT-for-TPU modules."""
    _skip_if_no_topology()
    from paddle_tpu.kernels.conv_epilogue import conv_bn_act, pallas_viable

    # in-envelope: fp32 3x3 at the ResNet stage-1 shape (in-VMEM pad
    # path) and a bf16 1x1 (the keep-bf16 chip config's coverage)
    cases = [((2, 56, 56, 64), (3, 3, 64, 64), jnp.float32),
             ((2, 28, 28, 128), (1, 1, 128, 128), jnp.bfloat16)]
    for xs, ws, dt in cases:
        assert pallas_viable(xs[0], xs[1], xs[2], xs[3], ws[3], ws[0],
                             dtype=dt)
        args = (jax.ShapeDtypeStruct(xs, dt),
                jax.ShapeDtypeStruct(ws, dt),
                jax.ShapeDtypeStruct((ws[3],), jnp.float32),
                jax.ShapeDtypeStruct((ws[3],), jnp.float32))
        comp = compile_tpu(lambda *a: conv_bn_act(*a), *args)
        ca = comp.cost_analysis()
        ca = ca if isinstance(ca, dict) else ca[0]
        assert ca.get("bytes accessed", 0) > 0
    # out-of-envelope bf16 3x3 is reported non-viable, not a compile bomb
    assert not pallas_viable(2, 28, 28, 64, 64, 3, dtype=jnp.bfloat16)
