"""fused_bn_add_act: the one-op BN + residual + activation with a
recompute-tagged backward (ops/nn_ops.py _fused_bn_add_act; replaces the
reference's batch_norm_op.cu.cc + elementwise_add + relu dispatches).

The contract is NUMERICAL IDENTITY with the unfused chain — same losses,
same trained weights, same moving statistics — with the storage trade
happening purely inside jax.checkpoint."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _train(fused: bool, steps=4, with_residual=True, seed=11):
    fluid.reset_default_env()
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    x = layers.data("x", [4, 8, 8], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    conv = layers.conv2d(x, num_filters=4, filter_size=3, padding=1,
                         bias_attr=False,
                         param_attr=fluid.ParamAttr(name="w_conv"))
    res = x if with_residual else None
    if fused:
        h = layers.fused_bn_add_act(
            conv, res, act="relu",
            param_attr=fluid.ParamAttr(name="bn_scale"),
            bias_attr=fluid.ParamAttr(name="bn_bias"),
            moving_mean_name="bn_mean", moving_variance_name="bn_var")
    else:
        b = layers.batch_norm(conv, act=None,
                              param_attr=fluid.ParamAttr(name="bn_scale"),
                              bias_attr=fluid.ParamAttr(name="bn_bias"),
                              moving_mean_name="bn_mean",
                              moving_variance_name="bn_var")
        h = layers.relu(layers.elementwise_add(b, res) if res is not None
                        else b)
    pool = layers.pool2d(h, pool_size=8, pool_type="avg")
    pred = layers.fc(pool, size=3, act="softmax",
                     param_attr=fluid.ParamAttr(name="w_fc"))
    loss = layers.mean(layers.cross_entropy(pred, y))
    fluid.optimizer.MomentumOptimizer(
        learning_rate=0.1, momentum=0.9).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(5)
    xv = rng.randn(8, 4, 8, 8).astype("float32")
    yv = rng.randint(0, 3, size=(8, 1)).astype("int64")
    losses = [
        float(np.ravel(np.asarray(
            exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])[0]))[0])
        for _ in range(steps)
    ]
    scope = fluid.global_scope()
    state = {
        n: np.array(np.asarray(scope.find_var(n)))
        for n in ("w_conv", "bn_scale", "bn_bias", "bn_mean", "bn_var",
                  "w_fc")
    }
    return losses, state, (exe, pred, xv)


def _assert_matches(with_residual):
    ref_losses, ref_state, _ = _train(False, with_residual=with_residual)
    fus_losses, fus_state, _ = _train(True, with_residual=with_residual)
    np.testing.assert_allclose(ref_losses, fus_losses, rtol=1e-5, atol=1e-6)
    assert ref_losses[-1] < ref_losses[0]  # training actually moved
    for n in ref_state:
        np.testing.assert_allclose(
            ref_state[n], fus_state[n], rtol=1e-5, atol=1e-6,
            err_msg=f"state {n} diverged between fused and unfused")


def test_fused_matches_unfused_with_residual():
    _assert_matches(with_residual=True)


def test_fused_matches_unfused_without_residual():
    _assert_matches(with_residual=False)


def test_fused_op_is_recompute_tagged():
    fluid.reset_default_env()
    x = layers.data("x", [4, 8, 8], dtype="float32")
    layers.fused_bn_add_act(layers.conv2d(x, 4, 3, padding=1), x)
    ops = fluid.default_main_program().global_block().ops
    fused = [op for op in ops if op.type == "fused_bn_add_act"]
    assert len(fused) == 1
    assert fused[0].attr("@recompute@") is True


def test_fused_test_mode_uses_moving_stats():
    """for_test clone: normalize with the moving stats, no stat update —
    exercised through the inference-program path like batch_norm."""
    _, _, (exe, pred, xv) = _train(True, steps=3)
    infer = fluid.io.get_inference_program([pred])
    mean_before = np.array(
        np.asarray(fluid.global_scope().find_var("bn_mean")))
    (o1,) = exe.run(program=infer, feed={"x": xv}, fetch_list=[pred])
    (o2,) = exe.run(program=infer, feed={"x": xv}, fetch_list=[pred])
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               rtol=1e-6, atol=1e-7)  # deterministic
    np.testing.assert_array_equal(
        np.asarray(fluid.global_scope().find_var("bn_mean")), mean_before)


def test_resnet_fused_matches_unfused():
    """resnet_cifar10-scale end to end: fuse_bn=True and False give the
    same loss trajectory (the flagship model's default path is safe)."""
    from paddle_tpu import models

    def run(fuse_bn):
        fluid.reset_default_env()
        fluid.default_main_program().random_seed = 3
        fluid.default_startup_program().random_seed = 3
        img = layers.data("image", [3, 16, 16], dtype="float32")
        label = layers.data("label", [1], dtype="int64")
        s = _shortcut_block(img, fuse_bn)
        pool = layers.pool2d(s, pool_size=8, pool_type="avg")
        pred = layers.fc(pool, size=4, act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, label))
        fluid.optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(1)
        xv = rng.randn(8, 3, 16, 16).astype("float32")
        yv = rng.randint(0, 4, size=(8, 1)).astype("int64")
        return [
            float(np.ravel(np.asarray(exe.run(
                feed={"image": xv, "label": yv}, fetch_list=[loss])[0]))[0])
            for _ in range(4)
        ]

    def _shortcut_block(img, fuse_bn):
        return models.resnet.bottleneck(img, 8, 2, fuse_bn=fuse_bn)

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-6)


def test_flash_bwd_jaxlib_flag_accepted_cpu_fallback():
    """FLAGS_flash_bwd=jaxlib routes to the jax-shipped TPU kernel pair on
    TPU only; on CPU the flag is accepted and attention falls back to the
    recompute-jax path with unchanged numerics."""
    import jax.numpy as jnp

    from paddle_tpu.kernels.flash_attention import flash_attention

    q = jnp.asarray(np.random.RandomState(0).randn(1, 2, 16, 8),
                    jnp.float32)
    base = flash_attention(q, q, q, causal=True)
    fluid.set_flags({"FLAGS_flash_bwd": "jaxlib"})
    try:
        out = flash_attention(q, q, q, causal=True)
    finally:
        fluid.set_flags({"FLAGS_flash_bwd": "jax"})
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               rtol=1e-6, atol=1e-7)


def test_fused_bn_fuzz_parity_vs_composed_ops():
    """Seeded fuzz: random shapes / eps / momentum / residual presence /
    act, fwd + one SGD step, fused op vs the composed batch_norm +
    elementwise_add + relu chain.  20 cases."""
    rng = np.random.RandomState(123)
    for case in range(20):
        c = int(rng.choice([1, 3, 8]))
        h = int(rng.choice([4, 7, 8]))
        bs = int(rng.choice([2, 5, 8]))
        eps = float(rng.choice([1e-5, 1e-3]))
        momentum = float(rng.choice([0.9, 0.99]))
        with_res = bool(rng.randint(2))
        act = "relu" if rng.randint(2) else None
        xv = rng.randn(bs, c, h, h).astype("float32")

        outs = {}
        for fused in (True, False):
            fluid.reset_default_env()
            fluid.default_main_program().random_seed = 10 + case
            fluid.default_startup_program().random_seed = 10 + case
            x = layers.data("x", [c, h, h], dtype="float32")
            if fused:
                y = layers.fused_bn_add_act(
                    x, x if with_res else None, act=act,
                    epsilon=eps, momentum=momentum,
                    param_attr=fluid.ParamAttr(name="fz_s"),
                    bias_attr=fluid.ParamAttr(name="fz_b"),
                    moving_mean_name="fz_m", moving_variance_name="fz_v")
            else:
                b = layers.batch_norm(
                    x, act=None, epsilon=eps, momentum=momentum,
                    param_attr=fluid.ParamAttr(name="fz_s"),
                    bias_attr=fluid.ParamAttr(name="fz_b"),
                    moving_mean_name="fz_m", moving_variance_name="fz_v")
                y = layers.elementwise_add(b, x) if with_res else b
                if act:
                    y = layers.relu(y)
            loss = layers.reduce_mean(layers.square(y))
            fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            (yv,) = exe.run(feed={"x": xv}, fetch_list=[y])
            outs[fused] = (
                np.asarray(yv),
                np.array(np.asarray(fluid.global_scope().find_var("fz_s"))),
                np.array(np.asarray(fluid.global_scope().find_var("fz_m"))),
            )
        tag = (f"case {case}: c={c} h={h} bs={bs} eps={eps} "
               f"mom={momentum} res={with_res} act={act}")
        for a, b in zip(outs[True], outs[False]):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6,
                                       err_msg=tag)


def test_fused_bn_rejects_mismatched_residual_shape():
    """ADVICE r4: a broadcastable-but-wrong Z (e.g. [N,C,1,1]) must fail
    shape inference, not silently broadcast inside the lowering."""
    import pytest

    fluid.reset_default_env()
    x = layers.data("x", [4, 8, 8], dtype="float32")
    conv = layers.conv2d(x, num_filters=4, filter_size=3, padding=1)
    bad_z = layers.pool2d(x, pool_size=8, pool_type="avg")  # [N,4,1,1]
    with pytest.raises(ValueError, match="residual Z shape"):
        layers.fused_bn_add_act(conv, bad_z, act="relu")
