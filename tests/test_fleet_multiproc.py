"""Process-level fleet (ISSUE 17): replicas as real OS processes.

Three layers of contract, cheapest first:

- **Framed data plane** — a `SeqExport` (fp32 and int8-with-scales)
  survives the `FrameServer`/`FrameClient` pickle round-trip
  byte-identical; pool-geometry errors re-raise BY NAME across the
  socket; a response torn mid-frame (FAULT_RPC_TRUNCATE_ONCE) or a
  dropped call (FAULT_RPC_DROP_ONCE) surfaces as a typed retryable
  `ConnectionError` that the bounded-backoff retry absorbs — never a
  hang, never a partial-pickle ValueError.
- **SIGKILL e2e (tier-1, small shapes)** — a 2+2 process fleet loses
  one replica to a real SIGKILL on a live pid mid-work and another to
  an external `os.kill`; every request completes token-identical to a
  thread-fleet oracle, `lost_requests=0`, the casualty is quarantined
  and respawned by the controller, both audits come back clean.
- **Full storm (slow/chaos)** — kills x handoff drops x a rolling
  upgrade under sustained load.
"""

import os
import pickle
import signal
import time

import numpy as np
import pytest

from paddle_tpu.elastic.rpc import (
    FrameClient,
    FrameError,
    RemoteMaster,
    serve_frames,
    serve_master,
)
from paddle_tpu.elastic.master import InMemStore, MasterService
from paddle_tpu.resilience import faultinject
from paddle_tpu.serving import DecodeConfig, DecodeRequest, init_decode_params
from paddle_tpu.serving.distributed import ReplicaDirectory
from paddle_tpu.serving.fleet import (
    DecodeReplica,
    Fleet,
    FleetController,
    PrefillReplica,
    ProcSpawner,
)
from paddle_tpu.serving.kvcache import KVCachePool


# -- the framed data plane -------------------------------------------------

def _filled_pool(dtype: str, num_pages: int = 8, page_size: int = 4):
    """A tiny pool with one 10-token sequence whose pages hold known
    content (written straight into the page arrays — the round-trip
    contract is about bytes on the wire, not the prefill math)."""
    import jax.numpy as jnp

    pool = KVCachePool(num_pages=num_pages, page_size=page_size,
                       num_layers=2, num_heads=2, head_dim=4,
                       dtype=dtype)
    pool.allocate(7)
    pool.append_tokens([7], [10])
    rng = np.random.RandomState(0)
    shape = pool.k_pages.shape
    if dtype == "int8":
        k = rng.randint(-128, 128, size=shape).astype(np.int8)
        v = rng.randint(-128, 128, size=shape).astype(np.int8)
        pool.k_scales[:] = rng.rand(*pool.k_scales.shape)
        pool.v_scales[:] = rng.rand(*pool.v_scales.shape)
    else:
        k = rng.standard_normal(shape).astype(np.float32)
        v = rng.standard_normal(shape).astype(np.float32)
    pool.k_pages = jnp.asarray(k)
    pool.v_pages = jnp.asarray(v)
    return pool


def _echo_dispatch(verb, **kw):
    if verb == "echo":
        return kw["payload"]
    raise ValueError(f"unknown verb {verb!r}")


@pytest.mark.parametrize("dtype", ["float32", "int8"])
def test_seqexport_survives_frame_roundtrip_byte_identical(dtype):
    """The handoff payload crosses a REAL socket unchanged: every page
    byte, every dtype, every int8 scale."""
    pool = _filled_pool(dtype)
    exp = pool.export_seq(7)
    srv = serve_frames(_echo_dispatch)
    try:
        cli = FrameClient(srv.endpoint)
        back = cli.call("echo", payload=exp)
        cli.close()
    finally:
        srv.shutdown()
    assert back.seq_id == exp.seq_id and back.length == exp.length
    assert back.k.dtype == exp.k.dtype and back.v.dtype == exp.v.dtype
    assert back.k.tobytes() == exp.k.tobytes()
    assert back.v.tobytes() == exp.v.tobytes()
    if dtype == "int8":
        assert back.k_scales is not None
        assert back.k_scales.tobytes() == exp.k_scales.tobytes()
        assert back.v_scales.tobytes() == exp.v_scales.tobytes()
    else:
        assert back.k_scales is None and back.v_scales is None
    # and the round-tripped payload is admissible: import into a
    # geometry-matched pool reproduces the content
    dst = KVCachePool(num_pages=8, page_size=4, num_layers=2,
                      num_heads=2, head_dim=4, dtype=dtype)
    dst.allocate(7)
    dst.import_seq(back, seq_id=7)
    assert dst._tables[7].length == exp.length


def test_geometry_mismatch_reraises_by_name_across_socket():
    """A destination pool with the wrong page_size must reject the
    import with the SAME typed ValueError the in-process path raises —
    re-raised by name on the client side of the socket."""
    pool = _filled_pool("float32", page_size=4)
    exp = pool.export_seq(7)
    dst = KVCachePool(num_pages=8, page_size=8, num_layers=2,
                      num_heads=2, head_dim=4, dtype="float32")

    dst.allocate(7)

    def dispatch(verb, **kw):
        if verb == "imp":
            dst.import_seq(kw["payload"], seq_id=7)
            return {}
        raise ValueError(f"unknown verb {verb!r}")

    srv = serve_frames(dispatch)
    try:
        cli = FrameClient(srv.endpoint)
        with pytest.raises(ValueError, match="pool geometry mismatch"):
            cli.call("imp", payload=exp, retry=False)
        cli.close()
    finally:
        srv.shutdown()


def test_frame_truncate_mid_response_is_typed_and_retried(monkeypatch):
    """FAULT_RPC_TRUNCATE_ONCE tears one response mid-frame: the
    client must see a typed retryable ConnectionError (FrameError) —
    not a partial-pickle crash, not a hang — and the bounded-backoff
    retry must complete the call."""
    monkeypatch.setenv("FAULT_RPC_TRUNCATE_ONCE", "1")
    faultinject.reset()
    pool = _filled_pool("float32")
    exp = pool.export_seq(7)
    srv = serve_frames(_echo_dispatch)
    try:
        cli = FrameClient(srv.endpoint)
        back = cli.call("echo", payload=exp)
        assert back.k.tobytes() == exp.k.tobytes()
        assert "rpc_truncate" in faultinject.fired
        assert cli.last_call_retries >= 1
        assert cli.retry_stats["retries"] >= 1
        cli.close()
    finally:
        srv.shutdown()
        faultinject.reset()


def test_frame_drop_once_absorbed_by_retry(monkeypatch):
    monkeypatch.setenv("FAULT_RPC_DROP_ONCE", "echo")
    faultinject.reset()
    srv = serve_frames(_echo_dispatch)
    try:
        cli = FrameClient(srv.endpoint)
        assert cli.call("echo", payload=41) == 41
        assert cli.last_call_retries >= 1
        cli.close()
    finally:
        srv.shutdown()
        faultinject.reset()


def test_frame_truncate_without_retry_raises_frame_error(monkeypatch):
    monkeypatch.setenv("FAULT_RPC_TRUNCATE_ONCE", "1")
    faultinject.reset()
    srv = serve_frames(_echo_dispatch)
    try:
        cli = FrameClient(srv.endpoint)
        with pytest.raises(FrameError):
            cli.call("echo", payload=1, retry=False)
        cli.close()
    finally:
        srv.shutdown()
        faultinject.reset()


def test_master_line_protocol_truncate_is_typed_and_retried(monkeypatch):
    """The SAME torn-write fault against the line-JSON master plane: a
    half-written response must surface as a typed retryable error (no
    partial-JSON ValueError) and RemoteMaster's retry must absorb it."""
    monkeypatch.setenv("FAULT_RPC_TRUNCATE_ONCE", "1")
    faultinject.reset()
    svc = MasterService(InMemStore(), failure_max=7)
    srv = serve_master(svc, port=0)
    try:
        m = RemoteMaster(srv.endpoint)
        assert m.failure_max == 7
        assert "rpc_truncate" in faultinject.fired
        assert m.last_call_retries >= 1
    finally:
        srv.shutdown()
        faultinject.reset()


def test_exceptions_survive_pickling():
    """Process fleets ship typed errors inside results — every custom
    __init__ signature must round-trip (NonFiniteSequenceError's
    two-arg constructor broke default exception pickling)."""
    from paddle_tpu.serving.generate import NonFiniteSequenceError

    err = NonFiniteSequenceError(3, 17)
    back = pickle.loads(pickle.dumps(err))
    assert isinstance(back, NonFiniteSequenceError)
    assert back.seq_id == 3 and back.step == 17


# -- the process fleet -----------------------------------------------------

_POOL = dict(num_pages=32, page_size=4)


def _thread_fleet(params, cfg):
    return Fleet(
        lambda n: PrefillReplica(n, params, cfg, **_POOL),
        lambda n: DecodeReplica(n, params, cfg, **_POOL),
        n_prefill=1, n_decode=1)


def _run(fleet, prompts, max_new=5, timeout=180):
    futs = [fleet.submit(DecodeRequest(prompt=p, max_new_tokens=max_new))
            for p in prompts]
    return [f.result(timeout=timeout).tokens for f in futs]


def test_proc_fleet_prefix_reservation_skips_tokens(monkeypatch):
    """Cross-process prefix reservations (ISSUE 18 bugfix): repeated
    prompts through a 1+1 PROCESS fleet must reserve the decode
    child's cached prefix over the `reserve_prefix` verb and ship only
    the unshared tail (`skipped_tokens > 0`), matching the thread
    fleet's planned-handoff numbers exactly — tokens, skipped tokens,
    and handoff bytes."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    cfg = DecodeConfig()
    params = init_decode_params(cfg, seed=0)
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5]  # 11 tokens, 2 full pages

    def run_seq(fleet):
        # sequential submits: each request retires (and its pages join
        # the decode child's prefix cache) before the next one plans
        return [fleet.submit(DecodeRequest(prompt=list(prompt),
                                           max_new_tokens=4))
                .result(timeout=180).tokens for _ in range(3)]

    oracle = _thread_fleet(params, cfg)
    want = run_seq(oracle)
    ost = oracle.stats()
    oracle.close()
    assert ost["skipped_tokens"] > 0  # the oracle itself planned

    spawner = ProcSpawner(params, cfg, prefill_kwargs=_POOL,
                          decode_kwargs=_POOL)
    fleet = Fleet(spawner.prefill, spawner.decode,
                  n_prefill=1, n_decode=1)
    try:
        got = run_seq(fleet)
        st = fleet.stats()
        audit = fleet.audit()
    finally:
        fleet.close()
        spawner.close()
    assert got == want
    assert st["skipped_tokens"] == ost["skipped_tokens"]
    assert st["handoff_bytes"] == ost["handoff_bytes"]
    assert st["lost_requests"] == 0 and st["failed"] == 0
    assert st["re_prefills"] == 0  # no reservation was dropped
    assert audit["pages_leaked"] == 0 and audit["invariants_ok"] == 1


def test_proc_fleet_sigkill_failover_token_identical(monkeypatch):
    """The tentpole contract end to end: a 2+2 fleet of real processes
    takes a chaos SIGKILL on prefill0 mid-work (phase A) and an
    external SIGKILL on decode0's live pid (phase B); every request
    completes token-identical to the thread-fleet oracle,
    lost_requests banks 0, the controller quarantines the corpse and
    respawns below min, and both audits come back clean."""
    cfg = DecodeConfig()
    params = init_decode_params(cfg, seed=0)
    prompts_a = [[i + 1, i + 2, i + 3] for i in range(6)]
    prompts_b = [[9, 8, 7, i + 1] for i in range(4)]

    oracle = _thread_fleet(params, cfg)
    want_a = _run(oracle, prompts_a)
    want_b = _run(oracle, prompts_b)
    oracle.close()

    monkeypatch.setenv("FAULT_SERVE_PROC_KILL", "prefill0")
    faultinject.reset()
    srv = serve_master(MasterService(InMemStore()))
    directory = ReplicaDirectory(RemoteMaster(srv.endpoint),
                                 max_silence_s=2.0)
    spawner = ProcSpawner(params, cfg, prefill_kwargs=_POOL,
                          decode_kwargs=_POOL,
                          master_endpoint=srv.endpoint)
    fleet = Fleet(spawner.prefill, spawner.decode, n_prefill=2,
                  n_decode=2, directory=directory)
    ctl = FleetController(fleet,
                          min_replicas={"prefill": 2, "decode": 2},
                          max_replicas={"prefill": 3, "decode": 3})
    try:
        # phase A: prefill0 SIGKILLs itself at its first batch start —
        # its ACKed work fails over and still completes correctly
        got_a = _run(fleet, prompts_a)
        assert got_a == want_a
        st = fleet.stats()
        assert st["lost_requests"] == 0 and st["failed"] == 0

        # the corpse: quarantined (deregistered, pid confirmed dead)
        # and replaced because the class dropped below min
        p0 = fleet.replicas("prefill").get("prefill0")
        deadline = time.time() + 15
        while time.time() < deadline and p0 is not None and p0.alive:
            time.sleep(0.1)
        for _ in range(4):
            ctl.step()
            time.sleep(0.2)
        st = fleet.stats()
        assert st["respawns"] >= 1
        assert st["replica_deaths"] >= 1
        # the corpse is off the routing plane; its replacement is live
        p0 = fleet.replicas("prefill").get("prefill0")
        assert p0 is None or not p0.routing
        assert any(r.alive and r.routing and n != "prefill0"
                   for n, r in fleet.replicas("prefill").items())

        # phase B: an EXTERNAL SIGKILL on decode0's live pid while its
        # handoffs are in flight
        d0 = fleet.replicas("decode").get("decode0")
        futs = [fleet.submit(DecodeRequest(prompt=p, max_new_tokens=5))
                for p in prompts_b]
        time.sleep(0.3)  # let handoffs land on decode replicas
        if d0 is not None and d0.proc.poll() is None:
            os.kill(d0.pid, signal.SIGKILL)
        got_b = [f.result(timeout=180).tokens for f in futs]
        assert got_b == want_b

        st = fleet.stats()
        assert st["lost_requests"] == 0
        assert st["completed"] == len(prompts_a) + len(prompts_b)
        audit = fleet.audit()
        assert audit["pages_leaked"] == 0
        assert audit["invariants_ok"] == 1
    finally:
        fleet.close()
        spawner.close()
        srv.shutdown()
        faultinject.reset()


@pytest.mark.slow
@pytest.mark.chaos
def test_proc_fleet_storm(monkeypatch):
    """Kills x handoff drops x a rolling upgrade under load: the
    worst hour of a deployment's life, compressed.  Everything still
    completes, nothing is lost, nothing leaks."""
    cfg = DecodeConfig()
    params = init_decode_params(cfg, seed=0)

    monkeypatch.setenv("FAULT_SERVE_PROC_KILL", "decode0")
    monkeypatch.setenv("FAULT_SERVE_HANDOFF_DROP", "1")
    faultinject.reset()
    srv = serve_master(MasterService(InMemStore()))
    directory = ReplicaDirectory(RemoteMaster(srv.endpoint),
                                 max_silence_s=2.0)
    spawner = ProcSpawner(params, cfg, prefill_kwargs=_POOL,
                          decode_kwargs=_POOL,
                          master_endpoint=srv.endpoint)
    fleet = Fleet(spawner.prefill, spawner.decode, n_prefill=2,
                  n_decode=2, directory=directory)
    ctl = FleetController(fleet,
                          min_replicas={"prefill": 2, "decode": 2},
                          max_replicas={"prefill": 3, "decode": 3})
    try:
        # wave 1: traffic into the armed knobs — one handoff payload
        # vanishes in transit (re-prefilled), decode0 SIGKILLs itself
        futs = [fleet.submit(DecodeRequest(
            prompt=[i + 1, i + 2, (i % 5) + 1], max_new_tokens=5))
            for i in range(8)]
        res = [f.result(timeout=240) for f in futs]
        assert all(r.error is None for r in res)
        st = fleet.stats()
        assert st["handoff_drops"] >= 1
        assert st["handoff_drops_recovered"] >= 1
        assert st["lost_requests"] == 0

        # let the controller clear the casualty and respawn
        for _ in range(4):
            ctl.step()
            time.sleep(0.2)
        assert fleet.stats()["respawns"] >= 1

        # wave 2: a rolling weight upgrade under fresh traffic — every
        # surviving replica drains, swaps, rejoins; traffic completes
        params2 = init_decode_params(cfg, seed=1)
        futs = [fleet.submit(DecodeRequest(
            prompt=[5, 4, i + 1], max_new_tokens=4)) for i in range(4)]
        upgraded = ctl.rolling_upgrade(params2, timeout=60.0)
        assert len(upgraded) >= 4
        res = [f.result(timeout=240) for f in futs]
        assert all(r.error is None for r in res)

        # wave 3: post-upgrade traffic decodes with the NEW weights —
        # token-identical to a thread oracle carrying params2
        oracle = _thread_fleet(params2, cfg)
        prompts = [[2, 4, 6, i + 1] for i in range(4)]
        want = _run(oracle, prompts, max_new=4)
        oracle.close()
        got = _run(fleet, prompts, max_new=4)
        assert got == want

        st = fleet.stats()
        assert st["lost_requests"] == 0 and st["failed"] == 0
        audit = fleet.audit()
        assert audit["pages_leaked"] == 0
        assert audit["invariants_ok"] == 1
    finally:
        fleet.close()
        spawner.close()
        srv.shutdown()
        faultinject.reset()
