"""API freeze check (reference: the API.spec diff gate in the reference's
CI, tools/print_signatures.py)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_public_api_matches_spec():
    spec_path = os.path.join(REPO, "API.spec")
    assert os.path.exists(spec_path), (
        "API.spec missing; run python tools/print_signatures.py --update")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "print_signatures.py")],
        capture_output=True, text=True, check=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    ).stdout
    with open(spec_path) as f:
        frozen = f.read()
    if out != frozen:
        import difflib

        diff = "\n".join(difflib.unified_diff(
            frozen.splitlines(), out.splitlines(),
            "API.spec", "current", lineterm="", n=0,
        ))
        raise AssertionError(
            "public API changed without updating API.spec "
            "(python tools/print_signatures.py --update):\n" + diff[:4000]
        )


def test_fluid_top_level_name_parity():
    """Every name the reference's fluid/__init__.py __all__ declares
    resolves on paddle_tpu (python/paddle/fluid/__init__.py:40)."""
    import paddle_tpu

    for n in ["io", "initializer", "layers", "contrib", "imperative",
              "transpiler", "nets", "optimizer", "learning_rate_decay",
              "backward", "LoDTensor", "LoDTensorArray", "CPUPlace",
              "CUDAPlace", "CUDAPinnedPlace", "Tensor", "ParamAttr",
              "WeightNormParamAttr", "DataFeeder", "clip", "profiler",
              "unique_name", "recordio_writer", "Scope"]:
        assert hasattr(paddle_tpu, n), n


def test_lod_tensor_shim_feeds_executor():
    """fluid.LoDTensor() with set()/set_lod() feeds a sequence op like the
    reference's pybind LoDTensor."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import layers

    x = layers.data("x", [3], dtype="float32", lod_level=1)
    pooled = layers.sequence_pool(x, pool_type="sum")
    exe = fluid.Executor(fluid.CPUPlace())
    t = fluid.LoDTensor()
    flat = np.arange(15, dtype="float32").reshape(5, 3)
    t.set(flat)
    t.set_lod([[0, 2, 5]])
    (got,) = exe.run(feed={"x": t}, fetch_list=[pooled])
    want = np.stack([flat[:2].sum(0), flat[2:].sum(0)])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_name_scope_annotates_ops():
    """fluid.name_scope (framework.py name_scope) attaches the reference's
    op_namescope debug attr; execution is unaffected."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import layers

    x = layers.data("x", [2], dtype="float32")
    with fluid.name_scope("encoder"):
        with fluid.name_scope("l0"):
            h = layers.fc(x, size=2)
    out = layers.fc(h, size=1)
    ops = fluid.default_main_program().desc.block(0).ops
    scoped = [op.attrs.get("op_namescope") for op in ops
              if op.attrs.get("op_namescope")]
    assert "/encoder/l0/" in scoped
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    (got,) = exe.run(feed={"x": np.ones((2, 2), "float32")},
                     fetch_list=[out])
    assert np.isfinite(np.asarray(got)).all()


def test_name_scope_suffixes_repeated_siblings():
    import paddle_tpu as fluid
    from paddle_tpu import layers

    x = layers.data("x", [2], dtype="float32")
    seen = []
    for _ in range(2):
        with fluid.name_scope("block"):
            h = layers.fc(x, size=2)
            ops = fluid.default_main_program().desc.block(0).ops
            seen.append(ops[-1].attrs.get("op_namescope"))
    assert seen[0] == "/block/" and seen[1] == "/block_1/"


def test_weight_norm_negative_dim():
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import layers

    x = layers.data("x", [6], dtype="float32")
    h = layers.fc(x, size=4,
                  param_attr=fluid.WeightNormParamAttr(dim=-1, name="wn2"),
                  bias_attr=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    g = np.asarray(fluid.global_scope().find_var("wn2.w_g"))
    assert g.shape == (4,)  # dim=-1 == last axis, per-column norms
    (got,) = exe.run(feed={"x": np.ones((2, 6), "float32")},
                     fetch_list=[h])
    assert np.isfinite(np.asarray(got)).all()
