"""API freeze check (reference: the API.spec diff gate in the reference's
CI, tools/print_signatures.py)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_public_api_matches_spec():
    spec_path = os.path.join(REPO, "API.spec")
    assert os.path.exists(spec_path), (
        "API.spec missing; run python tools/print_signatures.py --update")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "print_signatures.py")],
        capture_output=True, text=True, check=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    ).stdout
    with open(spec_path) as f:
        frozen = f.read()
    if out != frozen:
        import difflib

        diff = "\n".join(difflib.unified_diff(
            frozen.splitlines(), out.splitlines(),
            "API.spec", "current", lineterm="", n=0,
        ))
        raise AssertionError(
            "public API changed without updating API.spec "
            "(python tools/print_signatures.py --update):\n" + diff[:4000]
        )
