"""Per-op numeric sweep of the activation family (reference: the
test_activation_op.py corpus driven by op_test.py; op macros at
operators/activation_op.cc:478-520).  Each case checks the op output
against an independently written numpy reference and its analytic gradient
against central finite differences."""

import math

import numpy as np
import pytest

from op_test import OpTest


def _np_erf(x):
    return np.vectorize(math.erf)(x)


def _rand(shape, lo=-2.0, hi=2.0, seed=7):
    rng = np.random.RandomState(seed)
    return (rng.uniform(lo, hi, size=shape)).astype("float32")


def _away_from(x, points, eps=0.05):
    """Nudge samples away from non-differentiable kinks."""
    for p in points:
        close = np.abs(x - p) < eps
        x = np.where(close, p + np.sign(x - p + 1e-9) * eps * 2, x)
    return x.astype("float32")


def _np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_softplus(x):
    return np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0)


# op -> (input, attrs, numpy reference, check_grad?)
CASES = {
    "sigmoid": (_rand((3, 8)), {}, _np_sigmoid, True),
    "logsigmoid": (_rand((3, 8)), {}, lambda x: np.log(_np_sigmoid(x)), True),
    "exp": (_rand((3, 8)), {}, np.exp, True),
    "relu": (_away_from(_rand((3, 8)), [0]), {}, lambda x: np.maximum(x, 0), True),
    "gelu": (_rand((3, 8)), {},
             lambda x: 0.5 * x * (1 + _np_erf(x / np.sqrt(2))), True),
    "tanh": (_rand((3, 8)), {}, np.tanh, True),
    "tanh_shrink": (_rand((3, 8)), {}, lambda x: x - np.tanh(x), True),
    "sqrt": (_rand((3, 8), 0.5, 2.0), {}, np.sqrt, True),
    "rsqrt": (_rand((3, 8), 0.5, 2.0), {}, lambda x: 1 / np.sqrt(x), True),
    "abs": (_away_from(_rand((3, 8)), [0]), {}, np.abs, True),
    "ceil": (_away_from(_rand((3, 8)), [-1, 0, 1]), {}, np.ceil, False),
    "floor": (_away_from(_rand((3, 8)), [-1, 0, 1]), {}, np.floor, False),
    "cos": (_rand((3, 8)), {}, np.cos, True),
    "sin": (_rand((3, 8)), {}, np.sin, True),
    "round": (_away_from(_rand((3, 8)), [-0.5, 0.5]), {}, np.round, False),
    "reciprocal": (_rand((3, 8), 0.5, 2.0), {}, lambda x: 1 / x, True),
    "log": (_rand((3, 8), 0.5, 2.0), {}, np.log, True),
    "square": (_rand((3, 8)), {}, np.square, True),
    "softplus": (_rand((3, 8)), {}, _np_softplus, True),
    "softsign": (_rand((3, 8)), {}, lambda x: x / (1 + np.abs(x)), True),
    "softshrink": (
        _away_from(_rand((3, 8)), [-0.5, 0.5]), {"lambda": 0.5},
        lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0.0)),
        True),
    "hard_shrink": (
        _away_from(_rand((3, 8)), [-0.5, 0.5]), {"threshold": 0.5},
        lambda x: np.where(np.abs(x) > 0.5, x, 0.0), True),
    "brelu": (
        _away_from(_rand((3, 8), -3, 3), [-1.0, 2.0]),
        {"t_min": -1.0, "t_max": 2.0},
        lambda x: np.clip(x, -1.0, 2.0), True),
    "leaky_relu": (
        _away_from(_rand((3, 8)), [0]), {"alpha": 0.1},
        lambda x: np.where(x >= 0, x, 0.1 * x), True),
    "soft_relu": (
        _rand((3, 8)), {"threshold": 40.0},
        lambda x: np.log1p(np.exp(np.clip(x, -40.0, 40.0))), True),
    "elu": (
        _away_from(_rand((3, 8)), [0]), {"alpha": 0.8},
        lambda x: np.where(x > 0, x, 0.8 * (np.exp(x) - 1)), True),
    "relu6": (
        _away_from(_rand((3, 8), -2, 8), [0.0, 6.0]), {"threshold": 6.0},
        lambda x: np.clip(x, 0, 6.0), True),
    "pow": (_rand((3, 8), 0.3, 2.0), {"factor": 2.5},
            lambda x: np.power(x, 2.5), True),
    "stanh": (
        _rand((3, 8)), {"scale_a": 0.67, "scale_b": 1.7159},
        lambda x: 1.7159 * np.tanh(0.67 * x), True),
    "hard_sigmoid": (
        _away_from(_rand((3, 8), -4, 4), [-2.5, 2.5]),
        {"slope": 0.2, "offset": 0.5},
        lambda x: np.clip(0.2 * x + 0.5, 0, 1), True),
    "swish": (_rand((3, 8)), {"beta": 1.5},
              lambda x: x * _np_sigmoid(1.5 * x), True),
    "thresholded_relu": (
        _away_from(_rand((3, 8)), [1.0]), {"threshold": 1.0},
        lambda x: np.where(x > 1.0, x, 0.0), True),
    "silu": (_rand((3, 8)), {}, lambda x: x * _np_sigmoid(x), True),
    "mish": (_rand((3, 8)), {},
             lambda x: x * np.tanh(_np_softplus(x)), True),
    "sign": (_away_from(_rand((3, 8)), [0]), {}, np.sign, False),
    "tan": (_rand((3, 8), -1.0, 1.0), {}, np.tan, True),
    "acos": (_rand((3, 8), -0.9, 0.9), {}, np.arccos, True),
    "asin": (_rand((3, 8), -0.9, 0.9), {}, np.arcsin, True),
    "atan": (_rand((3, 8)), {}, np.arctan, True),
    "sinh": (_rand((3, 8)), {}, np.sinh, True),
    "cosh": (_rand((3, 8)), {}, np.cosh, True),
    "erf": (_rand((3, 8)), {}, _np_erf, True),
}


@pytest.mark.parametrize("op", sorted(CASES))
def test_activation(op):
    x, attrs, ref, do_grad = CASES[op]
    want = None if ref is None else ref(x.astype(np.float64))

    class T(OpTest):
        op_type = op

    t = T()
    t.inputs = {"X": x}
    t.attrs = attrs
    t.outputs = {"Out": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    if do_grad:
        t.check_grad(["X"], "Out", max_relative_error=0.01)
