"""Beam search ops (reference: test_beam_search_op.py,
test_beam_search_decode_op.py, machine-translation decode loop)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def test_beam_search_step_selects_topk():
    B, K, END = 2, 3, 0  # one batch entry, beam 2, 3 candidates each
    pre_ids = layers.data("pre_ids", [1], append_batch_size=False,
                          dtype="int64")
    pre_sc = layers.data("pre_sc", [1], append_batch_size=False,
                         dtype="float32")
    ids = layers.data("ids", [K], dtype="int64")
    sc = layers.data("sc", [K], dtype="float32")
    sel_ids, sel_sc = layers.beam_search(pre_ids, pre_sc, ids, sc,
                                         beam_size=B, end_id=END)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {
        "pre_ids": np.array([[5], [6]], dtype="int64"),
        "pre_sc": np.array([[0.0], [0.0]], dtype="float32"),
        "ids": np.array([[1, 2, 3], [4, 5, 6]], dtype="int64"),
        "sc": np.array([[-0.1, -2.0, -3.0], [-0.5, -1.5, -2.5]],
                       dtype="float32"),
    }
    got_ids, got_sc = exe.run(feed=feed, fetch_list=[sel_ids, sel_sc])
    # top 2 across 6 candidates: -0.1 (id 1) and -0.5 (id 4)
    np.testing.assert_array_equal(np.ravel(np.asarray(got_ids)), [1, 4])
    np.testing.assert_allclose(np.ravel(np.asarray(got_sc)), [-0.1, -0.5])


def test_beam_search_finished_beam_freezes():
    B, K, END = 2, 2, 0
    pre_ids = layers.data("pre_ids", [1], append_batch_size=False, dtype="int64")
    pre_sc = layers.data("pre_sc", [1], append_batch_size=False, dtype="float32")
    ids = layers.data("ids", [K], dtype="int64")
    sc = layers.data("sc", [K], dtype="float32")
    sel_ids, sel_sc = layers.beam_search(pre_ids, pre_sc, ids, sc,
                                         beam_size=B, end_id=END)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {
        # beam 0 already emitted END with score -0.2; beam 1 alive
        "pre_ids": np.array([[END], [7]], dtype="int64"),
        "pre_sc": np.array([[-0.2], [-0.3]], dtype="float32"),
        "ids": np.array([[1, 2], [3, 4]], dtype="int64"),
        "sc": np.array([[-5.0, -6.0], [-0.9, -1.1]], dtype="float32"),
    }
    got_ids, got_sc = exe.run(feed=feed, fetch_list=[sel_ids, sel_sc])
    got_ids = np.ravel(np.asarray(got_ids))
    got_sc = np.ravel(np.asarray(got_sc))
    # finished beam survives with END/-0.2; alive beam picks id 3 at -0.9
    assert END in got_ids and 3 in got_ids
    assert -0.2 in got_sc.round(6) and -0.9 in got_sc.round(6)


def test_decode_loop_end_to_end():
    """Greedy-ish 2-beam decode over a fixed 'LM' table, unrolled while."""
    V, BEAM, END, MAXLEN = 5, 2, 0, 4
    # log-prob table: token t -> scores over V; token 4 strongly -> END
    table_np = np.full((V, V), -5.0, dtype="float32")
    for t in range(V):
        table_np[t, (t + 1) % V] = -0.1  # prefer next token
    table_np[4, END] = -0.05

    table = layers.data("table", [V, V], append_batch_size=False,
                        dtype="float32")
    init_ids = layers.data("init_ids", [1], append_batch_size=False,
                           dtype="int64")
    init_sc = layers.data("init_sc", [1], append_batch_size=False,
                          dtype="float32")

    counter = layers.fill_constant([1], "int64", 0)
    maxlen = layers.fill_constant([1], "int64", MAXLEN)
    ids_arr = layers.create_array("int64")
    sc_arr = layers.create_array("float32")
    par_arr = layers.create_array("int64")

    cur_ids = layers.assign(init_ids)
    cur_sc = layers.assign(init_sc)
    cond = layers.less_than(counter, maxlen)
    w = layers.While(cond)
    with w.block():
        # candidate scores: pre_sc + table[cur_ids]
        cand = layers.gather(table, layers.reshape(cur_ids, [-1]))
        total = layers.elementwise_add(
            cand, layers.reshape(cur_sc, [-1, 1])
        )
        sel_ids, sel_sc = layers.beam_search(
            cur_ids, cur_sc, None, total, beam_size=BEAM, end_id=END
        )
        layers.array_write(sel_ids, counter, array=ids_arr)
        layers.array_write(sel_sc, counter, array=sc_arr)
        layers.array_write(sel_ids._parent_idx, counter, array=par_arr)
        layers.assign(sel_ids, cur_ids)
        layers.assign(sel_sc, cur_sc)
        layers.increment(counter, value=1, in_place=True)
        layers.less_than(counter, maxlen, cond=cond)

    sent_ids, sent_sc = layers.beam_search_decode(
        ids_arr, sc_arr, beam_size=BEAM, end_id=END,
        parent_idx=par_arr,
    )

    exe = fluid.Executor(fluid.CPUPlace())
    feed = {
        "table": table_np,
        "init_ids": np.array([[3], [3]], dtype="int64"),
        "init_sc": np.array([[0.0], [-1e9]], dtype="float32"),
    }
    (got,) = exe.run(feed=feed, fetch_list=[sent_ids], return_numpy=False)
    seqs = np.asarray(got.data)[..., 0]  # [beams, T]
    lens = np.asarray(got.lengths)
    # best beam from token 3: 4 -> 0(END); length 2
    best = seqs[0, : lens[0]]
    np.testing.assert_array_equal(best, [4, END])
