"""Scan-lowered while loops (VERDICT r1 weak #6): long static-trip-count
while bodies compile as ONE lax.scan step instead of T unrolled copies.
Parity is checked against the unroll path (scan_threshold attr) and against
numpy; a wall-clock budget guards the compile-time win at seq-len 100."""

import time

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.lod import create_lod_tensor


def _dynamic_rnn_program(hidden=8, feat=5, scan_threshold=None):
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        sent = layers.data(name="x", shape=[feat], dtype="float32",
                           lod_level=1)
        drnn = layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(sent)
            prev = drnn.memory(shape=[hidden], value=0.0)
            cat = layers.concat([word, prev], axis=1)
            h = layers.fc(cat, hidden, act="tanh",
                          param_attr=fluid.ParamAttr(name="w"),
                          bias_attr=fluid.ParamAttr(name="b"))
            drnn.update_memory(prev, h)
            drnn.output(h)
        out = drnn()
        last = layers.sequence_pool(out, "last")
        loss = layers.reduce_mean(last)
    if scan_threshold is not None:
        for op in prog.global_block().desc.ops:
            if op.type == "while":
                op.attrs["scan_threshold"] = scan_threshold
    return prog, startup, sent, loss


def _numpy_rnn(flat, lens, w, b, hidden):
    """Reference: h_t = tanh([x_t, h_{t-1}] @ w + b), per sequence."""
    outs = []
    off = 0
    for L in lens:
        h = np.zeros((hidden,), dtype=np.float64)
        for t in range(L):
            x = flat[off + t].astype(np.float64)
            h = np.tanh(np.concatenate([x, h]) @ w.astype(np.float64)
                        + b.astype(np.float64))
        outs.append(h)
        off += L
    return np.stack(outs)


def test_dynamic_rnn_scan_matches_unroll_and_numpy():
    hidden, feat = 8, 5
    lens = [23, 40, 17]  # max 40 > threshold -> scan path
    total = sum(lens)
    rng = np.random.RandomState(0)
    flat = rng.randn(total, feat).astype("float32")
    lod = create_lod_tensor(flat, [lens])
    w = rng.randn(feat + hidden, hidden).astype("float32") * 0.3
    b = rng.randn(hidden).astype("float32") * 0.1

    results = {}
    for name, thresh in (("scan", 16), ("unroll", 10_000)):
        fluid.reset_default_env()
        prog, startup, _, loss = _dynamic_rnn_program(hidden, feat,
                                                      scan_threshold=thresh)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.program_guard(prog, startup):
            exe.run(program=startup)
            scope = fluid.global_scope()
            scope.set_var("w", w)
            scope.set_var("b", b)
            (lv,) = exe.run(program=prog, feed={"x": lod},
                            fetch_list=[loss])
        results[name] = float(np.ravel(lv)[0])

    want = _numpy_rnn(flat, lens, w, b, hidden).mean()
    np.testing.assert_allclose(results["scan"], results["unroll"], rtol=1e-5)
    np.testing.assert_allclose(results["scan"], want, rtol=1e-4)


def test_dynamic_rnn_scan_trains():
    """Gradients flow through the scan-lowered while (jax.vjp over scan)."""
    fluid.reset_default_env()
    hidden, feat = 6, 4
    lens = [30, 25]
    rng = np.random.RandomState(1)
    flat = rng.randn(sum(lens), feat).astype("float32")
    lod = create_lod_tensor(flat, [lens])

    sent = layers.data(name="x", shape=[feat], dtype="float32", lod_level=1)
    drnn = layers.DynamicRNN()
    with drnn.block():
        word = drnn.step_input(sent)
        prev = drnn.memory(shape=[hidden], value=0.0)
        h = layers.fc(layers.concat([word, prev], axis=1), hidden,
                      act="tanh")
        drnn.update_memory(prev, h)
        drnn.output(h)
    out = drnn()
    last = layers.sequence_pool(out, "last")
    loss = layers.reduce_mean(layers.square(last))
    fluid.optimizer.SGD(0.5).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = [
        float(np.ravel(exe.run(feed={"x": lod}, fetch_list=[loss])[0])[0])
        for _ in range(12)
    ]
    assert losses[-1] < losses[0] * 0.7, losses


def test_long_sequence_compiles_fast():
    """Seq-len 400 must trace+compile via scan in bounded time; a 400x
    unrolled HLO would not fit this budget."""
    fluid.reset_default_env()
    hidden, feat, T = 16, 8, 400
    rng = np.random.RandomState(2)
    flat = rng.randn(T, feat).astype("float32")
    lod = create_lod_tensor(flat, [[T]])

    prog, startup, _, loss = _dynamic_rnn_program(hidden, feat)
    exe = fluid.Executor(fluid.CPUPlace())
    t0 = time.perf_counter()
    with fluid.program_guard(prog, startup):
        exe.run(program=startup)
        (lv,) = exe.run(program=prog, feed={"x": lod}, fetch_list=[loss])
    dt = time.perf_counter() - t0
    assert np.isfinite(float(np.ravel(lv)[0]))
    assert dt < 60.0, f"seq-len {T} took {dt:.1f}s — is the loop unrolling?"


def test_while_scan_written_not_read_output():
    """A parent var assigned every iteration but never read in-loop must
    surface its final value through the scan path (review finding r2)."""
    fluid.reset_default_env()
    i = layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = layers.fill_constant(shape=[1], dtype="int64", value=40)
    x = layers.data(name="x", shape=[3], dtype="float32")
    last = layers.fill_constant(shape=[1, 3], dtype="float32", value=0.0)
    cond = layers.less_than(x=i, y=n)
    w = layers.While(cond=cond)
    with w.block():
        scaled = layers.scale(x, scale=2.0)
        layers.assign(scaled, output=last)  # write-only from loop's view
        layers.increment(x=i, value=1, in_place=True)
        layers.less_than(x=i, y=n, cond=cond)
    exe = fluid.Executor(fluid.CPUPlace())
    xs = np.array([[1.0, 2.0, 3.0]], dtype="float32")
    (got,) = exe.run(feed={"x": xs}, fetch_list=[last])
    np.testing.assert_allclose(got, xs * 2.0, rtol=1e-6)


def test_stacked_array_append_after_scan():
    """write_to_array at index == length on a scan-produced array appends
    (parity with TensorArrayValue.write); skipping past the end raises."""
    import jax.numpy as jnp
    from paddle_tpu.core.tensor_array import StackedTensorArray

    arr = StackedTensorArray(jnp.arange(6.0).reshape(3, 2), 3)
    grown = arr.write(3, jnp.array([9.0, 9.0]))
    assert len(grown) == 4
    np.testing.assert_allclose(np.asarray(grown.read(3)), [9.0, 9.0])
    np.testing.assert_allclose(np.asarray(grown.read(0)), [0.0, 1.0])
    try:
        arr.write(5, jnp.zeros(2))
        raise AssertionError("expected IndexError")
    except IndexError:
        pass
