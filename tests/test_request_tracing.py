"""ISSUE 8: request-scoped tracing, OpenMetrics exemplars, and the
crash flight recorder across the serving tier.

Acceptance (tier-1): a request driven through the engine with
FLAGS_observability=1 shows (a) its trace_id on the returned result,
(b) its spans across submit and dispatcher threads in the merged
Perfetto trace, (c) an exemplar referencing that trace_id in the
latency histogram's OpenMetrics output, and (d) a FAULT_SERVE-induced
breaker trip writing a flight-recorder JSONL dump containing the
breaker-transition event; with FLAGS_observability=0 a tracemalloc
filter proves submit() allocates nothing from the observability
package."""

import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observability as obs
from paddle_tpu import serving
from paddle_tpu.resilience import faultinject

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def obs_on(tmp_path):
    """Observability on with a clean spine and a tmp flight dir."""
    fluid.set_flags({"FLAGS_observability": True,
                     "FLAGS_flight_dir": str(tmp_path / "flight")})
    obs.reset()
    yield
    obs.reset()
    fluid.set_flags({"FLAGS_observability": False,
                     "FLAGS_flight_dir": ""})


def _build_engine(buckets=(1, 2), max_wait_s=0.0, **cfg_kwargs):
    x = layers.data("x", [4], dtype="float32")
    y = layers.fc(x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return serving.Engine.from_program(
        exe, fluid.default_main_program(), [y], feed_names=["x"],
        config=serving.EngineConfig(buckets=buckets, max_wait_s=max_wait_s,
                                    **cfg_kwargs))


def _feed(rows=1):
    return {"x": np.zeros((rows, 4), np.float32)}


# -----------------------------------------------------------------------
# acceptance: end-to-end request trace through the engine
# -----------------------------------------------------------------------
def test_engine_request_trace_end_to_end(obs_on, tmp_path):
    with _build_engine() as eng:
        fut = eng.submit(_feed())
        fut.result(timeout=30)
        trace_id = fut.trace_id
    assert trace_id  # (a) the result carries its trace id

    run_dir = str(tmp_path / "run")
    obs.export_run(run_dir)

    # (b) the merged Perfetto trace holds this request's spans across
    # the submit and dispatcher threads, parented under one root
    with open(os.path.join(run_dir, "trace.json")) as f:
        doc = json.load(f)
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"
             and (e.get("args") or {}).get("trace_id") == trace_id]
    names = {e["name"] for e in spans}
    assert {"request", "request.submit", "request.queued",
            "request.dispatch"} <= names
    assert len({e["tid"] for e in spans}) >= 2  # cross-thread
    for e in spans:
        assert e["dur"] >= 0
        if e["name"] != "request":
            assert e["args"]["parent"] == "request"
    root = next(e for e in spans if e["name"] == "request")
    assert root["args"]["outcome"] == "ok"

    # (c) the latency histogram's OpenMetrics exposition carries an
    # exemplar referencing the trace
    prom = open(os.path.join(run_dir, "metrics.prom")).read()
    assert prom.rstrip().endswith("# EOF")
    exemplar_lines = [
        ln for ln in prom.splitlines()
        if ln.startswith("paddle_tpu_serving_request_latency_seconds_bucket")
        and f'# {{trace_id="{trace_id}"}}' in ln]
    assert exemplar_lines, prom


def test_breaker_trip_writes_flight_dump(obs_on, tmp_path):
    # (d) FAULT_SERVE-induced breaker trip -> flight-recorder JSONL dump
    # with the breaker transition event (and the failing dispatches
    # leading up to it)
    eng = _build_engine(breaker_threshold=2, breaker_cooldown_s=0.05)
    os.environ["FAULT_SERVE_DISPATCH_RAISE"] = "2"
    try:
        for _ in range(2):
            with pytest.raises(serving.EngineInternalError) as ei:
                eng.submit(_feed()).result(timeout=30)
            assert ei.value.trace_id  # typed errors carry trace ids
    finally:
        os.environ.pop("FAULT_SERVE_DISPATCH_RAISE", None)
        faultinject.reset()
    dumps = obs.default_flight().dump_paths
    assert len(dumps) == 1
    assert os.path.dirname(dumps[0]) == str(tmp_path / "flight")
    with open(dumps[0]) as f:
        lines = [json.loads(ln) for ln in f]
    header, events = lines[0], lines[1:]
    assert header["reason"] == "breaker_trip"
    assert header["events"] == len(events)
    kinds = [e["kind"] for e in events]
    assert "breaker_open" in kinds
    assert "batch_fail" in kinds and "submit" in kinds
    trip = next(e for e in events if e["kind"] == "breaker_open")
    assert trip["consecutive_errors"] == 2
    # recovery: after cooldown a successful probe closes the breaker
    # and the transition lands in the ring
    time.sleep(0.06)
    eng.infer(_feed())
    assert "breaker_close" in [e["kind"] for e in
                               obs.default_flight().events()]
    eng.close()


def test_submit_disabled_path_zero_observability_alloc():
    """The PR-3 zero-allocation contract extended to submit(): with the
    flag off, submitting allocates NOTHING from the observability
    package (and the Future still exposes trace_id=None)."""
    import tracemalloc

    assert not obs.enabled()
    # a large bucket + long fill window parks the dispatcher while we
    # measure, so only submit() itself runs inside the tracemalloc
    # window (the dispatch path is measured by PR-3's executor test)
    eng = _build_engine(buckets=(1, 2, 8), max_wait_s=5.0)
    eng.infer(_feed())  # warm caches/trailing-shape state end to end
    feeds = [_feed() for _ in range(3)]

    obs_pkg_dir = os.path.dirname(os.path.abspath(obs.__file__))
    tracemalloc.start()
    try:
        futs = [eng.submit(f) for f in feeds]
        snap = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    hits = snap.filter_traces(
        [tracemalloc.Filter(True, os.path.join(obs_pkg_dir, "*"))]
    ).statistics("filename")
    assert hits == [], f"observability allocated in submit(): {hits}"
    assert all(f.trace_id is None for f in futs)
    eng.close()
    for f in futs:
        f.result(timeout=30)
    # control: the same submit with the flag on mints a trace
    fluid.set_flags({"FLAGS_observability": True})
    try:
        eng2 = _build_engine(buckets=(1, 2))
        fut = eng2.submit(_feed())
        assert fut.trace_id is not None
        fut.result(timeout=30)
        eng2.close()
    finally:
        fluid.set_flags({"FLAGS_observability": False})
        obs.reset()


# -----------------------------------------------------------------------
# cross-thread span parenting round-trip (satellite)
# -----------------------------------------------------------------------
def test_cross_thread_span_parenting_roundtrip(obs_on, tmp_path):
    """A submit->dispatch->complete request round-trips through
    Chrome-trace export with its spans under ONE trace_id, correct
    parenting, and non-negative durations across threads."""
    with _build_engine() as eng:
        futs = [eng.submit(_feed()) for _ in range(3)]
        for f in futs:
            f.result(timeout=30)
    ids = {f.trace_id for f in futs}
    assert len(ids) == 3  # distinct ids per request

    path = str(tmp_path / "t.json")
    obs.write_chrome_trace(path, obs.default_tracer().spans())
    with open(path) as f:
        doc = json.load(f)
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    tid_names = {e["tid"]: e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "thread_name"}
    # every request that was kept round-trips as a well-formed tree
    kept = {t for t in ids if any(
        (e.get("args") or {}).get("trace_id") == t and e["name"] == "request"
        for e in xs)}
    assert kept  # at least the first (no-evidence) request is kept
    for t in kept:
        spans = [e for e in xs if (e.get("args") or {}).get("trace_id") == t]
        root = next(e for e in spans if e["name"] == "request")
        threads = {tid_names[e["tid"]] for e in spans}
        assert threading.main_thread().name in threads
        assert any(n.startswith("serving-") for n in threads)
        for e in spans:
            assert e["dur"] >= 0
            if e is not root:
                assert e["args"]["parent"] == "request"
            # children start within the root's envelope
            assert e["ts"] >= root["ts"] - 1e-3


# -----------------------------------------------------------------------
# tail sampling
# -----------------------------------------------------------------------
def test_tail_sampling_keeps_slow_and_errored(obs_on):
    tr = obs.RequestTracer()
    # no evidence yet: first request is kept
    assert tr.finish(tr.start(t0=0.0), outcome="ok", t_end=0.010)
    # seed the ring: 60 fast successes establish a ~10ms p99
    for _ in range(60):
        tr.finish(tr.start(t0=0.0), outcome="ok", t_end=0.010)
    before = tr.stats()
    # fast + ok -> sampled out
    assert not tr.finish(tr.start(t0=0.0), outcome="ok", t_end=0.001)
    # slow (>= p99) -> kept
    assert tr.finish(tr.start(t0=0.0), outcome="ok", t_end=0.050)
    # errored -> forced keep regardless of speed
    assert tr.finish(tr.start(t0=0.0), outcome="error", t_end=0.0001)
    after = tr.stats()
    assert after["sampled_out"] == before["sampled_out"] + 1
    assert after["kept"] == before["kept"] + 2
    # decisions land on the counter
    c = obs.default_registry().counter("paddle_tpu_request_traces", "")
    assert c.value(decision="kept") == after["kept"]
    assert c.value(decision="sampled_out") == after["sampled_out"]


def test_trace_budget_is_a_hard_cap(obs_on):
    fluid.set_flags({"FLAGS_request_trace_budget": 2})
    try:
        tr = obs.RequestTracer()
        kept = [tr.finish(tr.start(t0=0.0), outcome="error", t_end=1.0)
                for _ in range(5)]
        assert kept == [True, True, False, False, False]
        assert tr.stats()["budget_dropped"] == 3
        # budget-dropped traces emit NO spans
        assert len([s for s in obs.default_tracer().spans()
                    if s.cat == "request"]) == 2
    finally:
        fluid.set_flags({"FLAGS_request_trace_budget": 256})


def test_rejected_submits_carry_trace_ids_and_are_kept(obs_on):
    eng = _build_engine(queue_depth=1, max_wait_s=5.0, buckets=(1, 2, 8))
    try:
        fut = eng.submit(_feed())  # parks in the fill window
        with pytest.raises(serving.QueueFullError) as ei:
            eng.submit(_feed())
        assert ei.value.trace_id
        # the rejection is forced-keep: its root span is in the tracer
        roots = [s for s in obs.default_tracer().spans()
                 if s.cat == "request" and s.name == "request"
                 and s.args.get("trace_id") == ei.value.trace_id]
        assert len(roots) == 1
        assert roots[0].args["outcome"] == "rejected_queue_full"
        assert any(e["kind"] == "reject"
                   for e in obs.default_flight().events())
    finally:
        eng.close()
        fut.result(timeout=30)


# -----------------------------------------------------------------------
# flight recorder unit behavior
# -----------------------------------------------------------------------
def test_flight_recorder_ring_and_dump(obs_on, tmp_path):
    fr = obs.FlightRecorder(capacity=4)
    for i in range(6):
        fr.record("step", i=i)
    evts = fr.events()
    assert len(evts) == 4 and fr.dropped == 2
    assert [e["i"] for e in evts] == [2, 3, 4, 5]  # newest kept
    assert [e["seq"] for e in evts] == [3, 4, 5, 6]
    p = fr.dump("unit_test", dirname=str(tmp_path))
    lines = [json.loads(ln) for ln in open(p)]
    assert lines[0]["reason"] == "unit_test"
    assert lines[0]["events"] == 4 and lines[0]["dropped"] == 2
    assert [ln["i"] for ln in lines[1:]] == [2, 3, 4, 5]
    assert fr.dump_paths == [p]
    fr.reset()
    assert fr.events() == [] and fr.dump_paths == []


def test_health_broken_transition_dumps_once(obs_on):
    """Entering BROKEN via health() is the second dump trigger — and it
    fires on the EDGE, not on every poll."""
    eng = _build_engine(breaker_threshold=1, breaker_cooldown_s=30.0)
    assert eng.health()["state"] == "SERVING"
    os.environ["FAULT_SERVE_DISPATCH_RAISE"] = "1"
    try:
        with pytest.raises(serving.EngineInternalError):
            eng.submit(_feed()).result(timeout=30)
    finally:
        os.environ.pop("FAULT_SERVE_DISPATCH_RAISE", None)
        faultinject.reset()
    n_after_trip = len(obs.default_flight().dump_paths)
    assert n_after_trip == 1  # the breaker trip dumped
    assert eng.health()["state"] == "BROKEN"
    assert len(obs.default_flight().dump_paths) == 2  # BROKEN edge
    eng.health()  # still BROKEN: no new dump
    assert len(obs.default_flight().dump_paths) == 2
    healths = [e for e in obs.default_flight().events()
               if e["kind"] == "health"]
    assert [h["state"] for h in healths] == ["SERVING", "BROKEN"]
    eng.close()


# -----------------------------------------------------------------------
# engine.health() surfaces the admission-latency ring (satellite)
# -----------------------------------------------------------------------
def test_health_surfaces_batch_latency_percentiles():
    eng = _build_engine()
    try:
        h = eng.health()
        assert h["batch_latency_p50_s"] is None
        assert h["batch_latency_p99_s"] is None
        assert h["batch_latency_window"] == 0
        for _ in range(3):
            eng.infer(_feed())
        h = eng.health()
        assert h["batch_latency_p50_s"] > 0
        assert h["batch_latency_p99_s"] >= h["batch_latency_p50_s"]
        assert h["batch_latency_window"] == 3
    finally:
        eng.close()


# -----------------------------------------------------------------------
# Prometheus exposition escaping (satellite)
# -----------------------------------------------------------------------
def test_prometheus_label_values_escaped(obs_on):
    reg = obs.MetricsRegistry()
    reg.counter("errs", "by class").inc(
        error='said "no"\nand \\ left', trace_id="t-1")
    text = reg.to_prometheus()
    assert ('errs_total{error="said \\"no\\"\\nand \\\\ left",'
            'trace_id="t-1"} 1') in text
    # one logical line per sample: the newline must NOT split the line
    assert all(ln.count('"') % 2 == 0 for ln in text.splitlines()
               if ln.startswith("errs_total"))
    # openmetrics flavor escapes the same way and terminates with EOF
    om = reg.to_openmetrics()
    assert 'error="said \\"no\\"\\nand \\\\ left"' in om
    assert om.rstrip().endswith("# EOF")


def test_openmetrics_exemplars_render_and_merge_ignores_them(obs_on):
    reg = obs.MetricsRegistry()
    h = reg.histogram("lat_seconds", "", buckets=[0.01, 0.1, 1.0])
    h.observe(0.005)  # no exemplar
    h.observe(0.05, exemplar={"trace_id": "abc"})
    om = reg.to_openmetrics()
    line = next(ln for ln in om.splitlines()
                if ln.startswith('lat_seconds_bucket{le="0.1"}'))
    assert '# {trace_id="abc"} 0.05' in line
    # the classic exposition stays exemplar-free (Prometheus text
    # format predates them)
    assert "# {" not in reg.to_prometheus()
    # snapshots round-trip through merge with exemplars ignored
    reg2 = obs.MetricsRegistry()
    reg2.merge(reg.snapshot())
    assert reg2.histogram("lat_seconds", "").series_summary()["count"] == 2


# -----------------------------------------------------------------------
# decode-loop sequence tracing
# -----------------------------------------------------------------------
def _decode_fixture():
    cfg = serving.DecodeConfig(vocab_size=31, d_model=16, n_head=4,
                               n_layer=1, d_inner=32, max_length=32)
    params = serving.init_decode_params(cfg)
    pool = serving.KVCachePool(num_pages=32, page_size=4, num_layers=1,
                               num_heads=4, head_dim=4)
    return cfg, params, pool


def test_decode_sequences_carry_trace_ids_and_spans(obs_on):
    cfg, params, pool = _decode_fixture()
    loop = serving.ContinuousBatchingLoop(params, cfg, pool, max_batch=2)
    results = loop.run([
        serving.DecodeRequest(prompt=[1, 2, 3], max_new_tokens=3),
        serving.DecodeRequest(prompt=[4, 5], max_new_tokens=2,
                              trace_id="engine-minted-id"),
    ])
    assert results[0].trace_id and results[0].trace_id != "engine-minted-id"
    assert results[1].trace_id == "engine-minted-id"  # carried through
    spans = [s for s in obs.default_tracer().spans() if s.cat == "request"]
    for r in results:
        mine = [s for s in spans if s.args.get("trace_id") == r.trace_id]
        names = {s.name for s in mine}
        assert {"sequence", "sequence.queued", "sequence.prefill",
                "sequence.decode"} <= names
        root = next(s for s in mine if s.name == "sequence")
        assert root.args["outcome"] == "ok"
        assert root.args["tokens"] == len(r.tokens)
    # TTFT histogram carries a trace-id exemplar
    om = obs.default_registry().to_openmetrics()
    assert any("paddle_tpu_serving_ttft_seconds_bucket" in ln
               and "trace_id=" in ln for ln in om.splitlines())


def test_quarantined_sequence_trace_kept_and_flight_logged(obs_on):
    cfg, params, pool = _decode_fixture()
    loop = serving.ContinuousBatchingLoop(params, cfg, pool, max_batch=2)
    os.environ["FAULT_SERVE_NAN_SEQ"] = "1@1"
    try:
        results = loop.run([
            serving.DecodeRequest(prompt=[1, 2, 3], max_new_tokens=3),
            serving.DecodeRequest(prompt=[4, 5], max_new_tokens=3),
        ])
    finally:
        os.environ.pop("FAULT_SERVE_NAN_SEQ", None)
        faultinject.reset()
    bad = next(r for r in results if r.error is not None)
    assert bad.error.trace_id == bad.trace_id
    root = next(s for s in obs.default_tracer().spans()
                if s.cat == "request" and s.name == "sequence"
                and s.args.get("trace_id") == bad.trace_id)
    assert root.args["outcome"] == "quarantined"
    q = [e for e in obs.default_flight().events()
         if e["kind"] == "quarantine"]
    assert len(q) == 1 and q[0]["trace_id"] == bad.trace_id
    assert pool.stats()["used_pages"] == 0  # still no leaked pages


# -----------------------------------------------------------------------
# obsdump + serve_bench artifacts (satellites)
# -----------------------------------------------------------------------
def test_obsdump_renders_request_timeline_and_flight(obs_on, tmp_path,
                                                     capsys):
    from tools.obsdump import main as obsdump_main

    with _build_engine() as eng:
        fut = eng.submit(_feed())
        fut.result(timeout=30)
    obs.default_flight().dump("unit_test",
                              dirname=str(tmp_path / "run"))
    run_dir = str(tmp_path / "run")
    obs.export_run(run_dir)
    assert obsdump_main([run_dir]) == 0
    out = capsys.readouterr().out
    assert "== requests ==" in out
    assert fut.trace_id in out
    assert "request.dispatch" in out
    assert "tail sampling:" in out
    assert "== flight recorder ==" in out
    assert "reason=unit_test" in out


def test_serve_bench_reports_timestamps_and_artifacts(tmp_path, capsys):
    from tools.serve_bench import main as bench_main

    out = tmp_path / "r.json"
    obs_dir = tmp_path / "obs"
    rc = bench_main([
        "--model", "tiny", "--requests", "4", "--rate", "400",
        "--buckets", "1,2", "--batch-range", "1,2",
        "--json", str(out), "--obs-dir", str(obs_dir),
    ])
    capsys.readouterr()
    assert rc == 0
    result = json.loads(out.read_text())
    assert result["started_at"] <= result["finished_at"]
    assert abs(result["finished_at"] - time.time()) < 600
    art = result["artifacts"]
    assert os.path.exists(art["trace"])
    assert os.path.exists(art["metrics"])
    assert art["flight_dumps"] == []  # clean run: no incident, no dump
    # the flag was restored
    assert not obs.enabled()
    obs.reset()
