"""Control flow: While, arrays, StaticRNN, DynamicRNN, IfElse, Switch.

Mirrors reference tests: test_while_op.py, test_dyn_rnn.py,
test_recurrent_op.py, test_ifelse*.py, test_switch.py.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.lod import create_lod_tensor


def _run(feed, fetch_list, startup=True):
    exe = fluid.Executor(fluid.CPUPlace())
    if startup:
        exe.run(fluid.default_startup_program())
    return exe.run(feed=feed, fetch_list=fetch_list)


def test_while_accumulate():
    # sum 0..9 with a while loop (reference: test_while_op.py style)
    i = layers.fill_constant(shape=[1], dtype="int64", value=0)
    ten = layers.fill_constant(shape=[1], dtype="int64", value=10)
    acc = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    cond = layers.less_than(x=i, y=ten)
    w = layers.While(cond=cond)
    with w.block():
        acc2 = layers.cast(i, "float32")
        layers.sums([acc, acc2], out=acc)
        layers.increment(x=i, value=1, in_place=True)
        layers.less_than(x=i, y=ten, cond=cond)
    (out,) = _run({}, [acc], startup=False)
    assert float(np.ravel(out)[0]) == sum(range(10))


def test_array_write_read():
    x = layers.fill_constant(shape=[2, 3], dtype="float32", value=7.0)
    i = layers.fill_constant(shape=[1], dtype="int64", value=0)
    arr = layers.array_write(x, i)
    n = layers.array_length(arr)
    y = layers.array_read(arr, i)
    outs = _run({}, [y, n], startup=False)
    np.testing.assert_allclose(np.asarray(outs[0]), np.full((2, 3), 7.0))
    assert int(np.ravel(outs[1])[0]) == 1


def test_static_rnn_matches_numpy():
    T, N, F, H = 4, 3, 5, 5
    x = layers.data("x", [T, N, F], append_batch_size=False, dtype="float32")
    rnn = layers.StaticRNN()
    with rnn.step():
        word = rnn.step_input(x)
        prev = rnn.memory(shape=[-1, H], batch_ref=word, value=0.0)
        hidden = layers.elementwise_add(word, prev)
        rnn.update_memory(prev, hidden)
        rnn.step_output(hidden)
    out = rnn()
    xv = np.random.RandomState(0).randn(T, N, F).astype("float32")
    (got,) = _run({"x": xv}, [out], startup=False)
    want = np.cumsum(xv, axis=0)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_dynamic_rnn_trains():
    # cumulative-sum RNN over variable-length sequences; check loss + grads
    H = 8
    sent = layers.data("sent", [6], dtype="float32", lod_level=1)
    drnn = layers.DynamicRNN()
    with drnn.block():
        word = drnn.step_input(sent)
        prev = drnn.memory(shape=[H], value=0.0)
        hidden = layers.fc(input=[word, prev], size=H, act="tanh")
        drnn.update_memory(prev, hidden)
        drnn.output(hidden)
    last = layers.sequence_last_step(drnn())
    loss = layers.mean(last)
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

    rng = np.random.RandomState(1)
    feed_val = create_lod_tensor(
        [rng.randn(3, 6).astype("float32"), rng.randn(5, 6).astype("float32")]
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = []
    for _ in range(5):
        (lv,) = exe.run(feed={"sent": feed_val}, fetch_list=[loss])
        losses.append(float(np.ravel(np.asarray(lv))[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # SGD on mean(last) drives it down


def test_dynamic_rnn_respects_lengths():
    # identity RNN: output last step must be the true last element per row
    sent = layers.data("sent", [2], dtype="float32", lod_level=1)
    drnn = layers.DynamicRNN()
    with drnn.block():
        word = drnn.step_input(sent)
        drnn.output(word)
    last = layers.sequence_last_step(drnn())
    s0 = np.array([[1, 1], [2, 2]], dtype="float32")
    s1 = np.array([[3, 3], [4, 4], [5, 5], [6, 6]], dtype="float32")
    feed_val = create_lod_tensor([s0, s1])
    (got,) = _run({"sent": feed_val}, [last], startup=False)
    np.testing.assert_allclose(
        np.asarray(got), np.array([[2, 2], [6, 6]], dtype="float32")
    )


def test_ifelse_rowwise():
    x = layers.data("x", [1], dtype="float32")
    zero = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    cond = layers.greater_than(x, zero)
    ie = layers.IfElse(cond)
    with ie.true_block():
        d = ie.input(x)
        ie.output(layers.scale(d, scale=2.0))
    with ie.false_block():
        d = ie.input(x)
        ie.output(layers.scale(d, scale=-1.0))
    (out,) = ie()
    xv = np.array([[1.0], [-2.0], [3.0], [-4.0]], dtype="float32")
    (got,) = _run({"x": xv}, [out], startup=False)
    want = np.where(xv > 0, 2 * xv, -xv)
    np.testing.assert_allclose(np.asarray(got), want)


def test_switch_piecewise():
    # Switch picks the first true case (reference: test_switch.py)
    step = layers.data("step", [1], append_batch_size=False, dtype="float32")
    lr = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    b1 = layers.fill_constant(shape=[1], dtype="float32", value=10.0)
    b2 = layers.fill_constant(shape=[1], dtype="float32", value=20.0)
    with layers.Switch() as switch:
        with switch.case(layers.less_than(step, b1)):
            layers.assign(
                layers.fill_constant(shape=[1], dtype="float32", value=0.1), lr
            )
        with switch.case(layers.less_than(step, b2)):
            layers.assign(
                layers.fill_constant(shape=[1], dtype="float32", value=0.01), lr
            )
        with switch.default():
            layers.assign(
                layers.fill_constant(shape=[1], dtype="float32", value=0.001), lr
            )
    for sv, want in [(5.0, 0.1), (15.0, 0.01), (25.0, 0.001)]:
        (got,) = _run(
            {"step": np.array([sv], dtype="float32")}, [lr], startup=False
        )
        assert float(np.ravel(got)[0]) == pytest.approx(want)


def test_while_grad_through_array():
    # grads must flow through while + arrays into a parameter
    x = layers.data("x", [4], dtype="float32")
    proj = layers.fc(input=x, size=4, bias_attr=False)
    i = layers.fill_constant(shape=[1], dtype="int64", value=0)
    n = layers.fill_constant(shape=[1], dtype="int64", value=3)
    arr = layers.array_write(proj, i)
    cond = layers.less_than(x=i, y=n)
    w = layers.While(cond=cond)
    with w.block():
        prev = layers.array_read(arr, i)
        nxt = layers.scale(prev, scale=0.5)
        layers.increment(x=i, value=1, in_place=True)
        layers.array_write(nxt, i, array=arr)
        layers.less_than(x=i, y=n, cond=cond)
    final = layers.array_read(arr, n)
    # hack: read at index 3 == last write
    loss = layers.mean(final)
    fluid.optimizer.SGDOptimizer(learning_rate=0.5).minimize(loss)
    xv = np.ones((2, 4), dtype="float32")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    l0 = float(np.ravel(np.asarray(exe.run(feed={"x": xv}, fetch_list=[loss])[0]))[0])
    l1 = float(np.ravel(np.asarray(exe.run(feed={"x": xv}, fetch_list=[loss])[0]))[0])
    assert l1 != l0  # parameter moved => grad reached the fc weight
