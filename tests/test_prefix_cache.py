"""Prefix-cache subsystem (ISSUE 11): refcounted copy-on-write KV
pages + chunked prefill scheduling.

Acceptance pinned here:
(a) 8 sequences sharing a ~90%-length prefix: the pool allocates ONE
    page-set for the shared region (~1/8 of the unshared run's), the
    prefill model-steps charge only the unshared tails, and every
    generated sequence is token-identical to the ``full_decode`` oracle
    on BOTH prefill arms and BOTH paged impls (reference + interpret),
    with zero leaked pages after the cache releases its holds;
(b) a shared partially-filled tail page copy-on-writes on the first
    divergent append — the cached content stays frozen while the
    writer gets a private copy;
(c) refcount invariants (satellite): a refcounted shared page is NOT
    "double-owned" corruption, a forged share without a refcount IS,
    and orphan repair is refcount-correct (shared pages never freed);
(d) LRU eviction under pool pressure keeps admission alive with a
    cache bigger than the pool's spare capacity;
(e) chunked prefill: no engine step processes more prefill tokens than
    FLAGS_serving_prefill_chunk (counter-asserted) and decode steps
    interleave between a long prompt's chunks (a short sequence
    finishes generating BEFORE the long prompt's first token);
(f) serve_bench --prefix-share banks prefix_hit_rate /
    cached_prefill_tokens / TTFT through the 0/2/3 gate contract.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.serving import (
    ContinuousBatchingLoop,
    DecodeConfig,
    DecodeRequest,
    KVCachePool,
    PrefixCache,
    full_decode,
    full_forward,
    init_decode_params,
)
from paddle_tpu.serving.generate import chunk_prefill_step


def _cfg(**kw):
    base = dict(vocab_size=61, d_model=16, n_head=2, n_layer=2,
                d_inner=32, max_length=64)
    base.update(kw)
    return DecodeConfig(**base)


def _pool(cfg, num_pages=64, page_size=4):
    return KVCachePool(num_pages=num_pages, page_size=page_size,
                       num_layers=cfg.n_layer, num_heads=cfg.n_head,
                       head_dim=cfg.head_dim)


# -- (a) the headline acceptance: 8-way shared prefix -------------------

@pytest.mark.parametrize("prefill", ["batched", "token"])
@pytest.mark.parametrize("impl", ["reference", "interpret"])
def test_eight_way_shared_prefix_acceptance(prefill, impl):
    cfg = _cfg()
    params = init_decode_params(cfg, seed=5)
    rng = np.random.RandomState(5)
    ps, max_new = 4, 4
    # 18 shared tokens of a 20-token prompt: 90% shared
    shared = rng.randint(1, cfg.vocab_size, size=18).tolist()
    prompts = [shared + rng.randint(1, cfg.vocab_size, size=2).tolist()
               for _ in range(8)]
    oracles = [full_decode(params, cfg, p, max_new)[0] for p in prompts]

    def run(with_cache):
        pool = _pool(cfg, num_pages=96, page_size=ps)
        cache = PrefixCache(pool) if with_cache else None
        # max_batch=1: admissions are strictly staggered, so every
        # sequence after the first sees a warm cache
        loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                      prefill=prefill, paged_impl=impl,
                                      prefix_cache=cache)
        results = loop.run([DecodeRequest(list(p), max_new)
                            for p in prompts])
        return pool, cache, loop, results

    pool_nc, _, loop_nc, res_nc = run(False)
    pool_c, cache, loop_c, res_c = run(True)

    # token-identical to the full-recompute oracle on both arms/impls
    for res in (res_nc, res_c):
        for r, want in zip(res, oracles):
            assert r.error is None
            assert r.tokens == want

    # the shared region costs ONE page-set: 7 of the 8 sequences
    # attach the 4 shared full pages instead of allocating them
    shared_full_pages = (18 // ps)  # 4
    assert loop_c.prefix_hits == 7 and loop_c.prefix_misses == 1
    assert loop_c.cached_prefill_tokens == 7 * shared_full_pages * ps
    saved = pool_nc.stats()["page_allocs"] - pool_c.stats()["page_allocs"]
    # each hit saved its shared pages, minus at most one CoW copy each
    assert saved >= 7 * (shared_full_pages - 1)
    # prefill model-steps charge only the unshared tails
    total_prompt = sum(len(p) for p in prompts)
    assert loop_nc.prefill_tokens == total_prompt
    assert loop_c.prefill_tokens == \
        total_prompt - loop_c.cached_prefill_tokens

    # zero leaked pages: the cache's holds are the ONLY pages left,
    # and releasing them returns the pool to fully free
    assert pool_c.check_invariants()["ok"]
    assert pool_nc.used_pages == 0
    cache.clear()
    assert pool_c.used_pages == 0
    assert pool_c.check_invariants()["ok"]


# -- (b) copy-on-write of the shared partial tail -----------------------

def test_partial_tail_cow_preserves_cached_content():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=11)
    rng = np.random.RandomState(11)
    # 18 tokens at page_size 8: 2 full pages + a 2-token partial tail
    shared = rng.randint(1, cfg.vocab_size, size=18).tolist()
    pA = list(shared)                                     # insert arm
    pB = shared + rng.randint(1, cfg.vocab_size, size=5).tolist()
    pC = shared + rng.randint(1, cfg.vocab_size, size=2).tolist()
    pool = _pool(cfg, num_pages=40, page_size=8)
    cache = PrefixCache(pool)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  prefix_cache=cache)
    res = loop.run([DecodeRequest(p, 4) for p in (pA, pB, pC)])
    for p, r in zip((pA, pB, pC), res):
        want, _ = full_decode(params, cfg, p, 4)
        assert r.error is None and r.tokens == want
    # B and C matched INTO the partial page (18 tokens, mid-page) and
    # their first divergent append copy-on-wrote it — plus A itself
    # CoW'd its pinned tail when decoding past the prompt
    assert loop.cached_prefill_tokens == 2 * 18
    assert pool.stats()["cow_copies"] >= 3
    assert pool.check_invariants()["ok"]
    cache.clear()
    assert pool.used_pages == 0


def test_cow_accounting_is_atomic_under_exhaustion():
    """A claim whose CoW page cannot be satisfied must raise BEFORE any
    table mutates (the append_tokens atomicity contract extends to the
    copy-on-write page)."""
    from paddle_tpu.serving import PagePoolExhausted

    pool = KVCachePool(num_pages=2, page_size=4, num_layers=1,
                       num_heads=1, head_dim=4)
    pool.allocate(0)
    pool.append_tokens([0], [2])  # page 0: 2 of 4 slots used
    pool.allocate(1)
    pool.append_tokens([1], [4])  # page 1: full — pool exhausted
    # share 0's partial tail with a cache-style hold (registered as an
    # external owner so the audit can explain the refcount)
    held = pool.table_snapshot(0)[0][0]
    pool.retain_pages([held])
    pool.register_owner(lambda: {held: 1})
    with pytest.raises(PagePoolExhausted):
        pool.append_token([0])  # CoW needs a page; none free
    assert pool.length(0) == 2  # nothing advanced
    assert pool.check_invariants()["ok"]


# -- (c) refcount invariants (satellite) --------------------------------

def test_refcounted_share_is_not_double_owned():
    pool = KVCachePool(num_pages=8, page_size=2, num_layers=1,
                       num_heads=1, head_dim=4)
    pool.allocate(0)
    pool.append_tokens([0], [4])  # 2 full pages
    pages, _ = pool.table_snapshot(0)
    # a legitimate refcounted share: attach both pages to sequence 1
    pool.allocate(1)
    pool.attach_prefix(1, pages, 3)
    rep = pool.check_invariants()
    assert rep["ok"], rep
    assert rep["shared_pages"] == 2
    assert rep["double_owned_pages"] == []
    # retiring one owner keeps the pages live for the other
    assert pool.free_seq(0) == 0
    assert pool.free_seq(1) == 2
    assert pool.free_pages == pool.num_pages


def test_forged_share_without_refcount_still_flagged():
    pool = KVCachePool(num_pages=8, page_size=2, num_layers=1,
                       num_heads=1, head_dim=4)
    pool.allocate(0)
    pool.allocate(1)
    pool.append_token([0])
    pool.append_token([1])
    shared = pool._tables[0].pages[0]
    pool._tables[1].pages.append(shared)  # corruption: no refcount
    rep = pool.check_invariants()
    assert not rep["ok"]
    assert shared in rep["double_owned_pages"]
    assert shared in rep["refcount_mismatches"]


def test_orphan_repair_is_refcount_correct():
    pool = KVCachePool(num_pages=8, page_size=2, num_layers=1,
                       num_heads=1, head_dim=4)
    pool.allocate(0)
    pool.append_tokens([0], [4])
    pages, _ = pool.table_snapshot(0)
    pool.retain_pages(pages)  # cache-style hold on both pages...
    holds = {p: 1 for p in pages}
    pool.register_owner(lambda: holds)  # ...as a REGISTERED owner
    leaked = pool._free.pop()  # a genuine orphan
    rep = pool.check_invariants()
    assert not rep["ok"] and rep["orphaned_pages"] == [leaked]
    assert pool.reclaim_orphans() == 1  # repairs ONLY the orphan
    rep = pool.check_invariants()
    assert rep["ok"], rep
    # the shared pages kept their holds: freeing the sequence alone
    # does not release them
    assert pool.free_seq(0) == 0
    holds.clear()  # the "cache" lets go
    assert pool.release_pages(pages) == 2
    assert pool.free_pages == pool.num_pages


def test_defrag_remaps_cached_pages_and_refcounts():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=13)
    rng = np.random.RandomState(13)
    shared = rng.randint(1, cfg.vocab_size, size=12).tolist()
    pool = _pool(cfg, num_pages=32, page_size=4)
    cache = PrefixCache(pool)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  prefix_cache=cache)
    # a placeholder sequence pins the LOW page ids first, so the warm
    # run's cached pages land higher; freeing it leaves a hole defrag
    # must close by MOVING the cached pages down
    pool.allocate(1000)
    pool.append_tokens([1000], [8])
    warm = shared + rng.randint(1, cfg.vocab_size, size=2).tolist()
    loop.run([DecodeRequest(warm, 2)])
    assert cache.stats()["entries"] > 0
    pool.free_seq(1000)
    moves = pool.defrag()
    assert moves > 0  # cached pages moved into the hole
    assert pool.check_invariants()["ok"]
    # the cache followed the remap: a hit through the compacted pages
    # still decodes token-identically
    probe = shared + rng.randint(1, cfg.vocab_size, size=3).tolist()
    res = loop.run([DecodeRequest(probe, 3)])
    want, _ = full_decode(params, cfg, probe, 3)
    assert res[0].tokens == want
    assert loop.prefix_hits == 1
    cache.clear()
    assert pool.used_pages == 0


def test_uncharged_live_pages_survives_entry_drop():
    """Admission's set-aside bound comes from the POOL's allocator map,
    not cache entries: a page attached to a live reader stays counted
    after its charging sequence retires — even if every cache entry
    naming it is dropped (capacity cap / quarantine invalidation),
    which would blind an entry-based count and over-commit the pool."""
    pool = KVCachePool(num_pages=8, page_size=2, num_layers=1,
                       num_heads=1, head_dim=4)
    pool.allocate(0)
    pool.append_tokens([0], [4])  # 2 pages, charged by seq 0
    pages, _ = pool.table_snapshot(0)
    pool.allocate(1)
    pool.attach_prefix(1, pages, 3)  # reader, charged only its tail
    assert pool.uncharged_live_pages() == 0  # allocator still live
    assert pool.free_seq(0) == 0  # pages live on under the reader...
    assert pool.uncharged_live_pages() == 2  # ...now uncharged
    assert pool.free_seq(1) == 2
    assert pool.uncharged_live_pages() == 0


# -- (d) LRU eviction under pressure ------------------------------------

def test_lru_eviction_keeps_admission_alive():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=17)
    rng = np.random.RandomState(17)
    # pool far too small to cache every distinct prompt: eviction must
    # shed cold entries so fresh admissions keep fitting
    pool = _pool(cfg, num_pages=8, page_size=8)
    cache = PrefixCache(pool)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  prefix_cache=cache)
    reqs = [DecodeRequest(
        rng.randint(1, cfg.vocab_size, size=20).tolist(), 4)
        for _ in range(5)]
    res = loop.run(reqs)
    for q, r in zip(reqs, res):
        want, _ = full_decode(params, cfg, list(q.prompt), 4)
        assert r.error is None and r.tokens == want
    assert cache.stats()["evictions"] > 0
    assert pool.check_invariants()["ok"]
    cache.clear()
    assert pool.used_pages == 0


def test_max_pages_caps_cache_footprint():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=19)
    rng = np.random.RandomState(19)
    pool = _pool(cfg, num_pages=64, page_size=4)
    cache = PrefixCache(pool, max_pages=4)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  prefix_cache=cache)
    reqs = [DecodeRequest(
        rng.randint(1, cfg.vocab_size, size=14).tolist(), 2)
        for _ in range(4)]
    loop.run(reqs)
    assert cache.stats()["entries"] <= 4
    assert pool.check_invariants()["ok"]


# -- (e) chunked prefill ------------------------------------------------

def test_chunk_prefill_step_matches_full_forward():
    """Splitting a prompt into arbitrary chunks through
    chunk_prefill_step reproduces full_forward's last-row logits and
    the same cached K/V a whole-prompt prefill writes."""
    cfg = _cfg()
    params = init_decode_params(cfg, seed=23)
    rng = np.random.RandomState(23)
    prompt = rng.randint(1, cfg.vocab_size, size=13).tolist()
    pool = _pool(cfg, num_pages=16, page_size=4)
    pool.allocate(0)
    logits = None
    for lo, hi in ((0, 5), (5, 6), (6, 13)):
        logits = chunk_prefill_step(params, cfg, pool, [0],
                                    [prompt[lo:hi]], [lo])
    want = full_forward(params, cfg, prompt)[-1]
    np.testing.assert_allclose(logits[0], want, rtol=1e-4, atol=1e-4)
    assert pool.length(0) == len(prompt)


def test_chunk_cap_counter_asserted_and_decode_interleaves():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=9)
    rng = np.random.RandomState(9)
    p_long = rng.randint(1, cfg.vocab_size, size=40).tolist()
    p_short = rng.randint(1, cfg.vocab_size, size=4).tolist()
    cap = 8

    pool = _pool(cfg, num_pages=48, page_size=8)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=2,
                                  prefill_chunk=cap)
    # the short sequence needs fewer decode steps (3) than the long
    # prompt needs chunk steps (>= 5), so under alternation it must
    # finish generating strictly before the long prompt's first token
    res_short, res_long = loop.run([
        DecodeRequest(p_short, 3), DecodeRequest(p_long, 4)])
    for p, r in zip((p_short, p_long), (res_short, res_long)):
        want, _ = full_decode(params, cfg, p, len(r.tokens))
        assert r.tokens == want
    # no engine step processed more prefill tokens than the cap
    assert 0 < loop.max_prefill_tokens_step <= cap
    # the long prompt took multiple chunk steps...
    assert loop.prefill_steps >= 3
    # ...and decode steps interleaved between them: the short sequence
    # finished ALL its tokens before the long prompt's first token
    assert res_long.ttft_s is not None
    long_first_token_at = res_long.admitted_at + res_long.ttft_s
    assert res_short.finished_at < long_first_token_at
    assert pool.used_pages == 0


def test_chunk_flag_default_and_validation():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=1)
    pool = _pool(cfg)
    fluid.set_flags({"FLAGS_serving_prefill_chunk": 6})
    try:
        loop = ContinuousBatchingLoop(params, cfg, pool)
        assert loop._prefill_chunk == 6
    finally:
        fluid.set_flags({"FLAGS_serving_prefill_chunk": 0})
    with pytest.raises(ValueError, match="prefill_chunk"):
        ContinuousBatchingLoop(params, cfg, pool, prefill_chunk=-1)
    other = _pool(cfg)
    with pytest.raises(ValueError, match="different pool"):
        ContinuousBatchingLoop(params, cfg, pool,
                               prefix_cache=PrefixCache(other))


def test_token_arm_respects_chunk_cap():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=29)
    rng = np.random.RandomState(29)
    prompts = [rng.randint(1, cfg.vocab_size, size=10).tolist()
               for _ in range(3)]
    pool = _pool(cfg, num_pages=64, page_size=4)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3,
                                  prefill="token", prefill_chunk=2)
    res = loop.run([DecodeRequest(p, 3) for p in prompts])
    for p, r in zip(prompts, res):
        want, _ = full_decode(params, cfg, p, 3)
        assert r.tokens == want
    assert 0 < loop.max_prefill_tokens_step <= 2
    assert pool.used_pages == 0


# -- observability ------------------------------------------------------

def test_prefix_metrics_and_flight_events_emitted():
    from paddle_tpu import observability as obs

    obs.reset()
    fluid.set_flags({"FLAGS_observability": True})
    try:
        cfg = _cfg()
        params = init_decode_params(cfg, seed=31)
        rng = np.random.RandomState(31)
        shared = rng.randint(1, cfg.vocab_size, size=12).tolist()
        pool = _pool(cfg, num_pages=48, page_size=4)
        cache = PrefixCache(pool)
        loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                      prefix_cache=cache)
        prompts = [shared + rng.randint(1, cfg.vocab_size,
                                        size=2).tolist()
                   for _ in range(2)]
        loop.run([DecodeRequest(p, 2) for p in prompts])
        snap = obs.default_registry().snapshot()["metrics"]
        by_name = {m["name"]: m for m in snap}
        events = by_name["paddle_tpu_serving_prefix_events"]["series"]
        got = {s["labels"]["event"] for s in events}
        assert {"hit", "miss", "insert"} <= got
        assert "paddle_tpu_serving_prefix_cached_tokens" in by_name
        assert "paddle_tpu_serving_prefix_cache_pages" in by_name
        evs = obs.default_flight().events()
        assert any(e["kind"] == "prefix_hit" for e in evs)
    finally:
        fluid.set_flags({"FLAGS_observability": False})
        obs.reset()


# -- (f) serve_bench wiring ---------------------------------------------

def test_serve_bench_prefix_share_banks_and_gates(tmp_path, capsys):
    import json

    from tools.serve_bench import main as bench_main

    out = tmp_path / "out.json"
    argv = [
        "--mode", "decode", "--sequences", "6", "--max-new", "4",
        "--prefix-share", "0.9", "--prefill-chunk", "8",
        "--max-batch", "2", "--pages", "64", "--page-size", "8",
        "--d-model", "16", "--vocab", "61", "--max-len", "64",
    ]
    rc = bench_main(argv + ["--json", str(out)])
    capsys.readouterr()
    assert rc == 0
    result = json.loads(out.read_text())
    assert result["pages_leaked"] == 0
    assert result["prefix_hit_rate"] > 0
    assert result["cached_prefill_tokens"] > 0
    assert result["max_prefill_tokens_step"] <= 8
    assert result["ttft_p99_ms"] is not None
    # bank this run's capacity numbers + a generous TTFT ceiling and
    # re-gate: the 0/2/3 contract holds them (TTFT tolerance is wide —
    # CI wall clocks are noisy; the HIT-RATE floor is the sharp edge)
    bank = tmp_path / "bank.json"
    bank.write_text(json.dumps({
        "prefix_hit_rate": result["prefix_hit_rate"],
        "cached_prefill_tokens": result["cached_prefill_tokens"],
        "max_prefill_tokens_step": 8,
        "pages_leaked": 0,
        "ttft_p99_ms": result["ttft_p99_ms"] * 50,
    }))
    rc = bench_main(argv + ["--baseline", str(bank), "--gate"])
    capsys.readouterr()
    assert rc == 0
    # an impossible hit-rate baseline fails the gate with exit 3
    bank.write_text(json.dumps({"prefix_hit_rate": 1000.0}))
    rc = bench_main(argv + ["--baseline", str(bank), "--gate"])
    capsys.readouterr()
    assert rc == 3


def test_serve_bench_prefix_usage_errors(capsys):
    from tools.serve_bench import main as bench_main

    assert bench_main(["--prefix-share", "0.5"]) == 2  # needs decode
    assert bench_main(["--mode", "decode",
                       "--prefix-share", "1.5"]) == 2  # out of range
    assert bench_main(["--prefill-chunk", "4"]) == 2   # needs decode
    capsys.readouterr()
