"""FLAGS_fuse_conv_epilogue: the compile-time conv-epilogue fusion pass
(core/fusion.py; reference counterpart ir/conv_bn_fuse_pass +
conv_elementwise_add_act_fuse feeding conv_fusion_op.cu.cc).

Contracts: exact numerical parity with the unfused chain (the rewrite
targets the parity-tested conv_bn_add_act op), byte-identical lowering
when nothing matches, fetch-protection, and grad-window collapse that
preserves accumulation (`@RENAME@`) names."""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.fusion import fuse_conv_epilogue_ops


@pytest.fixture(autouse=True)
def _restore_flags():
    yield
    fluid.set_flags({"FLAGS_fuse_conv_epilogue": False})


def _block_ops():
    return list(fluid.default_main_program().desc.block(0).ops)


def _build_resnet_block(with_residual=True, bias=False, act="relu"):
    x = layers.data("x", [8, 8, 8], dtype="float32")
    yv = layers.data("y", [1], dtype="int64")
    conv = layers.conv2d(x, 8, 3, padding=1,
                         bias_attr=None if bias else False,
                         param_attr=fluid.ParamAttr(name="w"))
    b = layers.batch_norm(conv, act=None,
                          param_attr=fluid.ParamAttr(name="s"),
                          bias_attr=fluid.ParamAttr(name="b"),
                          moving_mean_name="m", moving_variance_name="v")
    h = layers.elementwise_add(b, x) if with_residual else b
    if act:
        h = layers.relu(h)
    pool = layers.pool2d(h, pool_size=8, pool_type="avg")
    pred = layers.fc(pool, size=3, act="softmax",
                     param_attr=fluid.ParamAttr(name="fc"))
    loss = layers.mean(layers.cross_entropy(pred, yv))
    fluid.optimizer.MomentumOptimizer(0.1, 0.9).minimize(loss)
    return loss, h


def _train(fuse, steps=4, **build_kw):
    fluid.reset_default_env()
    fluid.set_flags({"FLAGS_fuse_conv_epilogue": fuse})
    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7
    loss, _ = _build_resnet_block(**build_kw)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    r = np.random.RandomState(5)
    xa = r.randn(4, 8, 8, 8).astype("float32")
    ya = r.randint(0, 3, size=(4, 1)).astype("int64")
    ls = [float(np.ravel(np.asarray(exe.run(
        feed={"x": xa, "y": ya}, fetch_list=[loss])[0]))[0])
        for _ in range(steps)]
    sc = fluid.global_scope()
    st = {n: np.asarray(sc.find_var(n)).copy()
          for n in ("w", "s", "b", "m", "v", "fc")}
    nfused = max(
        getattr(e[1], "fused_conv_epilogue", 0) for e in exe._cache.values())
    return ls, st, nfused


@pytest.mark.parametrize("with_residual", [True, False])
def test_fused_training_matches_unfused(with_residual):
    """fwd + bwd + moving stats + optimizer states: exact parity (the
    rewrite routes through the parity-tested conv_bn_add_act lowering)."""
    l0, s0, n0 = _train(False, with_residual=with_residual)
    l1, s1, n1 = _train(True, with_residual=with_residual)
    assert n0 == 0 and n1 == 1
    assert l0[-1] < l0[0]  # training moved
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-6)
    for n in s0:
        np.testing.assert_allclose(s0[n], s1[n], rtol=1e-5, atol=1e-6,
                                   err_msg=n)


def test_bare_conv_bn_fuses_without_act():
    """conv -> bn with neither residual nor relu still fuses (act='')."""
    l0, s0, _ = _train(False, with_residual=False, act="")
    l1, s1, n1 = _train(True, with_residual=False, act="")
    assert n1 == 1
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-6)
    for n in s0:
        np.testing.assert_allclose(s0[n], s1[n], rtol=1e-5, atol=1e-6,
                                   err_msg=n)


def test_resnet_model_parity_and_full_block_coverage():
    """resnet_cifar10's unfused program: every conv+bn chain (main
    branches AND act-less shortcuts) collapses, and training matches."""
    from paddle_tpu import models

    def run(fuse):
        fluid.reset_default_env()
        fluid.set_flags({"FLAGS_fuse_conv_epilogue": fuse})
        fluid.default_main_program().random_seed = 3
        fluid.default_startup_program().random_seed = 3
        spec = models.resnet_cifar10(depth=8, class_num=4, fuse_bn=False)
        fluid.optimizer.MomentumOptimizer(0.05, 0.9).minimize(spec.loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        b = spec.synthetic_batch(8, seed=2)
        ls = [float(np.ravel(np.asarray(
            exe.run(feed=b, fetch_list=[spec.loss])[0]))[0])
            for _ in range(3)]
        nfused = max(getattr(e[1], "fused_conv_epilogue", 0)
                     for e in exe._cache.values())
        return ls, nfused

    l0, n0 = run(False)
    l1, n1 = run(True)
    blk = fluid.default_main_program().desc.block(0)
    n_convs = sum(1 for op in blk.ops if op.type == "conv2d")
    assert n0 == 0
    assert n1 == n_convs  # reverse-order matching fuses every chain
    assert l0[-1] < l0[0]
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-6)


def test_no_match_is_identity():
    """Programs without the pattern: the pass returns the SAME ops list
    object (so the lowering is byte-identical with the flag on)."""
    fluid.reset_default_env()
    x = layers.data("x", [8, 8, 8], dtype="float32")
    # conv with bias: conv2d -> elementwise_add(bias) breaks the pattern
    conv = layers.conv2d(x, 8, 3, padding=1)
    b = layers.batch_norm(conv, act="relu")
    loss = layers.mean(b)
    fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    blk = fluid.default_main_program().desc.block(0)
    ops = list(blk.ops)
    assert fuse_conv_epilogue_ops(ops, blk.vars, [loss.name]) is ops


def test_no_match_lowers_byte_identically():
    """Flag on + no pattern => the lowered StableHLO is identical."""
    from paddle_tpu.core.compiler import CompiledBlock
    from paddle_tpu.core.executor import _RunPlan

    def lower_text(fuse):
        fluid.reset_default_env()
        fluid.set_flags({"FLAGS_fuse_conv_epilogue": fuse})
        x = layers.data("x", [4], dtype="float32")
        h = layers.fc(x, size=4, act="relu",
                      param_attr=fluid.ParamAttr(name="fw"))
        loss = layers.mean(h)
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        prog = fluid.default_main_program()
        plan = _RunPlan(prog, ["x"], [loss.name])
        cb = CompiledBlock(prog, 0, plan.feed_names, plan.fetch_names,
                           plan.state_names, donate_states=False)
        blk = prog.desc.block(0)
        sv = plan.state_values(fluid.global_scope(), blk)
        xa = np.zeros((2, 4), "float32")
        txt = jax.jit(cb.raw_fn).lower(
            (xa,), sv, jax.random.PRNGKey(0)).as_text()
        fluid.set_flags({"FLAGS_fuse_conv_epilogue": False})
        return txt

    assert lower_text(False) == lower_text(True)


def test_fetched_intermediate_blocks_fusion():
    """A chain whose bn output is fetched must NOT be rewritten."""
    fluid.reset_default_env()
    fluid.set_flags({"FLAGS_fuse_conv_epilogue": True})
    fluid.default_startup_program().random_seed = 1
    x = layers.data("x", [8, 8, 8], dtype="float32")
    conv = layers.conv2d(x, 8, 3, padding=1, bias_attr=False)
    b = layers.batch_norm(conv, act=None)
    h = layers.relu(layers.elementwise_add(b, x))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xa = np.random.RandomState(0).randn(2, 8, 8, 8).astype("float32")
    bn_v, h_v = exe.run(feed={"x": xa}, fetch_list=[b, h])
    nfused = max(getattr(e[1], "fused_conv_epilogue", 0)
                 for e in exe._cache.values())
    assert nfused == 0  # bn output fetched -> chain protected
    assert np.asarray(bn_v).shape == np.asarray(h_v).shape


def test_test_mode_clone_not_fused():
    """clone(for_test=True) sets is_test on batch_norm: the pass must
    leave inference programs to the transpiler fold."""
    fluid.reset_default_env()
    x = layers.data("x", [8, 8, 8], dtype="float32")
    conv = layers.conv2d(x, 8, 3, padding=1, bias_attr=False)
    b = layers.batch_norm(conv, act="relu")
    test_prog = fluid.default_main_program().clone(for_test=True)
    blk = test_prog.desc.block(0)
    ops = list(blk.ops)
    assert fuse_conv_epilogue_ops(ops, blk.vars, []) is ops


def test_pass_preserves_grad_accumulation_names():
    """x feeds the conv AND the residual add: the fused grad op must
    scatter to the exact (possibly @RENAME@) names the original grad
    window produced, so downstream sum ops still see both parts."""
    fluid.reset_default_env()
    x = layers.data("x", [8, 8, 8], dtype="float32")
    conv = layers.conv2d(x, 8, 3, padding=1, bias_attr=False)
    b = layers.batch_norm(conv, act=None)
    h = layers.relu(layers.elementwise_add(b, x))
    # second consumer of the chain output
    loss = layers.mean(h) + layers.mean(h * h)
    fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    blk = fluid.default_main_program().desc.block(0)
    ops = list(blk.ops)
    fused = fuse_conv_epilogue_ops(ops, blk.vars, [loss.name])
    assert fused is not ops
    fwd = [o for o in fused if o.type == "conv_bn_add_act"]
    grad = [o for o in fused if o.type == "conv_bn_add_act_grad"]
    assert len(fwd) == 1 and len(grad) == 1
    produced = {n for o in fused for n in o.output_arg_names() if n}
    consumed = {n for o in fused for n in o.input_arg_names() if n}
    dangling = {n for n in consumed - produced
                if "@GRAD" in n and not blk.vars.get(n, None)}
    assert not dangling, dangling
